from .field_type import (
    TypeKind,
    FieldType,
    bigint_type,
    double_type,
    decimal_type,
    date_type,
    datetime_type,
    varchar_type,
    boolean_type,
)
from .value import (
    Decimal,
    Date,
    DateTime,
    encode_date,
    decode_date,
    encode_datetime,
    decode_datetime,
)

__all__ = [
    "TypeKind",
    "FieldType",
    "bigint_type",
    "double_type",
    "decimal_type",
    "date_type",
    "datetime_type",
    "varchar_type",
    "boolean_type",
    "Decimal",
    "Date",
    "DateTime",
    "encode_date",
    "decode_date",
    "encode_datetime",
    "decode_datetime",
]
