"""SQL field types with TPU-friendly physical encodings.

Plays the role of the reference's type metadata (reference:
types/field_type.go, parser `types.FieldType`), redesigned for a columnar
device layout instead of the row-based `Datum` interpreter:

  logical SQL type          physical device encoding
  ------------------------  ----------------------------------------------
  TINYINT..BIGINT           int64
  BOOLEAN                   int64 (0/1; MySQL booleans are tinyint)
  FLOAT/DOUBLE              float64 host / float32 on device when needed
  DECIMAL(M, D)             int64 scaled by 10**D (exact fixed-point;
                            reference types/mydecimal.go is an arbitrary-
                            precision engine — we keep MySQL semantics for
                            M<=18 which covers TPC-H/SSB, and overflow-check
                            on the host for the long tail)
  DATE                      int32 days since 1970-01-01
  DATETIME/TIMESTAMP        int64 microseconds since epoch
  CHAR/VARCHAR/TEXT         int32 dictionary code (append-ordered, NOT
                            order-preserving; ordering/range predicates go
                            through Dictionary.sort_ranks / code_table)

Static dtypes keep every column XLA-tileable; NULLs live in a separate
validity bitmap (see tidb_tpu/chunk).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class TypeKind(enum.IntEnum):
    NULL = 0
    TINYINT = 1
    SMALLINT = 2
    INT = 3
    BIGINT = 4
    FLOAT = 5
    DOUBLE = 6
    DECIMAL = 7
    DATE = 8
    DATETIME = 9
    TIMESTAMP = 10
    CHAR = 11
    VARCHAR = 12
    TEXT = 13
    BOOLEAN = 14
    YEAR = 15
    TIME = 16  # MySQL TIME (duration); int64 microseconds
    ENUM = 17  # dictionary code over a fixed, definition-ordered elem set
    SET = 18   # int64 bitmask over up to 64 elems
    BIT = 19   # int64 (BIT(n), n <= 64)
    JSON = 20  # dictionary-coded normalized JSON text


INT_KINDS = frozenset(
    {TypeKind.TINYINT, TypeKind.SMALLINT, TypeKind.INT, TypeKind.BIGINT,
     TypeKind.BOOLEAN, TypeKind.YEAR, TypeKind.BIT}
)
FLOAT_KINDS = frozenset({TypeKind.FLOAT, TypeKind.DOUBLE})
# ENUM and JSON ride the dictionary-string machinery: predicates, joins,
# grouping and rendering all go through codes (reference: types/json
# binary docs + enum/set in types/etc.go — re-based on the columnar
# dictionary layout instead of row bytes)
STRING_KINDS = frozenset({TypeKind.CHAR, TypeKind.VARCHAR, TypeKind.TEXT,
                          TypeKind.ENUM, TypeKind.JSON})
TIME_KINDS = frozenset({TypeKind.DATE, TypeKind.DATETIME, TypeKind.TIMESTAMP})

# collations with case-insensitive equality (reference:
# util/collate/collate.go:62 — the general_ci/unicode_ci family)
_CI_SUFFIXES = ("_ci", "_ai_ci")


@dataclass(frozen=True)
class FieldType:
    kind: TypeKind
    # DECIMAL precision/scale; flen doubles as CHAR/VARCHAR length and
    # BIT width.
    flen: int = -1
    scale: int = 0
    nullable: bool = True
    # ENUM/SET element labels in definition order
    elems: tuple = ()
    # '' = binary collation (code-space compares); *_ci = case-insensitive
    collate: str = ""

    @property
    def is_ci(self) -> bool:
        return self.collate.endswith(_CI_SUFFIXES)

    # ---- classification ----------------------------------------------------
    @property
    def is_integer(self) -> bool:
        return self.kind in INT_KINDS

    @property
    def is_float(self) -> bool:
        return self.kind in FLOAT_KINDS

    @property
    def is_decimal(self) -> bool:
        return self.kind == TypeKind.DECIMAL

    @property
    def is_string(self) -> bool:
        return self.kind in STRING_KINDS

    @property
    def is_temporal(self) -> bool:
        return self.kind in TIME_KINDS or self.kind == TypeKind.TIME

    # ---- physical layout ---------------------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        """Host-side storage dtype for a column of this type."""
        if self.is_integer or self.is_decimal:
            return np.dtype(np.int64)
        if self.is_float:
            return np.dtype(np.float64)
        if self.kind == TypeKind.DATE:
            return np.dtype(np.int32)
        if self.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP, TypeKind.TIME):
            return np.dtype(np.int64)
        if self.is_string:
            return np.dtype(np.int32)  # dictionary code
        if self.kind == TypeKind.SET:
            return np.dtype(np.int64)  # element bitmask
        if self.kind == TypeKind.NULL:
            return np.dtype(np.int64)
        raise TypeError(f"no physical dtype for {self.kind!r}")

    @property
    def decimal_multiplier(self) -> int:
        assert self.is_decimal
        return 10 ** self.scale

    def __repr__(self) -> str:  # compact, for plan explain output
        name = self.kind.name.lower()
        if self.is_decimal:
            return f"{name}({self.flen},{self.scale})"
        if self.kind in (TypeKind.ENUM, TypeKind.SET):
            return f"{name}({','.join(repr(e) for e in self.elems)})"
        if self.kind == TypeKind.BIT and self.flen >= 0:
            return f"{name}({self.flen})"
        if self.is_string and self.flen >= 0:
            return f"{name}({self.flen})"
        return name


# ---- constructors ----------------------------------------------------------

def bigint_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.BIGINT, nullable=nullable)


def double_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DOUBLE, nullable=nullable)


def decimal_type(flen: int = 15, scale: int = 2, nullable: bool = True) -> FieldType:
    if flen > 18:
        # int64 holds 18 full decimal digits; MySQL supports 65. The wide
        # tail is rejected loudly rather than silently corrupted.
        raise ValueError(f"DECIMAL precision {flen} > 18 not supported yet")
    return FieldType(TypeKind.DECIMAL, flen=flen, scale=scale, nullable=nullable)


def date_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DATE, nullable=nullable)


def datetime_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.DATETIME, nullable=nullable)


def varchar_type(flen: int = -1, nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.VARCHAR, flen=flen, nullable=nullable)


def char_type(flen: int = 1, nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.CHAR, flen=flen, nullable=nullable)


def boolean_type(nullable: bool = True) -> FieldType:
    return FieldType(TypeKind.BOOLEAN, nullable=nullable)
