"""Host-side scalar values and temporal codecs.

The reference carries row values as dynamic `types.Datum` (reference:
types/datum.go) with a 2.4k-line arbitrary-precision decimal engine
(types/mydecimal.go). On TPU the data plane is columnar and typed, so the
host only needs thin exact scalars for: literals in the parser/planner,
final-stage arithmetic (e.g. AVG = SUM/COUNT with MySQL scale rules), and
result rendering.

Decimal here is an exact scaled integer over Python's bignum ints, so host
math never overflows; only the *device* columns are bounded to int64
(checked at ingest).
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass

_EPOCH = _dt.date(1970, 1, 1)


@dataclass(frozen=True)
class Decimal:
    """Exact fixed-point decimal: value = unscaled / 10**scale."""

    unscaled: int
    scale: int

    # ---- construction ------------------------------------------------------
    @staticmethod
    def parse(text: str) -> "Decimal":
        text = text.strip()
        neg = text.startswith("-")
        if text and text[0] in "+-":
            text = text[1:]
        exp = 0
        for e in ("e", "E"):
            if e in text:
                text, exp_s = text.split(e, 1)
                exp = int(exp_s)
                break
        if "." in text:
            intpart, frac = text.split(".", 1)
        else:
            intpart, frac = text, ""
        intpart = intpart or "0"
        unscaled = int(intpart + frac) if (intpart + frac) else 0
        if neg:
            unscaled = -unscaled
        scale = len(frac) - exp
        if scale < 0:
            unscaled *= 10 ** (-scale)
            scale = 0
        return Decimal(unscaled, scale)

    @staticmethod
    def from_int(v: int, scale: int = 0) -> "Decimal":
        return Decimal(v * 10 ** scale, scale)

    # ---- scale management --------------------------------------------------
    def rescale(self, scale: int) -> "Decimal":
        """Exact when widening; MySQL half-away-from-zero rounding when narrowing
        (reference: types/mydecimal.go Round, ModeHalfEven name notwithstanding
        MySQL rounds half away from zero)."""
        if scale == self.scale:
            return self
        if scale > self.scale:
            return Decimal(self.unscaled * 10 ** (scale - self.scale), scale)
        div = 10 ** (self.scale - scale)
        q, r = divmod(abs(self.unscaled), div)
        if 2 * r >= div:
            q += 1
        return Decimal(-q if self.unscaled < 0 else q, scale)

    # ---- arithmetic (MySQL result-scale rules) -----------------------------
    def __add__(self, other: "Decimal") -> "Decimal":
        s = max(self.scale, other.scale)
        return Decimal(self.rescale(s).unscaled + other.rescale(s).unscaled, s)

    def __sub__(self, other: "Decimal") -> "Decimal":
        s = max(self.scale, other.scale)
        return Decimal(self.rescale(s).unscaled - other.rescale(s).unscaled, s)

    def __mul__(self, other: "Decimal") -> "Decimal":
        return Decimal(self.unscaled * other.unscaled, self.scale + other.scale)

    def div(self, other: "Decimal", incr_scale: int = 4) -> "Decimal":
        """MySQL division: result scale = dividend scale + div_precincrement
        (default 4; reference: expression/builtin_arithmetic.go DIV scale)."""
        if other.unscaled == 0:
            raise ZeroDivisionError("decimal division by zero")
        target = self.scale + incr_scale
        # compute the quotient at the target scale directly and round once on
        # the true remainder (half away from zero)
        num = self.unscaled * 10 ** (target - self.scale)
        q, r = divmod(abs(num), abs(other.unscaled))
        if 2 * r >= abs(other.unscaled):
            q += 1
        if (self.unscaled < 0) != (other.unscaled < 0):
            q = -q
        return Decimal(q, target)

    def __neg__(self) -> "Decimal":
        return Decimal(-self.unscaled, self.scale)

    # ---- comparison --------------------------------------------------------
    def _cmp(self, other: "Decimal") -> int:
        s = max(self.scale, other.scale)
        a, b = self.rescale(s).unscaled, other.rescale(s).unscaled
        return (a > b) - (a < b)

    def __lt__(self, o):  # type: ignore[no-untyped-def]
        return self._cmp(o) < 0

    def __le__(self, o):  # type: ignore[no-untyped-def]
        return self._cmp(o) <= 0

    def __gt__(self, o):  # type: ignore[no-untyped-def]
        return self._cmp(o) > 0

    def __ge__(self, o):  # type: ignore[no-untyped-def]
        return self._cmp(o) >= 0

    def __eq__(self, o: object) -> bool:
        return isinstance(o, Decimal) and self._cmp(o) == 0

    def __hash__(self) -> int:
        return hash(self.normalize())

    def normalize(self) -> tuple[int, int]:
        u, s = self.unscaled, self.scale
        while s > 0 and u % 10 == 0:
            u //= 10
            s -= 1
        return (u, s)

    # ---- conversion --------------------------------------------------------
    def to_float(self) -> float:
        return self.unscaled / 10 ** self.scale

    def __str__(self) -> str:
        if self.scale == 0:
            return str(self.unscaled)
        sign = "-" if self.unscaled < 0 else ""
        digits = str(abs(self.unscaled)).rjust(self.scale + 1, "0")
        return f"{sign}{digits[:-self.scale]}.{digits[-self.scale:]}"

    def __repr__(self) -> str:
        return f"Decimal({self})"


# ---- temporal encodings -----------------------------------------------------
# DATE      -> int32 days since 1970-01-01
# DATETIME  -> int64 microseconds since 1970-01-01T00:00:00

Date = _dt.date
DateTime = _dt.datetime


def encode_date(d: _dt.date) -> int:
    return (d - _EPOCH).days


def decode_date(days: int) -> _dt.date:
    return _EPOCH + _dt.timedelta(days=int(days))


def parse_date(text: str) -> int:
    y, m, d = text.strip().split("-")
    return encode_date(_dt.date(int(y), int(m), int(d)))


def encode_datetime(dt: _dt.datetime) -> int:
    delta = dt - _dt.datetime(1970, 1, 1)
    return (delta.days * 86_400 + delta.seconds) * 1_000_000 + delta.microseconds


def decode_datetime(micros: int) -> _dt.datetime:
    return _dt.datetime(1970, 1, 1) + _dt.timedelta(microseconds=int(micros))


def parse_datetime(text: str) -> int:
    text = text.strip()
    if " " in text:
        datepart, timepart = text.split(" ", 1)
    else:
        datepart, timepart = text, "00:00:00"
    y, m, d = (int(x) for x in datepart.split("-"))
    hms = timepart.split(":")
    h, mi = int(hms[0]), int(hms[1])
    sec = float(hms[2]) if len(hms) > 2 else 0.0
    s = int(sec)
    us = round((sec - s) * 1e6)
    return encode_datetime(_dt.datetime(y, m, d, h, mi, s, us))
