"""Logical plan operators.

Counterpart of reference planner/core logical ops (LogicalDataSource,
LogicalSelection, LogicalProjection, LogicalAggregation, LogicalJoin,
LogicalSort, LogicalLimit — planner/core/logical_plans.go). The rule
pipeline here keeps the reference's order for the rules we implement
(reference planner/core/optimizer.go:59-74): column pruning and predicate
pushdown happen during build; agg/topn pushdown happens at physical time
when choosing the cop/root split.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..catalog.schema import TableInfo
from .expr import AggDesc, PlanExpr
from .schema import PlanSchema


class LogicalPlan:
    schema: PlanSchema
    children: list["LogicalPlan"]


@dataclass
class LogicalScan(LogicalPlan):
    table: TableInfo
    alias: str
    schema: PlanSchema
    children: list[LogicalPlan] = field(default_factory=list)
    # filled by column pruning: offsets of table columns actually needed
    used_offsets: Optional[list[int]] = None


@dataclass
class LogicalSelection(LogicalPlan):
    conditions: list[PlanExpr]
    schema: PlanSchema
    children: list[LogicalPlan] = field(default_factory=list)


@dataclass
class LogicalProjection(LogicalPlan):
    exprs: list[PlanExpr]
    schema: PlanSchema
    children: list[LogicalPlan] = field(default_factory=list)


@dataclass
class LogicalAggregation(LogicalPlan):
    group_by: list[PlanExpr]
    aggs: list[AggDesc]
    schema: PlanSchema  # group cols then agg results
    children: list[LogicalPlan] = field(default_factory=list)


@dataclass
class LogicalJoin(LogicalPlan):
    kind: str  # 'INNER' | 'LEFT' | 'RIGHT' | 'CROSS'
    # equi-join conditions as (left_idx, right_idx) over child schemas
    eq_conditions: list[tuple[int, int]]
    # residual conditions over the concatenated (left ++ right) schema
    other_conditions: list[PlanExpr]
    schema: PlanSchema
    children: list[LogicalPlan] = field(default_factory=list)


@dataclass
class LogicalSort(LogicalPlan):
    items: list[tuple[PlanExpr, bool]]  # (expr, desc)
    schema: PlanSchema
    children: list[LogicalPlan] = field(default_factory=list)


@dataclass
class LogicalLimit(LogicalPlan):
    limit: int
    offset: int
    schema: PlanSchema
    children: list[LogicalPlan] = field(default_factory=list)


@dataclass
class LogicalUnion(LogicalPlan):
    """UNION ALL: bag concatenation of same-width children (reference:
    planner/core LogicalUnionAll; DISTINCT lowers to an aggregation above,
    exactly like buildDistinct)."""

    schema: PlanSchema
    children: list[LogicalPlan] = field(default_factory=list)


@dataclass
class WindowItem:
    """One window computation (reference: planner/core LogicalWindow
    WindowFuncDesc). frame=None means the default frame: with order,
    running (peers share values — RANGE UNBOUNDED PRECEDING..CURRENT
    ROW); without, the whole partition. An explicit frame is the AST
    WindowFrame (ROWS/RANGE bounds)."""

    func: str  # upper-case window/agg function name
    args: list[PlanExpr]
    partition: list[PlanExpr]
    order: list[tuple[PlanExpr, bool]]
    ftype: object
    frame: object = None  # ast.WindowFrame | None


@dataclass
class LogicalWindow(LogicalPlan):
    """Appends one output column per window item to the child schema
    (reference: planner/core/logical_plans.go LogicalWindow;
    executor/window.go)."""

    items: list[WindowItem]
    schema: PlanSchema
    children: list[LogicalPlan] = field(default_factory=list)
