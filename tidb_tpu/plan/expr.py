"""Resolved expression IR with MySQL type inference.

Counterpart of the reference's `expression.Expression` tree
(reference: expression/expression.go — Column/Constant/ScalarFunction) but
columnar-only: every node evaluates to a whole column vector. Constants hold
*physical* encodings (decimal -> scaled int, date -> day number, string ->
resolved per-use), so the device compiler never sees host objects.

Operator names are lowercase snake tags; the pushdown allowlist in
copr/kernels is keyed on them (the canFuncBePushed analog,
reference expression/expression.go:921).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..types.field_type import FieldType, TypeKind, boolean_type
from ..types.value import Decimal


class ExprError(Exception):
    pass


class PlanExpr:
    ftype: FieldType


@dataclass
class Col(PlanExpr):
    idx: int  # offset into the child plan's output schema
    ftype: FieldType
    name: str = ""  # for explain output

    def __repr__(self) -> str:
        return self.name or f"col#{self.idx}"


@dataclass
class Const(PlanExpr):
    value: Any  # physical encoding; None = NULL
    ftype: FieldType

    def __repr__(self) -> str:
        if self.ftype.is_decimal and self.value is not None:
            return str(Decimal(self.value, self.ftype.scale))
        return repr(self.value)


@dataclass
class Call(PlanExpr):
    """Scalar function call. op tags:

    arithmetic: add sub mul div intdiv mod neg
    comparison: eq ne lt le gt ge
    logic:      and or not
    null:       isnull ifnull coalesce
    membership: in_values (args[0] vs consts), like
    control:    case (when1, then1, ..., [else]) if
    conversion: cast (target = ftype)
    string-pred lowering produces: dict_lookup (see copr) — not built here
    """

    op: str
    args: list[PlanExpr]
    ftype: FieldType
    # op-specific payload (e.g. 'in_values' constant list, like pattern)
    extra: Any = None

    def __repr__(self) -> str:
        inner = ", ".join(map(repr, self.args))
        if self.extra is not None:
            return f"{self.op}({inner}; {self.extra!r})"
        return f"{self.op}({inner})"


@dataclass
class ScalarSubq(PlanExpr):
    """Uncorrelated scalar subquery. Materialized to a Const once per
    statement before execution (counterpart of the reference's scalar
    subquery rewrite, planner/core/expression_rewriter.go — which also
    evaluates uncorrelated subqueries eagerly)."""

    logical: Any  # LogicalPlan (typed loosely to avoid an import cycle)
    ftype: FieldType
    phys: Any = None  # PhysicalPlan, filled during optimize()

    def __repr__(self) -> str:
        return "scalar_subquery()"


@dataclass
class AggDesc:
    """One aggregate: func in {sum,count,avg,min,max}, arg expr (None for
    COUNT(*)), result type. Counterpart of expression/aggregation descriptors
    (reference: expression/aggregation/descriptor.go)."""

    func: str
    arg: Optional[PlanExpr]
    ftype: FieldType
    distinct: bool = False
    name: str = ""
    # constant extra parameters (e.g. APPROX_PERCENTILE's percent)
    params: tuple = ()

    def __repr__(self) -> str:
        inner = "*" if self.arg is None else repr(self.arg)
        d = "distinct " if self.distinct else ""
        return f"{self.func}({d}{inner})"


# ---- type inference ---------------------------------------------------------

_NUMERIC_RANK = {
    TypeKind.BOOLEAN: 0, TypeKind.TINYINT: 1, TypeKind.SMALLINT: 2,
    TypeKind.YEAR: 2, TypeKind.BIT: 3, TypeKind.INT: 3, TypeKind.BIGINT: 4,
    TypeKind.DECIMAL: 5, TypeKind.FLOAT: 6, TypeKind.DOUBLE: 7,
}


def is_numeric(ft: FieldType) -> bool:
    return ft.kind in _NUMERIC_RANK


def arith_result_type(op: str, a: FieldType, b: FieldType) -> FieldType:
    """MySQL numeric result typing (reference: types/field_type.go merge +
    expression/builtin_arithmetic.go scale rules)."""
    if a.kind == TypeKind.DATE or a.kind == TypeKind.DATETIME:
        # date arithmetic handled by caller (interval ops)
        raise ExprError(f"arith on temporal requires INTERVAL (op {op})")
    if not (is_numeric(a) and is_numeric(b)):
        raise ExprError(f"non-numeric operand for {op}: {a!r}, {b!r}")
    if a.kind == TypeKind.DOUBLE or b.kind == TypeKind.DOUBLE or \
            a.kind == TypeKind.FLOAT or b.kind == TypeKind.FLOAT:
        return FieldType(TypeKind.DOUBLE)
    a_dec, b_dec = a.is_decimal, b.is_decimal
    if op == "div":
        # decimal division: scale = s1 + 4 (div_precincrement)
        s = (a.scale if a_dec else 0) + 4
        return FieldType(TypeKind.DECIMAL, flen=18, scale=min(s, 12))
    if a_dec or b_dec:
        sa = a.scale if a_dec else 0
        sb = b.scale if b_dec else 0
        if op in ("add", "sub", "mod"):
            s = max(sa, sb)
        elif op == "mul":
            s = sa + sb
        elif op == "intdiv":
            return FieldType(TypeKind.BIGINT)
        else:
            raise ExprError(f"unknown arith op {op}")
        if s > 12:
            raise ExprError(f"decimal scale {s} exceeds device precision")
        return FieldType(TypeKind.DECIMAL, flen=18, scale=s)
    return FieldType(TypeKind.BIGINT)


def agg_result_type(func: str, arg: Optional[PlanExpr]) -> FieldType:
    if func in ("count", "approx_count_distinct"):
        # reference: executor/aggfuncs/builder.go:63 buildApproxCountDistinct
        # -> BIGINT, never NULL (0 on empty input), like COUNT
        return FieldType(TypeKind.BIGINT, nullable=False)
    assert arg is not None
    at = arg.ftype
    if func in ("std", "stddev", "stddev_pop", "stddev_samp",
                "variance", "var_pop", "var_samp"):
        # reference: executor/aggfuncs/func_varpop.go family -> DOUBLE
        return FieldType(TypeKind.DOUBLE)
    if func in ("bit_and", "bit_or", "bit_xor"):
        # reference: executor/aggfuncs/func_bitfuncs.go -> BIGINT UNSIGNED
        return FieldType(TypeKind.BIGINT, nullable=False)
    if func in ("any_value", "approx_percentile"):
        # reference: executor/aggfuncs/builder.go:110
        # buildApproxPercentile -> the argument's type
        return at
    if func == "group_concat":
        # reference: executor/aggfuncs/func_group_concat.go -> TEXT
        return FieldType(TypeKind.VARCHAR, flen=1024)
    if func in ("json_arrayagg", "json_objectagg"):
        # reference: executor/aggfuncs/func_json_arrayagg.go /
        # func_json_objectagg.go -> JSON
        return FieldType(TypeKind.JSON)
    if func in ("min", "max"):
        return at
    if func == "sum":
        if at.is_decimal:
            return FieldType(TypeKind.DECIMAL, flen=18, scale=at.scale)
        if at.is_float:
            return FieldType(TypeKind.DOUBLE)
        if at.is_integer:
            # MySQL: SUM(int) -> DECIMAL; we keep BIGINT on device and let the
            # host render; overflow beyond int64 is a known limitation
            return FieldType(TypeKind.BIGINT)
        raise ExprError(f"SUM over non-numeric {at!r}")
    if func == "avg":
        if at.is_decimal or at.is_integer:
            s = (at.scale if at.is_decimal else 0) + 4
            return FieldType(TypeKind.DECIMAL, flen=18, scale=min(s, 12))
        if at.is_float:
            return FieldType(TypeKind.DOUBLE)
        raise ExprError(f"AVG over non-numeric {at!r}")
    raise ExprError(f"unknown aggregate {func}")


def comparable(a: FieldType, b: FieldType) -> bool:
    if is_numeric(a) and is_numeric(b):
        return True
    if a.is_string and b.is_string:
        return True
    if a.is_temporal and (b.is_temporal or b.is_string):
        return True
    if b.is_temporal and a.is_string:
        return True
    from ..types.field_type import TypeKind as _TK
    if a.kind == _TK.SET and b.kind == _TK.SET:
        return True  # bitmask compare after const coercion
    return False


def bool_call(op: str, args: list[PlanExpr], extra: Any = None) -> Call:
    return Call(op, args, boolean_type(), extra=extra)
