"""Physical planning: column pruning, pushdown split, host operators.

Counterpart of the reference's physical optimization + task model (reference:
planner/core/find_best_task.go, task.go:56 copTask/rootTask; pushdown gate
expression.CanExprsPushDown -> canFuncBePushed, expression/expression.go:921).
Round-1 strategy is heuristic rather than cost-based: push the largest
scan->selection->agg/projection prefix whose expressions the device kernel
library supports; everything above runs in the host volcano engine.

Pruning mirrors columnPruner (reference: planner/core/rule_column_pruning.go):
scans read only referenced columns — essential when the device column cache
holds wide TPC-H tables.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..types.field_type import FieldType, TypeKind
from .dag import (CopDAG, DAGAggregation, DAGScan, DAGSelection, DAGTopN,
                  DAGLimit, HLL_WORDS)
from .expr import AggDesc, Call, Col, Const, PlanExpr, ScalarSubq
from .logical import (
    LogicalAggregation,
    LogicalJoin,
    LogicalLimit,
    LogicalPlan,
    LogicalProjection,
    LogicalScan,
    LogicalSelection,
    LogicalSort,
    LogicalUnion,
    LogicalWindow,
)
from .schema import PlanSchema, ResultField


# ==================== physical nodes ====================

class PhysicalPlan:
    schema: PlanSchema
    children: list["PhysicalPlan"]


@dataclass
class PhysTableRead(PhysicalPlan):
    """Leaf: ships a CopDAG to the TiTPU coprocessor (distsql.Select analog).

    With a pushed aggregation the output is partial-state columns:
    [group cols..., (val, cnt) per agg...] — the host PhysHashAgg(final)
    merges them (reference P2: partial agg in copr, final in TiDB)."""

    dag: CopDAG
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)
    est_rows: Optional[float] = None  # CBO estimate for EXPLAIN
    table: object = None  # TableInfo (fragment eligibility, plan/fragment.py)


@dataclass
class PhysPointGet(PhysicalPlan):
    """Point / batch-point get: resolve rows directly by handle or by a
    fully-pinned unique index key, bypassing the coprocessor scan entirely
    (reference: executor/point_get.go, executor/batch_point_get.go; planned
    by the TryFastPlan bypass, planner/core/point_get_plan.go:413)."""

    table: object  # TableInfo
    col_offsets: list[int]
    # pk-is-handle path: literal handles to fetch; else None
    handles: Optional[list[int]]
    # unique-index path: ScanRanges with full key points; else None
    ranges: Optional[object]
    # residual filter over the output schema
    conditions: list[PlanExpr]
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)
    est_rows: Optional[float] = None


@dataclass
class PhysIndexMerge(PhysicalPlan):
    """Union several index paths' handle sets, fetch once, re-check the
    full filter (reference: executor/index_merge_reader.go; planned by
    generateIndexMergePath, planner/core/stats.go). Chosen when the
    filter has one OR conjunct whose EVERY disjunct is servable by some
    index — each branch over-approximates its disjunct, so the union
    over-approximates the OR and the residual filter restores exactness."""

    table: object  # TableInfo
    col_offsets: list[int]
    branches: list[object]  # one ScanRanges per OR disjunct
    conditions: list[PlanExpr]  # FULL conjunct list, re-checked on fetch
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)
    est_rows: Optional[float] = None


@dataclass
class PhysSelection(PhysicalPlan):
    conditions: list[PlanExpr]
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysProjection(PhysicalPlan):
    exprs: list[PlanExpr]
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysHashAgg(PhysicalPlan):
    """mode 'final': merge device partials; mode 'complete': host-only agg."""

    mode: str
    group_by: list[PlanExpr]
    aggs: list[AggDesc]
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysHashJoin(PhysicalPlan):
    kind: str
    eq_conditions: list[tuple[int, int]]
    other_conditions: list[PlanExpr]
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysIndexJoin(PhysicalPlan):
    """Outer-driven index lookup join: per outer batch, probe the inner
    table's lazy sorted-permutation index with the outer keys and gather
    only the matching rows — no build of the full inner side (reference:
    executor/index_lookup_join.go; chosen by cost like
    planner/core/exhaust_physical_plans.go getIndexJoin when the outer is
    far smaller than the indexed inner). children = [outer, inner scan];
    the inner PhysTableRead is for EXPLAIN/stats — execution probes its
    table's index directly."""

    kind: str
    eq_conditions: list[tuple[int, int]]   # [(outer idx, inner LOCAL idx)]
    other_conditions: list[PlanExpr]
    schema: PlanSchema
    inner_offset: int = 0                  # store offset of the join col
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysMergeJoin(PhysicalPlan):
    """Sort-merge equi-join over key-ordered inputs (both sides join on
    their PK handles, which the columnar epochs keep ordered) — no hash
    table, a single searchsorted alignment (reference:
    executor/merge_join.go; picked by exhaust_physical_plans.go when both
    children provide the key order)."""

    kind: str
    eq_conditions: list[tuple[int, int]]
    other_conditions: list[PlanExpr]
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysUnion(PhysicalPlan):
    """UNION ALL: run children, normalize each child's columns to the
    unified schema (scale/width/dictionary), concatenate (reference:
    executor/union iterating children; DISTINCT is an agg above)."""

    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysWindow(PhysicalPlan):
    """Host window computation appending one column per item (reference:
    executor/window.go; shuffle-partition parallelism replaced by
    vectorized segmented numpy passes)."""

    items: list
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysSort(PhysicalPlan):
    items: list[tuple[PlanExpr, bool]]
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


@dataclass
class PhysLimit(PhysicalPlan):
    limit: int
    offset: int
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)


# ==================== pushdown gate ====================

# ops the JAX kernel compiler supports (copr/compiler.py) — keep in sync.
_DEVICE_OPS = frozenset(
    """
    add sub mul div intdiv mod neg abs
    eq ne lt le gt ge
    and or not isnull in_values like if ifnull coalesce case
    year month day date_add_days cast
    """.split()
)

_STRING_OK_OPS = frozenset({"eq", "ne", "in_values", "like", "isnull",
                            "ifnull", "coalesce", "if", "case"})


def _type_on_device(ft: FieldType) -> bool:
    return ft.kind != TypeKind.NULL


def expr_pushable(e: PlanExpr) -> bool:
    """The canFuncBePushed analog for the TiTPU store."""
    if isinstance(e, (Col, Const)):
        if e.ftype.is_string and e.ftype.is_ci:
            # ci collations compare casefolded strings; the device code
            # tables are built per-predicate host-side, but keeping ci
            # columns host-only keeps code-space semantics simple
            # (reference gates new collations similarly,
            # expression.go:921 canFuncBePushed collation check)
            return False
        return _type_on_device(e.ftype)
    if isinstance(e, Call):
        if e.op not in _DEVICE_OPS:
            return False
        if e.op == "cast":
            # only numeric<->numeric casts on device
            if e.ftype.is_string or any(a.ftype.is_string for a in e.args):
                return False
        for a in e.args:
            if a.ftype.is_string and e.op not in _STRING_OK_OPS:
                return False
            if not expr_pushable(a):
                return False
        return _type_on_device(e.ftype)
    return False


def agg_pushable(group_by: list[PlanExpr], aggs: list[AggDesc]) -> bool:
    for g in group_by:
        if not expr_pushable(g):
            return False
        if g.ftype.is_float:
            # float group keys are ill-defined on device hashing; host handles
            return False
        if g.ftype.is_string and g.ftype.is_ci:
            return False  # ci grouping merges case variants host-side
    for d in aggs:
        if d.distinct:
            return False
        if d.func not in ("sum", "count", "avg", "min", "max",
                          "approx_count_distinct"):
            return False
        if d.func == "approx_count_distinct":
            # device HLL hashes the widened int32 value; floats would hash
            # their f32 staging (host values are f64 — sketch mismatch) and
            # string dict codes differ across partition dictionaries, so
            # both stay host-side
            if d.arg is None or not expr_pushable(d.arg) \
                    or d.arg.ftype.is_string or d.arg.ftype.is_float:
                return False
            continue
        if d.arg is not None:
            if not expr_pushable(d.arg):
                return False
            if d.arg.ftype.is_string:
                return False  # min/max over dict codes is order-wrong
    return True


# ==================== predicate pushdown ====================

def push_predicates(plan: LogicalPlan) -> LogicalPlan:
    """Push selection conditions below joins; discover equi-join conditions
    from WHERE (turns comma/CROSS joins into INNER hash joins). Counterpart
    of reference planner/core/rule_predicate_push_down.go. Outer joins only
    accept pushes to their outer side (null-extension safety)."""
    plan.children = [push_predicates(c) for c in plan.children]

    if isinstance(plan, (LogicalUnion, LogicalWindow)):
        return plan

    if isinstance(plan, LogicalSelection):
        child = plan.children[0]
        if isinstance(child, LogicalSelection):
            child.conditions = plan.conditions + child.conditions
            return child
        if isinstance(child, LogicalJoin):
            join = child
            nleft = len(join.children[0].schema)
            left_c: list[PlanExpr] = []
            right_c: list[PlanExpr] = []
            remain: list[PlanExpr] = []
            for cond in plan.conditions:
                cols: set[int] = set()
                _expr_cols(cond, cols)
                pair = _as_equi_pair_phys(cond, nleft)
                if pair is not None and join.kind in ("INNER", "CROSS"):
                    join.eq_conditions.append(pair)
                elif cols and max(cols) < nleft and join.kind in (
                    "INNER", "CROSS", "LEFT", "SEMI", "ANTI", "ANTI_NULL"
                ):
                    left_c.append(cond)
                elif cols and min(cols) >= nleft and join.kind in (
                    "INNER", "CROSS", "RIGHT"
                ):
                    right_c.append(_remap_expr(
                        cond, {i: i - nleft for i in cols}))
                elif join.kind in ("INNER", "CROSS"):
                    join.other_conditions.append(cond)
                else:
                    remain.append(cond)
            if join.kind == "CROSS" and (join.eq_conditions or
                                         join.other_conditions):
                join.kind = "INNER"
            if left_c:
                join.children[0] = push_predicates(LogicalSelection(
                    left_c, join.children[0].schema, [join.children[0]]))
            if right_c:
                join.children[1] = push_predicates(LogicalSelection(
                    right_c, join.children[1].schema, [join.children[1]]))
            if remain:
                plan.conditions = remain
                plan.children = [join]
                return plan
            return join
    return plan


def _as_equi_pair_phys(cond: PlanExpr, nleft: int):
    if isinstance(cond, Call) and cond.op == "eq":
        a, b = cond.args
        if isinstance(a, Col) and isinstance(b, Col):
            if a.idx < nleft <= b.idx:
                return (a.idx, b.idx - nleft)
            if b.idx < nleft <= a.idx:
                return (b.idx, a.idx - nleft)
    return None


# ==================== column pruning ====================

def _expr_cols(e: PlanExpr, out: set[int]) -> None:
    if isinstance(e, Col):
        out.add(e.idx)
    elif isinstance(e, Call):
        for a in e.args:
            _expr_cols(a, out)


def _remap_expr(e: PlanExpr, mapping: dict[int, int]) -> PlanExpr:
    if isinstance(e, Col):
        return Col(mapping[e.idx], e.ftype, e.name)
    if isinstance(e, Call):
        return Call(e.op, [_remap_expr(a, mapping) for a in e.args], e.ftype,
                    e.extra)
    return e


def prune(plan: LogicalPlan, required: Optional[set[int]] = None) -> LogicalPlan:
    """Drop unused columns below each node; rewrites Col indices in place of
    the old schema positions. `required` is the set of this node's output
    indices the parent needs (None = all)."""
    if required is None:
        required = set(range(len(plan.schema)))

    if isinstance(plan, LogicalUnion):
        # union columns align by position, so the parent's requirement
        # prunes every child at the same positions; a child that must
        # keep extra columns (its selection's condition columns) gets an
        # aligning projection. Essential for partitioned scans, whose
        # unions would otherwise read every column of wide tables.
        keep = sorted(required)
        if not keep and plan.schema.fields:
            keep = [0]
        new_children = []
        for c in plan.children:
            c2 = prune(c, set(keep))
            m = c2._prune_map  # type: ignore[attr-defined]
            positions = [m[old] for old in keep]
            if positions != list(range(len(c2.schema))):
                exprs = [Col(m[old], c2.schema.fields[m[old]].ftype)
                         for old in keep]
                c2 = LogicalProjection(
                    exprs,
                    PlanSchema([c2.schema.fields[m[old]] for old in keep]),
                    [c2])
            new_children.append(c2)
        plan.children = new_children
        plan.schema = PlanSchema([plan.schema.fields[i] for i in keep])
        plan._prune_map = {old: new for new, old in enumerate(keep)}  # type: ignore[attr-defined]
        return plan

    if isinstance(plan, LogicalWindow):
        # window items reference arbitrary child columns; keep them all
        plan.children = [prune(c) for c in plan.children]
        plan._prune_map = {i: i for i in range(len(plan.schema))}  # type: ignore[attr-defined]
        return plan

    if isinstance(plan, LogicalScan):
        keep = sorted(required) or [0] if plan.table.columns else []
        if plan.table.columns and not keep:
            keep = [0]
        fields = [plan.schema.fields[i] for i in keep]
        plan.used_offsets = [plan.schema.fields[i].source_offset for i in keep]
        plan.schema = PlanSchema(fields)
        plan._prune_map = {old: new for new, old in enumerate(keep)}  # type: ignore[attr-defined]
        return plan

    if isinstance(plan, LogicalSelection):
        need = set(required)
        for c in plan.conditions:
            _expr_cols(c, need)
        child = prune(plan.children[0], need)
        m = child._prune_map  # type: ignore[attr-defined]
        plan.conditions = [_remap_expr(c, m) for c in plan.conditions]
        plan.schema = child.schema
        plan._prune_map = m  # type: ignore[attr-defined]
        return plan

    if isinstance(plan, LogicalProjection):
        keep = sorted(required)
        if not keep and plan.exprs:
            # a zero-column chunk cannot carry a row count: keep one
            # expr so '(select 1) d' cross joins still contribute rows
            keep = [0]
        exprs = [plan.exprs[i] for i in keep]
        need: set[int] = set()
        for e in exprs:
            _expr_cols(e, need)
        child = prune(plan.children[0], need)
        m = child._prune_map  # type: ignore[attr-defined]
        plan.exprs = [_remap_expr(e, m) for e in exprs]
        plan.schema = PlanSchema([plan.schema.fields[i] for i in keep])
        plan._prune_map = {old: new for new, old in enumerate(keep)}  # type: ignore[attr-defined]
        return plan

    if isinstance(plan, LogicalAggregation):
        ngroups = len(plan.group_by)
        keep_aggs = sorted(
            {i - ngroups for i in required if i >= ngroups}
        )
        plan.aggs = [plan.aggs[i] for i in keep_aggs]
        need: set[int] = set()
        for g in plan.group_by:
            _expr_cols(g, need)
        for d in plan.aggs:
            if d.arg is not None:
                _expr_cols(d.arg, need)
        child = prune(plan.children[0], need)
        m = child._prune_map  # type: ignore[attr-defined]
        plan.group_by = [_remap_expr(g, m) for g in plan.group_by]
        plan.aggs = [
            AggDesc(d.func, None if d.arg is None else _remap_expr(d.arg, m),
                    d.ftype, d.distinct, d.name, d.params)
            for d in plan.aggs
        ]
        fields = plan.schema.fields[:ngroups] + [
            plan.schema.fields[ngroups + i] for i in keep_aggs
        ]
        plan.schema = PlanSchema(fields)
        out_map = {g: g for g in range(ngroups)}
        for new, old in enumerate(keep_aggs):
            out_map[ngroups + old] = ngroups + new
        plan._prune_map = out_map  # type: ignore[attr-defined]
        return plan

    if isinstance(plan, LogicalSort):
        need = set(required)
        for e, _ in plan.items:
            _expr_cols(e, need)
        child = prune(plan.children[0], need)
        m = child._prune_map  # type: ignore[attr-defined]
        plan.items = [(_remap_expr(e, m), d) for e, d in plan.items]
        plan.schema = child.schema
        plan._prune_map = m  # type: ignore[attr-defined]
        return plan

    if isinstance(plan, LogicalLimit):
        child = prune(plan.children[0], set(required))
        plan.schema = child.schema
        plan._prune_map = child._prune_map  # type: ignore[attr-defined]
        return plan

    if isinstance(plan, LogicalJoin):
        semi = plan.kind in ("SEMI", "ANTI", "ANTI_NULL")
        nleft = len(plan.children[0].schema)
        need_l: set[int] = set()
        need_r: set[int] = set()
        for i in required:
            # semi/anti joins output the left schema only
            (need_l if i < nleft else need_r).add(i if i < nleft else i - nleft)
        for li, ri in plan.eq_conditions:
            need_l.add(li)
            need_r.add(ri)
        both: set[int] = set()
        for c in plan.other_conditions:
            _expr_cols(c, both)
        for i in both:
            (need_l if i < nleft else need_r).add(i if i < nleft else i - nleft)
        left = prune(plan.children[0], need_l)
        right = prune(plan.children[1], need_r)
        ml = left._prune_map  # type: ignore[attr-defined]
        mr = right._prune_map  # type: ignore[attr-defined]
        new_nleft = len(left.schema)
        m = {}
        for old, new in ml.items():
            m[old] = new
        for old, new in mr.items():
            m[nleft + old] = new_nleft + new
        plan.eq_conditions = [(ml[a], mr[b]) for a, b in plan.eq_conditions]
        plan.other_conditions = [
            _remap_expr(c, m) for c in plan.other_conditions
        ]
        if semi:
            plan.schema = PlanSchema(left.schema.fields)
            plan._prune_map = ml  # type: ignore[attr-defined]
        else:
            plan.schema = PlanSchema(left.schema.fields + right.schema.fields)
            plan._prune_map = m  # type: ignore[attr-defined]
        return plan

    raise TypeError(f"prune: unknown node {type(plan).__name__}")


# ==================== physical build ====================

def optimize(plan: LogicalPlan, stats=None) -> PhysicalPlan:
    plan = push_predicates(plan)
    from .partition import expand_partitions
    plan = expand_partitions(plan)
    from .reorder import reorder_joins
    plan = reorder_joins(plan, stats)
    plan = prune(plan)
    phys = _to_physical(plan, stats)
    from .fragment import apply_fragments
    phys = apply_fragments(phys)
    # joins the device fragment rewriter left on the host pick their
    # algorithm by cost (hash / index-lookup / merge)
    phys = apply_join_algorithms(phys)
    _optimize_subqueries(phys, stats)
    return phys


def apply_join_algorithms(plan: PhysicalPlan) -> PhysicalPlan:
    plan.children = [apply_join_algorithms(c) for c in plan.children]
    if isinstance(plan, PhysHashJoin):
        return _choose_join(plan, plan.children[0], plan.children[1])
    return plan


def _optimize_subqueries(plan: PhysicalPlan, stats=None) -> None:
    """Optimize the logical plan inside every ScalarSubq expression
    (uncorrelated — runs once per statement, engine materializes it)."""
    for e in _node_exprs(plan):
        _optimize_subq_expr(e, stats)
    for c in plan.children:
        _optimize_subqueries(c, stats)


def _optimize_subq_expr(e: PlanExpr, stats=None) -> None:
    if isinstance(e, ScalarSubq):
        if e.phys is None:
            e.phys = optimize(e.logical, stats)
    elif isinstance(e, Call):
        for a in e.args:
            _optimize_subq_expr(a, stats)


def _node_exprs(plan: PhysicalPlan) -> list[PlanExpr]:
    out: list[PlanExpr] = []
    if isinstance(plan, PhysSelection):
        out += plan.conditions
    elif isinstance(plan, PhysPointGet):
        out += plan.conditions
    elif isinstance(plan, PhysProjection):
        out += plan.exprs
    elif isinstance(plan, PhysHashAgg):
        out += plan.group_by
        out += [d.arg for d in plan.aggs if d.arg is not None]
    elif isinstance(plan, PhysSort):
        out += [e for e, _ in plan.items]
    elif isinstance(plan, PhysHashJoin):
        out += plan.other_conditions
    return out


def _fresh_table_read(scan: LogicalScan) -> PhysTableRead:
    offsets = scan.used_offsets
    if offsets is None:
        offsets = [f.source_offset for f in scan.schema.fields]
    dag = CopDAG(
        scan=DAGScan(scan.table.id, offsets),
        output_types=[f.ftype for f in scan.schema.fields],
    )
    return PhysTableRead(dag, scan.schema, table=scan.table)


def _bare_scan(tr: PhysTableRead) -> bool:
    dag = tr.dag
    if dag.scan.table_id < 0:
        return False  # dual pseudo-table: everything stays host-side
    return dag.agg is None and dag.topn is None and dag.limit is None and \
        dag.projections is None


def _has_subq(e: PlanExpr) -> bool:
    if isinstance(e, ScalarSubq):
        return True
    if isinstance(e, Call):
        return any(_has_subq(a) for a in e.args)
    return False


# index path cost gates (fractions of table rows): the device scan is so
# fast that host-side gather only wins at low selectivity
POINT_SEL_LIMIT = 0.1     # non-unique equality points (stats available)
INTERVAL_SEL_LIMIT = 0.05  # interval ranges (require stats to justify)


def _access_path(scan_offsets: list[int], table, conditions, stats=None,
                 scan=None):
    """Choose an index access path from the conjuncts. Equality points are
    chosen heuristically (point lookups justify themselves); interval
    ranges are chosen only when statistics estimate low selectivity.
    USE_INDEX/IGNORE_INDEX hints on the scan constrain the candidate set
    and bypass the selectivity gates (reference: hints.go).
    Returns ('handles', [int], est) | ('unique', ScanRanges, est) |
    ('ranges', ScanRanges, est) | None (full scan). Reference: access-path
    selection planner/core/planbuilder.go:933 + point-get bypass
    point_get_plan.go:413 + selectivity feed statistics/selectivity.go.
    """
    from .ranger import (
        _eq_values,
        extract_interval,
        extract_points,
        full_unique_match,
        ScanRanges,
    )

    use_hint = [n.lower() for n in
                getattr(scan, "hint_use_index", [])] if scan else []
    ignore_hint = {n.lower() for n in
                   getattr(scan, "hint_ignore_index", [])} if scan else set()

    def allowed(index) -> bool:
        if index.name.lower() in ignore_hint:
            return False
        return not use_hint or index.name.lower() in use_hint

    col_map = {i: off for i, off in enumerate(scan_offsets)}
    if table.pk_handle_offset is not None and not use_hint:
        for c in conditions:
            hit = _eq_values(c, col_map)
            if hit is not None and hit[0] == table.pk_handle_offset:
                return "handles", [int(v) for v in hit[1]], float(len(hit[1]))
    ts = stats.table_stats(table.id) if stats is not None else None
    best = None
    best_est = None
    # the ranged path evals all conjuncts storage-side, which can't host a
    # scalar subquery; unique/handle point gets filter engine-side, so
    # they stay eligible
    has_subq = any(_has_subq(c) for c in conditions)
    for index in table.indices:
        if not index.visible:
            continue  # still being built online (ddl/ddl.py)
        if not allowed(index):
            continue
        r = extract_points(table, index, conditions, col_map)
        if r is None:
            continue
        if full_unique_match(table, r):
            return "unique", r, float(len(r.points))
        if has_subq:
            continue
        if not r.points:  # contradictory equalities: provably empty
            return "ranges", r, 0.0
        est = None
        if ts is not None:
            off0 = index.col_offsets[0]
            est = sum(
                stats.est_eq_rows(table.id, off0, p[0], ts.row_count)
                for p in r.points)
            if est > ts.row_count * POINT_SEL_LIMIT and \
                    index.name.lower() not in use_hint:
                continue  # too many rows: the full scan is cheaper
        depth = len(r.points[0])
        if best is None or depth > len(best.points[0]) or (
                depth == len(best.points[0])
                and len(r.points) < len(best.points)):
            best, best_est = r, est
    if best is not None:
        return "ranges", best, best_est
    # interval ranges: only with statistics backing the choice (a USE_INDEX
    # hint overrides the gate — the user asserted the path is good)
    if (ts is not None or use_hint) and not has_subq:
        for index in table.indices:
            if not index.visible or not allowed(index):
                continue
            off0 = index.col_offsets[0]
            if table.columns[off0].ftype.is_string:
                continue
            interval = extract_interval(off0, conditions, col_map)
            if interval is None:
                continue
            lo, hi, li, hi_i = interval
            if index.name.lower() in use_hint:
                return "ranges", ScanRanges(index, [], interval), None
            if ts is None:
                continue
            est = stats.est_range_rows(table.id, off0, lo, hi, li, hi_i,
                                       ts.row_count)
            if est <= ts.row_count * INTERVAL_SEL_LIMIT:
                return "ranges", ScanRanges(index, [], interval), est
    return None


MERGE_SEL_LIMIT = 0.3  # union of branch estimates vs full scan


def _flatten_bool(e: PlanExpr, op: str) -> list[PlanExpr]:
    if isinstance(e, Call) and e.op == op:
        out: list[PlanExpr] = []
        for a in e.args:
            out.extend(_flatten_bool(a, op))
        return out
    return [e]


def _index_merge_path(scan_offsets: list[int], table, conditions,
                      stats=None, scan=None):
    """(branches, est) for an index-merge UNION read, or None.

    Looks for ONE conjunct that is an OR whose every disjunct (itself a
    conjunction) is servable by an index equality-point set — or by the
    pk-handle column. Estimates sum per-branch; with statistics the sum
    must clear MERGE_SEL_LIMIT (without them, points-only branches are
    allowed on the same heuristic as the single-index path). Reference:
    planner/core/stats.go generateIndexMergePath + its accessPaths-per-
    disjunct check."""
    from .ranger import _eq_values, extract_points

    use_hint = [n.lower() for n in
                getattr(scan, "hint_use_index", [])] if scan else []
    ignore_hint = {n.lower() for n in
                   getattr(scan, "hint_ignore_index", [])} if scan else set()
    col_map = {i: off for i, off in enumerate(scan_offsets)}
    or_cond = None
    for c in conditions:
        if isinstance(c, Call) and c.op == "or":
            if _has_subq(c):
                return None
            if or_cond is not None:
                return None  # one mergeable OR at a time (ref parity)
            or_cond = c
    if or_cond is None:
        return None
    disjuncts = _flatten_bool(or_cond, "or")
    if len(disjuncts) < 2:
        return None
    ts = stats.table_stats(table.id) if stats is not None else None
    branches = []
    total_est = 0.0 if ts is not None else None
    for d in disjuncts:
        conjs = _flatten_bool(d, "and")
        # pk-handle branch: col = const / IN on the handle column
        handle_rng = None
        if table.pk_handle_offset is not None:
            for c in conjs:
                hit = _eq_values(c, col_map)
                if hit is not None and hit[0] == table.pk_handle_offset:
                    from .ranger import ScanRanges
                    handle_rng = ScanRanges(
                        None, [(int(v),) for v in hit[1]])
                    break
        best = None
        for index in table.indices:
            if not index.visible or index.name.lower() in ignore_hint:
                continue
            if use_hint and index.name.lower() not in use_hint:
                continue
            r = extract_points(table, index, conjs, col_map)
            if r is None or not r.points:
                continue
            depth = len(r.points[0])
            if best is None or depth > len(best.points[0]) or (
                    depth == len(best.points[0])
                    and len(r.points) < len(best.points)):
                best = r
        if best is None:
            best = handle_rng
        if best is None:
            return None  # a disjunct with no index: merge can't win
        branches.append(best)
        if ts is not None:
            if best.index is None:
                total_est += len(best.points)
            else:
                off0 = best.index.col_offsets[0]
                total_est += sum(
                    stats.est_eq_rows(table.id, off0, p[0], ts.row_count)
                    for p in best.points)
    if ts is not None and total_est > ts.row_count * MERGE_SEL_LIMIT \
            and not use_hint:
        return None
    return branches, total_est


def conds_digest(conditions: list[PlanExpr]) -> str:
    """Stable identity of a conjunct set (feedback keying)."""
    return "&".join(sorted(repr(c) for c in conditions))


def _est_selection_rows(table, scan_offsets: list[int],
                        conditions: list[PlanExpr], stats) -> Optional[float]:
    """Cardinality estimate for a conjunct set (reference:
    statistics/selectivity.go): per-conjunct selectivities combined
    with exponential backoff (most selective factor fully, later ones
    with diminishing exponents) so correlated predicates don't compound
    into wild underestimates. An actual-execution feedback record for
    the same conjunct set overrides everything
    (statistics/feedback.go)."""
    if stats is not None:
        fb = stats.feedback_rows(table.id, conds_digest(conditions))
        if fb is not None:
            return float(fb)
    ts = stats.table_stats(table.id) if stats is not None else None
    if ts is None:
        return None
    from .ranger import _eq_values, extract_interval

    col_map = {i: off for i, off in enumerate(scan_offsets)}
    rows = max(ts.row_count, 1.0)
    interval_offs: set[int] = set()
    sels: list[float] = []
    for c in conditions:
        hit = _eq_values(c, col_map)
        if hit is not None:
            off, vals = hit
            est = sum(stats.est_eq_rows(table.id, off, v, rows)
                      for v in vals)
            sels.append(min(est / rows, 1.0))
            continue
        if isinstance(c, Call) and c.op in ("lt", "le", "gt", "ge"):
            cols: set[int] = set()
            _expr_cols(c, cols)
            offs = {col_map[i] for i in cols if i in col_map}
            if len(offs) == 1:
                off = next(iter(offs))
                if off in interval_offs:
                    continue  # both bounds of one interval: count once
                interval_offs.add(off)
                iv = extract_interval(off, conditions, col_map)
                if iv is not None:
                    est = stats.est_range_rows(table.id, off, *iv,
                                               fallback_rows=rows)
                    sels.append(min(est / rows, 1.0))
                    continue
        sels.append(0.8)  # uninterpretable conjunct: mild filter factor
    # exponential backoff instead of naive independence: correlated
    # predicates make the product wildly underestimate, so later (less
    # selective... sorted ascending) factors contribute with diminishing
    # exponents s0 * s1^(1/2) * s2^(1/4) * ... (reference: the
    # selectivity ordering in statistics/selectivity.go; the backoff
    # form is TiDB's tidb_opt_correlation-era estimator)
    sel = 1.0
    for k, s in enumerate(sorted(sels)):
        if k >= 4:
            break  # factors beyond the 4th add nothing measurable
        sel *= s ** (1.0 / (1 << k))
    return rows * sel


def _to_physical(plan: LogicalPlan, stats=None) -> PhysicalPlan:
    if isinstance(plan, LogicalScan):
        tr = _fresh_table_read(plan)
        ts = stats.table_stats(plan.table.id) if stats is not None \
            else None
        if ts is not None:
            tr.est_rows = float(ts.row_count)
        return tr

    if isinstance(plan, LogicalSelection):
        child = _to_physical(plan.children[0], stats)
        if isinstance(child, PhysTableRead) and _bare_scan(child) and \
                isinstance(plan.children[0], LogicalScan):
            scan = plan.children[0]
            ap = _access_path(child.dag.scan.col_offsets, scan.table,
                              plan.conditions, stats, scan=scan)
            if ap is not None:
                kind, payload, est = ap
                if kind in ("handles", "unique"):
                    return PhysPointGet(
                        scan.table, child.dag.scan.col_offsets,
                        payload if kind == "handles" else None,
                        payload if kind == "unique" else None,
                        list(plan.conditions), plan.schema, est_rows=est)
                child.dag.scan.ranges = payload
                child.dag.selection = DAGSelection(list(plan.conditions))
                child.est_rows = est
                return child
            im = _index_merge_path(child.dag.scan.col_offsets, scan.table,
                                   plan.conditions, stats, scan=scan)
            if im is not None:
                branches, est = im
                return PhysIndexMerge(
                    scan.table, child.dag.scan.col_offsets, branches,
                    list(plan.conditions), plan.schema, est_rows=est)
        if (
            isinstance(child, PhysTableRead)
            and _bare_scan(child)
            and all(expr_pushable(c) for c in plan.conditions)
        ):
            dag = child.dag
            if dag.selection is None:
                dag.selection = DAGSelection(list(plan.conditions))
            else:
                dag.selection.conditions.extend(plan.conditions)
            if isinstance(plan.children[0], LogicalScan):
                child.est_rows = _est_selection_rows(
                    plan.children[0].table, dag.scan.col_offsets,
                    plan.conditions, stats)
            return child
        return PhysSelection(plan.conditions, plan.schema, [child])

    if isinstance(plan, LogicalAggregation):
        child = _to_physical(plan.children[0], stats)
        if (
            isinstance(child, PhysTableRead)
            and _bare_scan(child)
            and agg_pushable(plan.group_by, plan.aggs)
        ):
            dag = child.dag
            dag.agg = DAGAggregation(list(plan.group_by), list(plan.aggs))
            # partial layout: group cols, then (val, cnt) per agg —
            # except approx_count_distinct, which ships HLL_WORDS packed
            # register words + cnt (plan/dag.agg_partial_width)
            fields = []
            for i, g in enumerate(plan.group_by):
                fields.append(ResultField(f"gk#{i}", g.ftype))
            for i, d in enumerate(plan.aggs):
                if d.func == "approx_count_distinct":
                    for w in range(HLL_WORDS):
                        fields.append(ResultField(
                            f"ph#{i}_{w}",
                            FieldType(TypeKind.BIGINT, nullable=False)))
                else:
                    val_t = _partial_val_type(d)
                    fields.append(ResultField(f"pv#{i}", val_t))
                fields.append(
                    ResultField(f"pc#{i}",
                                FieldType(TypeKind.BIGINT, nullable=False))
                )
            child.schema = PlanSchema(fields)
            dag.output_types = [f.ftype for f in fields]
            return PhysHashAgg("final", plan.group_by, plan.aggs, plan.schema,
                               [child])
        return PhysHashAgg("complete", plan.group_by, plan.aggs, plan.schema,
                           [child])

    if isinstance(plan, LogicalProjection):
        child = _to_physical(plan.children[0], stats)
        if (
            isinstance(child, PhysTableRead)
            and _bare_scan(child)
            and all(expr_pushable(e) for e in plan.exprs)
            and not any(e.ftype.is_string and not isinstance(e, Col)
                        for e in plan.exprs)
        ):
            child.dag.projections = list(plan.exprs)
            child.dag.output_types = [e.ftype for e in plan.exprs]
            child.schema = plan.schema
            return child
        return PhysProjection(plan.exprs, plan.schema, [child])

    if isinstance(plan, LogicalUnion):
        return PhysUnion(plan.schema,
                         [_to_physical(c, stats) for c in plan.children])

    if isinstance(plan, LogicalWindow):
        return PhysWindow(plan.items, plan.schema,
                          [_to_physical(plan.children[0], stats)])

    if isinstance(plan, LogicalSort):
        child = _to_physical(plan.children[0], stats)
        return PhysSort(plan.items, plan.schema, [child])

    if isinstance(plan, LogicalLimit):
        # TopN pushdown (reference: rule_topn_push_down.go). Patterns:
        #   Limit <- Sort <- pushable chain
        #   Limit <- Projection(trim) <- Sort <- pushable chain
        # dag.topn runs after dag.projections, so sort items referencing the
        # projected output are valid as-is.
        if plan.offset == 0:
            sort_node = None
            trim: Optional[LogicalProjection] = None
            c0 = plan.children[0]
            if isinstance(c0, LogicalSort):
                sort_node = c0
            elif isinstance(c0, LogicalProjection) and \
                    isinstance(c0.children[0], LogicalSort) and \
                    all(isinstance(e, Col) for e in c0.exprs):
                trim = c0
                sort_node = c0.children[0]
            if sort_node is not None and all(
                expr_pushable(e) and not e.ftype.is_string
                for e, _ in sort_node.items
            ):
                inner = _to_physical(sort_node.children[0], stats)
                if isinstance(inner, PhysTableRead) and \
                        inner.dag.scan.table_id >= 0 and \
                        inner.dag.agg is None and \
                        inner.dag.topn is None and inner.dag.limit is None:
                    inner.dag.topn = DAGTopN(sort_node.items, plan.limit)
                    # per-batch top-k results (base epoch + MVCC overlay)
                    # still need a host merge sort + exact limit
                    merged: PhysicalPlan = PhysSort(
                        sort_node.items, inner.schema, [inner])
                    merged = PhysLimit(plan.limit, 0, inner.schema, [merged])
                    if trim is not None:
                        return PhysProjection(trim.exprs, trim.schema,
                                              [merged])
                    return merged
        child = _to_physical(plan.children[0], stats)
        # Limit over a pushable chain lowers to dag.limit (per-region limit is
        # a superset; host PhysLimit still enforces the exact count)
        if isinstance(child, PhysTableRead) and child.dag.agg is None and \
                child.dag.topn is None and child.dag.limit is None:
            child.dag.limit = DAGLimit(plan.limit + plan.offset)
        return PhysLimit(plan.limit, plan.offset, plan.schema, [child])

    if isinstance(plan, LogicalJoin):
        left = _to_physical(plan.children[0], stats)
        right = _to_physical(plan.children[1], stats)
        return PhysHashJoin(plan.kind, plan.eq_conditions,
                            plan.other_conditions, plan.schema,
                            [left, right])

    raise TypeError(f"optimize: unknown node {type(plan).__name__}")


# outer side must be this much smaller (and absolutely small) before
# an index probe beats building one hash of the inner
_INDEX_JOIN_RATIO = 32
_INDEX_JOIN_MAX_OUTER = 200_000


def _join_col_index(table, off: int) -> bool:
    """Does the inner table have a usable single-column index (or the PK
    handle) on store offset `off`?"""
    if table.pk_handle_offset == off:
        return True
    for ix in table.indices:
        if ix.visible and ix.col_offsets == [off]:
            return True
    return False


def _bare_inner_scan(node) -> bool:
    return (isinstance(node, PhysTableRead)
            and getattr(node, "table", None) is not None
            and node.dag.agg is None and node.dag.topn is None
            and node.dag.limit is None and node.dag.scan.ranges is None
            and node.dag.projections is None)


def _choose_join(plan: PhysHashJoin, left, right):
    """Cost-based physical join selection (reference:
    planner/core/exhaust_physical_plans.go): index-lookup join when one
    side is a bare indexed scan and the other side is much smaller;
    merge join when both sides arrive ordered on their join keys (PK
    handles); hash join otherwise. Runs AFTER the device-fragment
    rewriter — only host-remaining joins choose an algorithm."""
    hash_join = plan
    if len(plan.eq_conditions) != 1:
        return hash_join

    def est(node):
        return getattr(node, "est_rows", None)

    # ---- merge join: both sides PK-ordered on the join key ----
    if plan.kind == "INNER" and _bare_inner_scan(left) and \
            _bare_inner_scan(right):
        # LogicalJoin eq pairs are (left idx, right-LOCAL idx)
        li, ri = plan.eq_conditions[0]
        l_off = left.dag.scan.col_offsets[li] if li < len(
            left.dag.scan.col_offsets) else None
        r_off = right.dag.scan.col_offsets[ri] \
            if ri < len(right.dag.scan.col_offsets) else None
        if l_off == left.table.pk_handle_offset and \
                r_off == right.table.pk_handle_offset and \
                l_off is not None and r_off is not None:
            return PhysMergeJoin(plan.kind, plan.eq_conditions,
                                 plan.other_conditions, plan.schema,
                                 [left, right])

    # ---- index join: inner is a bare indexed scan, outer is small ----
    if plan.kind in ("INNER", "SEMI"):
        oi, ii = plan.eq_conditions[0]
        inner, outer = right, left
        if _bare_inner_scan(inner) and ii < len(
                inner.dag.scan.col_offsets):
            off = inner.dag.scan.col_offsets[ii]
            ft = inner.dag.output_types[ii]
            # BOTH key types must be integral: the probe casts outer
            # keys to int64, which would silently truncate float or
            # misread scaled-decimal keys
            oft = outer.schema.fields[oi].ftype \
                if oi < len(outer.schema.fields) else None
            o_est, i_est = est(outer), est(inner)
            if oft is not None and oft.kind in _INT_JOIN_KINDS and \
                    ft.kind in _INT_JOIN_KINDS and \
                    _join_col_index(inner.table, off) and \
                    o_est is not None and i_est is not None and \
                    o_est < _INDEX_JOIN_MAX_OUTER and \
                    o_est * _INDEX_JOIN_RATIO < i_est:
                return PhysIndexJoin(plan.kind, plan.eq_conditions,
                                     plan.other_conditions, plan.schema,
                                     off, [outer, inner])
    return hash_join


_INT_JOIN_KINDS = (TypeKind.TINYINT, TypeKind.SMALLINT, TypeKind.INT,
                   TypeKind.BIGINT, TypeKind.YEAR)


def _partial_val_type(d: AggDesc) -> FieldType:
    if d.func == "count":
        return FieldType(TypeKind.BIGINT, nullable=False)
    if d.func == "avg":
        assert d.arg is not None
        at = d.arg.ftype
        if at.is_decimal:
            return FieldType(TypeKind.DECIMAL, flen=18, scale=at.scale)
        if at.is_float:
            return FieldType(TypeKind.DOUBLE)
        return FieldType(TypeKind.BIGINT)
    return d.ftype


# ==================== explain ====================

def explain_nodes(plan: PhysicalPlan, depth: int = 0):
    """[(node, rendered line)] in display order."""
    out = [(plan, explain_plan(plan, depth)[0])]
    for c in plan.children:
        out.extend(explain_nodes(c, depth + 1))
    return out


def explain_plan(plan: PhysicalPlan, depth: int = 0) -> list[str]:
    pad = "  " * depth
    name = type(plan).__name__
    if isinstance(plan, PhysTableRead):
        est = f" est={plan.est_rows:.0f}" if plan.est_rows is not None else ""
        line = f"{pad}TableRead[TiTPU]: {plan.dag.describe()}{est}"
    elif isinstance(plan, PhysPointGet):
        if plan.handles is not None:
            what = f"handles={plan.handles}"
        else:
            what = plan.ranges.describe()
        line = f"{pad}PointGet: {plan.table.name} {what}"
    elif isinstance(plan, PhysIndexMerge):
        parts = []
        for r in plan.branches:
            if r.index is None:
                parts.append(f"handle[{len(r.points)} pts]")
            else:
                parts.append(r.describe())
        est = f" est={plan.est_rows:.0f}" if plan.est_rows is not None else ""
        line = (f"{pad}IndexMerge(union): {plan.table.name} "
                f"{' | '.join(parts)}{est}")
    elif isinstance(plan, PhysHashAgg):
        line = (f"{pad}HashAgg({plan.mode}): groups={len(plan.group_by)} "
                f"aggs={plan.aggs}")
    elif isinstance(plan, PhysSelection):
        line = f"{pad}Selection: {plan.conditions}"
    elif isinstance(plan, PhysProjection):
        line = f"{pad}Projection: {plan.exprs}"
    elif isinstance(plan, PhysSort):
        line = f"{pad}Sort: {[(repr(e), d) for e, d in plan.items]}"
    elif isinstance(plan, PhysLimit):
        line = f"{pad}Limit: {plan.limit} offset {plan.offset}"
    elif isinstance(plan, PhysHashJoin):
        line = f"{pad}HashJoin({plan.kind}): eq={plan.eq_conditions}"
    elif isinstance(plan, PhysIndexJoin):
        line = (f"{pad}IndexJoin({plan.kind}): eq={plan.eq_conditions} "
                f"inner_offset={plan.inner_offset}")
    elif isinstance(plan, PhysMergeJoin):
        line = f"{pad}MergeJoin({plan.kind}): eq={plan.eq_conditions}"
    elif isinstance(plan, PhysUnion):
        line = f"{pad}Union: {len(plan.children)} children"
    elif isinstance(plan, PhysWindow):
        line = f"{pad}Window: {[it.func for it in plan.items]}"
    elif name == "PhysFragmentRead":
        line = f"{pad}FragmentRead[TiTPU]: {plan.frag.describe()}"
    else:
        line = f"{pad}{name}"
    out = [line]
    for c in plan.children:
        out.extend(explain_plan(c, depth + 1))
    return out
