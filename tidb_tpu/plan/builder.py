"""AST -> logical plan: name resolution, type inference, agg extraction.

Counterpart of the reference's logical plan builder (reference:
planner/core/logical_plan_builder.go + planbuilder.go — buildSelect,
buildAggregation, buildProjection, havingWindowAndOrderbyExprResolver).
Strict ONLY_FULL_GROUP_BY semantics: a non-aggregated column must appear in
GROUP BY.

Constant folding runs inline during resolution (reference:
expression/constant_fold.go) — required for plan-time temporal arithmetic
like `date '1998-12-01' - interval '90' day`.
"""

from __future__ import annotations

import datetime as _dt
from typing import Callable, Optional

from ..catalog.schema import Catalog, TableInfo
from ..sql import ast
from ..types.field_type import FieldType, TypeKind, boolean_type
from ..types.value import Decimal, decode_date, encode_date, parse_date, parse_datetime
from .expr import (
    AggDesc,
    Call,
    Col,
    Const,
    ExprError,
    PlanExpr,
    ScalarSubq,
    agg_result_type,
    arith_result_type,
    bool_call,
    comparable,
    is_numeric,
)
from .logical import (
    LogicalAggregation,
    LogicalJoin,
    LogicalLimit,
    LogicalPlan,
    LogicalProjection,
    LogicalScan,
    LogicalSelection,
    LogicalSort,
)
from .schema import PlanSchema, ResultField

_AGG_NAMES = {"COUNT", "SUM", "AVG", "MIN", "MAX",
              "GROUP_CONCAT", "STD", "STDDEV", "STDDEV_POP",
              "STDDEV_SAMP", "VARIANCE", "VAR_POP", "VAR_SAMP",
              "BIT_AND", "BIT_OR", "BIT_XOR", "ANY_VALUE",
              "APPROX_COUNT_DISTINCT", "APPROX_PERCENTILE",
              "JSON_ARRAYAGG", "JSON_OBJECTAGG"}

_ARITH_OPS = {"+": "add", "-": "sub", "*": "mul", "/": "div",
              "DIV": "intdiv", "%": "mod"}
_CMP_OPS = {"=": "eq", "<=>": "eq", "<>": "ne", "<": "lt", "<=": "le",
            ">": "gt", ">=": "ge"}
_CMP_SWAP = {"eq": "eq", "ne": "ne", "lt": "gt", "le": "ge", "gt": "lt",
             "ge": "le"}


from ..errno import ER_BAD_FIELD, CodedError
from ..errno import wrap as err_wrap


class PlanError(CodedError):
    """Planner error; name-resolution sites attach 1054/1146 etc."""


def ast_key(node: object) -> str:
    """Structural identity for AST expressions (group-by matching)."""
    return repr(node).lower()


def _coerce_date_arg(a: PlanExpr, fname: str) -> PlanExpr:
    """Date-bearing argument: DATE/DATETIME/TIMESTAMP columns pass
    through; string literals parse (reference: implicit temporal casts,
    types/convert.go). TIME is a duration, not a calendar point."""
    from ..types.field_type import TypeKind as _TK

    if a.ftype.is_string and isinstance(a, Const) and a.value is not None:
        from ..types.value import parse_date, parse_datetime
        s = str(a.value)
        try:
            if " " in s or "T" in s:
                return Const(parse_datetime(s),
                             FieldType(_TK.DATETIME))
            return Const(parse_date(s), FieldType(_TK.DATE))
        except ValueError:
            raise PlanError(
                f"invalid date literal {s!r} for {fname}") from None
    if a.ftype.kind in (_TK.DATE, _TK.DATETIME, _TK.TIMESTAMP):
        return a
    raise PlanError(f"{fname} requires a date argument")


def _parse_time_us(s: str) -> int:
    """'[-]HH:MM:SS[.ffffff]' -> signed microseconds (TIME domain)."""
    neg = s.startswith("-")
    body = s[1:] if neg else s
    parts = body.split(":")
    if len(parts) != 3:
        raise PlanError(f"invalid TIME literal {s!r}")
    try:
        h = int(parts[0])
        m = int(parts[1])
        sec = float(parts[2])
    except ValueError:
        raise PlanError(f"invalid TIME literal {s!r}") from None
    us = int(round((h * 3600 + m * 60 + sec) * 1_000_000))
    return -us if neg else us


class PlanBuilder:
    def __init__(self, catalog: Catalog, current_db: str = "test") -> None:
        self.catalog = catalog
        self.current_db = current_db
        self._hints: list[tuple[str, list[str]]] = []

    # ==================== SELECT ====================
    def build_select(self, stmt) -> LogicalPlan:
        if isinstance(stmt, ast.SetOpStmt):
            return self._build_set_op(stmt)
        # hint scope is per-SELECT: nested build_select calls (derived
        # tables, subqueries) must neither clobber the outer statement's
        # hints nor leak theirs outward
        prev_hints = self._hints
        self._hints = list(getattr(stmt, "hints", []) or [])
        try:
            return self._build_select_inner(stmt)
        finally:
            self._hints = prev_hints

    def _build_select_inner(self, stmt) -> LogicalPlan:
        if stmt.from_ is None:
            plan = self._build_dual(stmt)
        else:
            plan = self.build_table_refs(stmt.from_)
        # LEADING join-order hint travels on the plan for the reorder rule
        # (reference: hints.go HintLeading -> rule_join_reorder.go)
        for name, args in self._hints:
            if name == "LEADING" and args:
                plan._leading_hint = args  # type: ignore[attr-defined]

        if stmt.where is not None:
            plain, with_subq = [], []
            for c in _ast_conjuncts(stmt.where):
                (with_subq if _contains_subquery(c) else plain).append(c)
            conds: list[PlanExpr] = []
            for c in plain:
                conds.extend(self._split_conjuncts(
                    self.resolve(c, plan.schema)))
            if conds:
                plan = LogicalSelection(conds, plan.schema, [plan])
            for c in with_subq:
                plan = self._apply_subquery_conjunct(c, plan)

        has_agg = bool(stmt.group_by) or any(
            f.expr is not None and _contains_agg(f.expr) for f in stmt.fields
        ) or (stmt.having is not None and _contains_agg(stmt.having))

        if has_agg:
            plan = self._build_aggregate(stmt, plan)
        else:
            if stmt.having is not None:
                raise PlanError("HAVING without aggregation/group-by")
            if any(f.expr is not None and _contains_window(f.expr)
                   for f in stmt.fields):
                if stmt.from_ is None:
                    raise PlanError(
                        "window functions require a FROM clause")
                plan = self._build_windows(stmt, plan)
            plan = self._build_projection(stmt, plan)

        if stmt.distinct:
            plan = self._build_distinct(plan)

        if stmt.order_by:
            plan = self._build_sort(stmt, plan)

        if stmt.limit is not None or stmt.offset:
            limit = stmt.limit if stmt.limit is not None else 2**62
            plan = LogicalLimit(limit, stmt.offset, plan.schema, [plan])
        return plan

    def _build_set_op(self, stmt: ast.SetOpStmt) -> LogicalPlan:
        """Fold UNION [ALL] left to right; DISTINCT steps dedupe everything
        accumulated so far (MySQL cumulative-distinct semantics)."""
        from .logical import LogicalUnion

        plan = self.build_select(stmt.selects[0])
        for sel, is_all in zip(stmt.selects[1:], stmt.alls):
            right = self.build_select(sel)
            if len(right.schema) != len(plan.schema):
                raise PlanError(
                    "The used SELECT statements have a different number "
                    "of columns")
            fields = []
            for lf, rf in zip(plan.schema.fields, right.schema.fields):
                fields.append(ResultField(
                    lf.name, _union_ftype(lf.ftype, rf.ftype)))
            plan = LogicalUnion(PlanSchema(fields), [plan, right])
            if not is_all:
                plan = self._build_distinct(plan)
        if stmt.order_by:
            items = []
            for item in stmt.order_by:
                e = item.expr
                pe = None
                if isinstance(e, ast.Literal) and e.tag == "int":
                    k = int(e.value)
                    if not (1 <= k <= len(plan.schema)):
                        raise PlanError(
                            f"ORDER BY position {k} out of range")
                    pe = Col(k - 1, plan.schema.fields[k - 1].ftype)
                elif isinstance(e, ast.ColumnRef) and e.table is None:
                    idx = plan.schema.resolve(e.name)
                    if idx is not None:
                        pe = Col(idx, plan.schema.fields[idx].ftype, e.name)
                if pe is None:
                    raise PlanError(
                        "UNION ORDER BY must reference output columns")
                items.append((pe, item.desc))
            plan = LogicalSort(items, plan.schema, [plan])
        if stmt.limit is not None or stmt.offset:
            limit = stmt.limit if stmt.limit is not None else 2**62
            plan = LogicalLimit(limit, stmt.offset, plan.schema, [plan])
        return plan

    # ---- FROM -------------------------------------------------------------
    def build_table_refs(self, ref: ast.TableRef) -> LogicalPlan:
        if isinstance(ref, ast.TableName):
            return self._build_scan(ref)
        if isinstance(ref, ast.Join):
            return self._build_join(ref)
        if isinstance(ref, ast.SubqueryTable):
            sub = self.build_select(ref.query)
            alias = (ref.alias or "").lower()
            fields = [
                ResultField(f.name, f.ftype, alias) for f in sub.schema.fields
            ]
            sub.schema = PlanSchema(fields)
            return sub
        raise PlanError(f"unsupported table reference {type(ref).__name__}")

    def _build_scan(self, tn: ast.TableName):
        db = tn.db or self.current_db
        try:
            info = self.catalog.table(db, tn.name)
        except KeyError as e:
            view = self._lookup_view(db, tn.name)
            if view is not None:
                return self._expand_view(db, tn, view)
            raise err_wrap(PlanError, e) from None
        alias = (tn.alias or tn.name).lower()
        fields = [
            ResultField(c.name.lower(), c.ftype, alias, source_offset=c.offset)
            for c in info.columns
        ]
        scan = LogicalScan(info, alias, PlanSchema(fields))
        # USE_INDEX / IGNORE_INDEX hints pin this scan's access path
        # (reference: hints.go HintUseIndex -> access-path filtering,
        # planbuilder.go:933)
        for name, args in self._hints:
            if len(args) >= 1 and args[0] in (alias, tn.name.lower()):
                if name in ("USE_INDEX", "FORCE_INDEX"):
                    scan.hint_use_index = args[1:]  # type: ignore[attr-defined]
                elif name == "IGNORE_INDEX":
                    scan.hint_ignore_index = args[1:]  # type: ignore[attr-defined]
        return scan

    _VIEW_DEPTH_CAP = 16

    def _lookup_view(self, db: str, name: str):
        try:
            schema = self.catalog.schema(db)
        except KeyError:
            return None
        return getattr(schema, "views", {}).get(name.lower())

    def _expand_view(self, db: str, tn: ast.TableName, view) -> LogicalPlan:
        """Inline the view's stored SELECT as a derived table (reference:
        planner/core/logical_plan_builder.go BuildDataSourceFromView —
        the stored text re-parses against the CURRENT schema, so views
        track later DDL on their base tables)."""
        from ..sql.parser import parse_sql as _parse

        depth = getattr(self, "_view_depth", 0)
        if depth >= self._VIEW_DEPTH_CAP:
            raise PlanError(f"view nesting too deep at {view.name}")
        self._view_depth = depth + 1
        try:
            stmts = _parse(view.sql)
            sub = self.build_select(stmts[0])
        except Exception as e:
            if isinstance(e, PlanError):
                raise
            raise PlanError(
                f"view {view.name} is invalid: {e}") from None
        finally:
            self._view_depth = depth
        alias = (tn.alias or tn.name).lower()
        names = list(view.columns) if view.columns else [
            f.name for f in sub.schema.fields]
        if len(names) != len(sub.schema.fields):
            raise PlanError(f"view {view.name} column list mismatch")
        sub.schema = PlanSchema([
            ResultField(n.lower(), f.ftype, alias)
            for n, f in zip(names, sub.schema.fields)])
        return sub

    def _build_join(self, j: ast.Join) -> LogicalPlan:
        left = self.build_table_refs(j.left)
        right = self.build_table_refs(j.right)
        merged = PlanSchema(left.schema.fields + right.schema.fields)
        eq: list[tuple[int, int]] = []
        others: list[PlanExpr] = []
        nleft = len(left.schema)
        if j.using:
            for name in j.using:
                li = left.schema.resolve(name)
                ri = right.schema.resolve(name)
                if li is None or ri is None:
                    raise PlanError(f"USING column {name} not found on both sides")
                eq.append((li, ri))
        elif j.on is not None:
            for cond in self._split_conjuncts(self.resolve(j.on, merged)):
                pair = _as_equi_pair(cond, nleft)
                if pair is not None:
                    eq.append(pair)
                else:
                    others.append(cond)
        kind = j.kind if j.kind != "CROSS" else "INNER"
        if j.kind == "CROSS" and not eq and not others:
            kind = "CROSS"
        return LogicalJoin(kind, eq, others, merged, [left, right])

    # ---- subqueries --------------------------------------------------------
    #
    # The reference rewrites subqueries during logical planning
    # (planner/core/expression_rewriter.go + rule_decorrelate.go). We keep
    # the same playbook, specialized to the decision-support shapes:
    #   EXISTS / NOT EXISTS  -> SEMI / ANTI hash join (correlation becomes
    #                           join keys; non-equality correlation becomes
    #                           residual join conditions)
    #   x IN (sub)           -> SEMI join;  x NOT IN (sub) -> null-aware ANTI
    #   col CMP (corr. agg)  -> group the subquery by its correlation keys,
    #                           INNER join on them, filter CMP (Q2/Q17/Q20)
    #   uncorrelated scalar  -> ScalarSubq, materialized once at execution

    def _apply_subquery_conjunct(
        self, c: ast.Expr, plan: LogicalPlan
    ) -> LogicalPlan:
        neg = False
        node = c
        while isinstance(node, ast.UnaryOp) and node.op == "NOT":
            neg = not neg
            node = node.operand
        if isinstance(node, ast.SubqueryExpr) and node.exists:
            return self._build_exists(node.query, plan,
                                      anti=neg != node.negated)
        if isinstance(node, ast.InSubquery):
            return self._build_in_subquery(node, plan, negate=neg)
        if isinstance(node, ast.BinaryOp) and node.op in (
                "=", "<>", "!=", "<", "<=", ">", ">="):
            for lhs, sub, flip in ((node.left, node.right, False),
                                   (node.right, node.left, True)):
                if isinstance(sub, ast.SubqueryExpr) and not sub.exists \
                        and not _contains_subquery(lhs):
                    op = _flip_cmp(node.op) if flip else node.op
                    out = self._build_scalar_cmp(lhs, op, sub.query, plan)
                    if neg:
                        # NOT (a CMP b): wrap the appended selection
                        sel = out
                        assert isinstance(sel, LogicalSelection)
                        sel.conditions = [
                            bool_call("not", [_coerce_bool(x)])
                            for x in sel.conditions]
                    return out
        # fallback: resolve in place (uncorrelated subqueries become
        # ScalarSubq consts; correlated ones raise)
        conds = self._split_conjuncts(self.resolve(c, plan.schema))
        return LogicalSelection(conds, plan.schema, [plan])

    def _build_sub_source(
        self, sub: ast.SelectStmt, outer: PlanSchema
    ) -> tuple[LogicalPlan, list[tuple[int, int]], list[PlanExpr]]:
        """Build sub's FROM + WHERE with correlation split out.

        Returns (sub plan, eq pairs (outer_idx, sub_idx), residual
        conditions over the concatenated outer++sub schema)."""
        if sub.from_ is None:
            raise PlanError("correlated subquery needs a FROM clause")
        splan = self.build_table_refs(sub.from_)
        local: list[PlanExpr] = []
        eq_pairs: list[tuple[int, int]] = []
        residual: list[PlanExpr] = []
        nouter = len(outer)

        def r_scoped(node: ast.Expr) -> PlanExpr:
            # SQL scoping: the subquery's own tables shadow outer tables;
            # indices land in the concatenated outer++sub space
            if isinstance(node, ast.ColumnRef):
                idx = splan.schema.resolve(node.name, node.table)
                if idx is not None:
                    return Col(nouter + idx, splan.schema.fields[idx].ftype,
                               str(node))
                idx = outer.resolve(node.name, node.table)
                if idx is None:
                    raise PlanError(f"unknown column {node}",
                                    errno=ER_BAD_FIELD)
                return Col(idx, outer.fields[idx].ftype, str(node))
            return self._resolve_composite(node, r_scoped)

        if sub.where is not None:
            for conj in _ast_conjuncts(sub.where):
                if _contains_subquery(conj):
                    # nested subquery inside a correlated one: only the
                    # uncorrelated form is supported (resolved in place)
                    splan = self._apply_subquery_conjunct(conj, splan)
                    continue
                try:
                    local.extend(self._split_conjuncts(
                        self.resolve(conj, splan.schema)))
                    continue
                except PlanError:
                    pass
                e = r_scoped(conj)  # raises if truly unknown
                pair = _as_equi_pair(e, nouter)
                if pair is not None:
                    eq_pairs.append(pair)
                else:
                    residual.append(e)
        if local:
            splan = LogicalSelection(local, splan.schema, [splan])
        return splan, eq_pairs, residual

    def _build_exists(
        self, sub: ast.SelectStmt, plan: LogicalPlan, anti: bool
    ) -> LogicalPlan:
        # EXISTS truth depends only on row existence in FROM+WHERE.
        # LIMIT k>=1 does not change existence — drop it (the common
        # EXISTS(... LIMIT 1) idiom); LIMIT 0 yields no rows, so EXISTS
        # is constant FALSE. An UNgrouped aggregate always yields exactly
        # one row, so EXISTS is constant TRUE (reference:
        # rule_decorrelate.go handles these as trivial cases).
        if sub.limit == 0:
            const = Const(1 if anti else 0, FieldType(TypeKind.BOOLEAN))
            return LogicalSelection([const], plan.schema, [plan])
        if sub.limit is not None and sub.limit >= 1 and not sub.offset:
            import dataclasses
            sub = dataclasses.replace(sub, limit=None)
        has_agg = any(f.expr is not None and _contains_agg(f.expr)
                      for f in sub.fields)
        if has_agg and not sub.group_by and sub.having is None and \
                sub.limit is None and not sub.offset:
            # still VALIDATE the subquery (names, correlation) before
            # constant-folding it away
            splan, _eq, _res = self._build_sub_source(sub, plan.schema)
            comb = PlanSchema(plan.schema.fields + splan.schema.fields)
            try:
                for f in sub.fields:
                    if f.expr is None:
                        continue
                    for call in _find_aggs(f.expr):
                        if call.args and not call.is_star:
                            # inner scope shadows outer (SQL resolution)
                            try:
                                self.resolve(call.args[0], splan.schema)
                            except (PlanError, KeyError):
                                self.resolve(call.args[0], comb)
            except KeyError as e:
                raise err_wrap(PlanError, e) from None
            const = Const(0 if anti else 1, FieldType(TypeKind.BOOLEAN))
            return LogicalSelection([const], plan.schema, [plan])
        if sub.group_by or sub.having or sub.limit is not None or \
                sub.offset or has_agg:
            raise PlanError("EXISTS subquery with aggregation/HAVING/"
                            "LIMIT/OFFSET is not supported")
        splan, eq_pairs, residual = self._build_sub_source(sub, plan.schema)
        # remap residuals: outer indices stay, sub indices shift to
        # len(plan.schema) .. (they were resolved over outer++sub already)
        kind = "ANTI" if anti else "SEMI"
        return LogicalJoin(kind, eq_pairs, residual, plan.schema,
                           [plan, splan])

    def _build_in_subquery(
        self, node: ast.InSubquery, plan: LogicalPlan, negate: bool
    ) -> LogicalPlan:
        lhs = self.resolve(node.operand, plan.schema)
        if not isinstance(lhs, Col):
            raise PlanError("IN (subquery) requires a column operand")
        anti = negate != node.negated
        try:
            sub = self.build_select(node.query)
        except PlanError as e:
            # correlated IN: the subquery references outer columns —
            # recognizable as an unresolved-column error. Anything else
            # is a genuine error; re-raise it undisguised.
            # x IN (SELECT y FROM ... WHERE corr) decorrelates to a SEMI
            # join carrying both the correlation and the x = y equality
            # (reference: rule_decorrelate.go pulls the correlated
            # conditions into the semi join). NOT IN needs null-aware
            # anti semantics; with a correlated body we support it only
            # when both compared columns are non-nullable.
            if "unknown column" not in str(e).lower():
                raise
            return self._build_corr_in(node, plan, lhs, anti)
        if len(sub.schema) != 1:
            raise PlanError("IN subquery must return exactly one column")
        kind = "ANTI_NULL" if anti else "SEMI"
        return LogicalJoin(kind, [(lhs.idx, 0)], [], plan.schema,
                           [plan, sub])

    def _build_corr_in(self, node: ast.InSubquery, plan: LogicalPlan,
                       lhs: Col, anti: bool) -> LogicalPlan:
        sub = node.query
        if sub.group_by or sub.having or sub.limit is not None or \
                len(sub.fields) != 1 or sub.fields[0].expr is None or \
                _contains_agg(sub.fields[0].expr):
            raise PlanError("correlated IN subquery must be a bare "
                            "single-column SELECT")
        splan, eq_pairs, residual = self._build_sub_source(
            sub, plan.schema)
        # inner scope shadows outer for the selected column (SQL name
        # resolution); fall back to the combined space for qualified refs
        try:
            rhs_local = self.resolve(sub.fields[0].expr, splan.schema)
            rhs = Col(rhs_local.idx + len(plan.schema),
                      rhs_local.ftype) \
                if isinstance(rhs_local, Col) else None
        except (PlanError, KeyError):
            rhs = None
        if rhs is None:
            try:
                rhs = self.resolve(
                    sub.fields[0].expr,
                    PlanSchema(plan.schema.fields + splan.schema.fields))
            except KeyError as e:
                raise err_wrap(PlanError, e) from None
        if not isinstance(rhs, Col) or rhs.idx < len(plan.schema):
            raise PlanError("correlated IN subquery selects a non-column")
        if anti and (lhs.ftype.nullable or rhs.ftype.nullable):
            raise PlanError(
                "correlated NOT IN over nullable columns is not "
                "supported (null-aware anti join)")
        kind = "ANTI" if anti else "SEMI"
        eq_pairs = list(eq_pairs) + [(lhs.idx,
                                      rhs.idx - len(plan.schema))]
        return LogicalJoin(kind, eq_pairs, residual, plan.schema,
                           [plan, splan])

    def _build_scalar_cmp(
        self, lhs_ast: ast.Expr, op: str, sub: ast.SelectStmt,
        plan: LogicalPlan
    ) -> LogicalPlan:
        """col CMP (SELECT agg ... WHERE inner.k = outer.k ...) — the
        correlated-aggregate pattern (Q2/Q17/Q20)."""
        try:
            # uncorrelated scalar subquery: plain selection w/ ScalarSubq
            cond = self.resolve(
                ast.BinaryOp(op, lhs_ast, ast.SubqueryExpr(sub)), plan.schema)
            return LogicalSelection(self._split_conjuncts(cond), plan.schema,
                                    [plan])
        except PlanError:
            pass
        splan, eq_pairs, residual = self._build_sub_source(sub, plan.schema)
        if residual:
            raise PlanError(
                "correlated scalar subquery supports only equality "
                "correlation")
        if not eq_pairs:
            raise PlanError("correlated scalar subquery: no correlation "
                            "keys found")
        if len(sub.fields) != 1 or sub.fields[0].expr is None:
            raise PlanError("scalar subquery must select exactly one "
                            "expression")
        if sub.group_by or sub.having or sub.order_by or sub.limit:
            raise PlanError("correlated scalar subquery must be a bare "
                            "aggregate")
        nouter = len(plan.schema)
        # group the subquery by its correlation columns (sub-relative idx)
        group_cols = [Col(s, splan.schema.fields[s].ftype)
                      for _, s in eq_pairs]
        field_expr = sub.fields[0].expr
        aggs: list[AggDesc] = []
        agg_keys: dict[str, int] = {}
        for call in _find_aggs(field_expr):
            key = ast_key(call)
            if key in agg_keys:
                continue
            func = call.name.lower()
            if func not in ("sum", "min", "max", "avg", "count"):
                raise PlanError(f"unsupported aggregate {func} in "
                                "correlated subquery")
            arg = None if call.is_star else self.resolve(
                call.args[0], splan.schema)
            agg_keys[key] = len(aggs)
            aggs.append(AggDesc(func, arg, agg_result_type(func, arg),
                                call.distinct, name=key))
        if not aggs:
            raise PlanError("correlated scalar subquery must aggregate")
        ngroup = len(group_cols)
        agg_fields = [ResultField(f"#corr_k{i}", g.ftype, "#subq")
                      for i, g in enumerate(group_cols)]
        agg_fields += [ResultField(f"#corr_a{i}", d.ftype, "#subq")
                       for i, d in enumerate(aggs)]
        agg_plan = LogicalAggregation(
            list(group_cols), aggs, PlanSchema(agg_fields), [splan])

        # scalar-of-aggregate expression over the agg schema (e.g. 0.2*avg)
        def r_over(e: ast.Expr) -> PlanExpr:
            key = ast_key(e)
            if key in agg_keys:
                i = ngroup + agg_keys[key]
                return Col(i, agg_plan.schema.fields[i].ftype)
            if isinstance(e, ast.ColumnRef):
                raise PlanError(
                    f"column {e} not allowed in correlated scalar subquery")
            return self._resolve_composite(e, r_over)

        value = r_over(field_expr)
        proj_fields = [ResultField(f"#corr_k{i}", g.ftype, "#subq")
                       for i, g in enumerate(group_cols)]
        proj_fields.append(ResultField("#corr_v", value.ftype, "#subq"))
        proj = LogicalProjection(
            [Col(i, g.ftype) for i, g in enumerate(group_cols)] + [value],
            PlanSchema(proj_fields), [agg_plan])

        # LEFT join outer plan to the grouped subquery on correlation keys:
        # an outer row with no group sees NULL (scalar subquery over an
        # empty set), except COUNT which must see 0 (hence the ifnull)
        join_schema = PlanSchema(plan.schema.fields + proj_fields)
        join = LogicalJoin(
            "LEFT", [(o, i) for i, (o, _) in enumerate(eq_pairs)], [],
            join_schema, [plan, proj])
        lhs = self.resolve(lhs_ast, plan.schema)  # outer indices unchanged
        vcol: PlanExpr = Col(nouter + ngroup, value.ftype, "#corr_v")
        if isinstance(field_expr, ast.FuncCall) and \
                field_expr.name.upper() == "COUNT":
            vcol = Call("ifnull", [vcol, Const(0, vcol.ftype)], vcol.ftype)
        tag = {"=": "eq", "<>": "ne", "!=": "ne", "<": "lt", "<=": "le",
               ">": "gt", ">=": "ge"}[op]
        cond = self._resolve_cmp(tag, lhs, vcol)
        return LogicalSelection([cond], join_schema, [join])

    def _build_dual(self, stmt: ast.SelectStmt) -> LogicalPlan:
        """SELECT without FROM: a one-row, zero-column pseudo scan."""
        return LogicalScan(
            TableInfo(id=-1, name="dual", columns=[]), "dual", PlanSchema([])
        )

    # ---- projection / aggregation -----------------------------------------
    def _expand_fields(
        self, stmt: ast.SelectStmt, child_schema: PlanSchema
    ) -> list[tuple[ast.Expr, Optional[str]]]:
        """Expand wildcards into (expr, alias) pairs."""
        out: list[tuple[ast.Expr, Optional[str]]] = []
        for f in stmt.fields:
            if f.expr is not None:
                out.append((f.expr, f.alias))
                continue
            for rf in child_schema.fields:
                if f.wildcard_table and rf.table_alias != f.wildcard_table.lower():
                    continue
                if rf.name.startswith("#"):
                    continue  # hidden columns from subquery decorrelation
                out.append((ast.ColumnRef(rf.name, table=rf.table_alias or None),
                            None))
            if not out:
                raise PlanError("wildcard expanded to no columns")
        return out

    _WINDOW_ONLY = {"ROW_NUMBER", "RANK", "DENSE_RANK", "LEAD", "LAG",
                    "FIRST_VALUE", "LAST_VALUE", "NTH_VALUE", "NTILE",
                    "PERCENT_RANK", "CUME_DIST"}

    def _build_windows(self, stmt: ast.SelectStmt,
                       child: LogicalPlan) -> LogicalPlan:
        """Plan window computations between the row source and the final
        projection (reference: planner/core buildWindowFunctions;
        executor/window.go). Each distinct windowed call appends one
        "__win#i" column; the select fields are rewritten to reference it.
        Default frames only."""
        from .logical import LogicalWindow, WindowItem

        schema = child.schema
        items: list[WindowItem] = []
        keys: dict[str, int] = {}
        for f in stmt.fields:
            if f.expr is None:
                continue
            for call in _find_windows(f.expr):
                k = ast_key(call)
                if k in keys:
                    continue
                name = call.name
                args = [self.resolve(a, schema) for a in call.args]
                if name in ("ROW_NUMBER", "RANK", "DENSE_RANK"):
                    if args:
                        raise PlanError(f"{name}() takes no arguments")
                    ftype = FieldType(TypeKind.BIGINT, nullable=False)
                elif name in ("LEAD", "LAG"):
                    if not 1 <= len(args) <= 3:
                        raise PlanError(f"{name} takes 1-3 arguments")
                    if args[0].ftype.is_string and \
                            not isinstance(args[0], Col):
                        raise PlanError(
                            f"{name} over computed strings unsupported")
                    ftype = FieldType(args[0].ftype.kind,
                                      flen=args[0].ftype.flen,
                                      scale=args[0].ftype.scale)
                elif name in ("FIRST_VALUE", "LAST_VALUE", "NTH_VALUE"):
                    want = 2 if name == "NTH_VALUE" else 1
                    if len(args) != want:
                        raise PlanError(f"{name} takes {want} argument(s)")
                    if args[0].ftype.is_string and \
                            not isinstance(args[0], Col):
                        raise PlanError(
                            f"{name} over computed strings unsupported")
                    ftype = FieldType(args[0].ftype.kind,
                                      flen=args[0].ftype.flen,
                                      scale=args[0].ftype.scale)
                elif name == "NTILE":
                    if len(args) != 1:
                        raise PlanError("NTILE takes one argument")
                    ftype = FieldType(TypeKind.BIGINT)
                elif name in ("PERCENT_RANK", "CUME_DIST"):
                    if args:
                        raise PlanError(f"{name}() takes no arguments")
                    ftype = FieldType(TypeKind.DOUBLE, nullable=False)
                elif name.upper() in _AGG_NAMES:
                    if call.distinct:
                        # MySQL: DISTINCT is not allowed in window aggs
                        raise PlanError(
                            f"DISTINCT in window aggregate {name}")
                    if call.is_star:
                        args = []
                    elif len(args) != 1:
                        raise PlanError(f"{name} takes one argument")
                    if args and args[0].ftype.is_string and \
                            name.upper() != "COUNT":
                        raise PlanError(
                            f"window {name} over strings unsupported")
                    ftype = agg_result_type(
                        name.lower(), args[0] if args else None)
                else:
                    raise PlanError(f"unsupported window function {name}")
                spec = call.window
                part = [self.resolve(e, schema)
                        for e in spec.partition_by]
                order = [(self.resolve(it.expr, schema), it.desc)
                         for it in spec.order_by]
                frame = spec.frame
                if frame is not None:
                    # MySQL semantics: ranking funcs ignore the frame
                    if name in ("ROW_NUMBER", "RANK", "DENSE_RANK",
                                "NTILE", "PERCENT_RANK", "CUME_DIST",
                                "LEAD", "LAG"):
                        frame = None
                    elif frame.unit == "RANGE" and (
                            frame.start_value is not None
                            or frame.end_value is not None):
                        # value-offset RANGE needs exactly one numeric
                        # ORDER BY key (reference: MySQL 3593 checks)
                        if len(order) != 1 or order[0][0].ftype.is_string:
                            raise PlanError(
                                "RANGE frame with offset requires a "
                                "single numeric ORDER BY expression")
                keys[k] = len(items)
                items.append(WindowItem(name, args, part, order, ftype,
                                        frame))
        if not items:
            return child
        fields = list(schema.fields) + [
            ResultField(f"__win#{i}", it.ftype)
            for i, it in enumerate(items)
        ]
        wplan = LogicalWindow(items, PlanSchema(fields), [child])
        # rewrite the select fields: windowed calls -> __win#i refs
        wmap = {k: ast.ColumnRef(f"__win#{i}") for k, i in keys.items()}
        stmt.fields = [
            ast.SelectField(
                None if f.expr is None else _replace_windows(f.expr, wmap),
                f.alias, f.wildcard_table)
            for f in stmt.fields
        ]
        return wplan

    def _build_projection(
        self, stmt: ast.SelectStmt, child: LogicalPlan
    ) -> LogicalProjection:
        pairs = self._expand_fields(stmt, child.schema)
        exprs: list[PlanExpr] = []
        fields: list[ResultField] = []
        for e, alias in pairs:
            pe = self.resolve(e, child.schema)
            exprs.append(pe)
            fields.append(ResultField(_output_name(e, alias), pe.ftype))
        return LogicalProjection(exprs, PlanSchema(fields), [child])

    def _build_aggregate(
        self, stmt: ast.SelectStmt, child: LogicalPlan
    ) -> LogicalPlan:
        child_schema = child.schema
        # 1. resolve group-by expressions (positional ints and aliases allowed)
        pairs = self._expand_fields(stmt, child_schema)
        group_ast: list[ast.Expr] = []
        for g in stmt.group_by:
            if isinstance(g, ast.Literal) and g.tag == "int":
                k = int(g.value)
                if not (1 <= k <= len(pairs)):
                    raise PlanError(f"GROUP BY position {k} out of range")
                group_ast.append(pairs[k - 1][0])
            elif isinstance(g, ast.ColumnRef) and g.table is None and any(
                alias and alias.lower() == g.name.lower() for _, alias in pairs
            ):
                idx = next(i for i, (_, a) in enumerate(pairs)
                           if a and a.lower() == g.name.lower())
                group_ast.append(pairs[idx][0])
            else:
                group_ast.append(g)
        group_exprs = [self.resolve(g, child_schema) for g in group_ast]
        group_keys = [ast_key(g) for g in group_ast]

        # 2. collect aggregate descriptors across select/having/order exprs
        aggs: list[AggDesc] = []
        agg_keys: dict[str, int] = {}

        def collect(e: ast.Expr) -> None:
            for call in _find_aggs(e):
                key = ast_key(call)
                if key in agg_keys:
                    continue
                func = call.name.lower()
                params: tuple = ()
                if func == "json_objectagg" and len(call.args) != 2:
                    raise PlanError(
                        "Incorrect parameter count in the call to "
                        "native function 'json_objectagg'")
                if call.is_star:
                    arg = None
                elif func != "json_objectagg" and len(call.args) == 1:
                    arg = self.resolve(call.args[0], child_schema)
                elif func == "json_objectagg" and len(call.args) == 2:
                    # two-arg aggregate: pack (key, value) as a synthetic
                    # Call so pruning/remap walk both expressions; the
                    # engine evaluates the parts, never the call itself
                    k = self.resolve(call.args[0], child_schema)
                    v = self.resolve(call.args[1], child_schema)
                    arg = Call("json_kv", [k, v],
                               FieldType(TypeKind.JSON))
                elif func == "approx_percentile" and len(call.args) == 2:
                    # APPROX_PERCENTILE(expr, percent): percent must be a
                    # constant 1..100 (reference: builder.go:110)
                    arg = self.resolve(call.args[0], child_schema)
                    if arg.ftype.is_string:
                        raise PlanError(
                            "APPROX_PERCENTILE requires a numeric or "
                            "temporal argument")
                    p = self.resolve(call.args[1], child_schema)
                    if not isinstance(p, Const):
                        raise PlanError(
                            "APPROX_PERCENTILE percent must be constant")
                    try:
                        pv = float(p.value)
                    except (TypeError, ValueError):
                        raise PlanError(
                            "Percentage value 0-100 required") from None
                    if not 0 < pv <= 100:
                        raise PlanError(
                            "Percentage value 0-100 required")
                    params = (pv,)
                else:
                    raise PlanError(f"{call.name} takes one argument")
                if func != "count" and arg is None:
                    raise PlanError(f"{call.name}(*) is not valid")
                desc = AggDesc(func, arg, agg_result_type(func, arg),
                               call.distinct, name=key, params=params)
                agg_keys[key] = len(aggs)
                aggs.append(desc)

        for e, _ in pairs:
            collect(e)
        if stmt.having is not None:
            collect(stmt.having)
        for item in stmt.order_by:
            collect(item.expr)
        if not aggs and not group_exprs:
            raise PlanError("aggregation without aggregates or group by")

        # 3. agg node schema: [group cols..., agg results...]
        agg_fields = []
        for i, (g, ga) in enumerate(zip(group_exprs, group_ast)):
            name = ga.name.lower() if isinstance(ga, ast.ColumnRef) else f"group#{i}"
            tbl = (ga.table or "").lower() if isinstance(ga, ast.ColumnRef) else ""
            agg_fields.append(ResultField(name, g.ftype, tbl))
        for i, d in enumerate(aggs):
            agg_fields.append(ResultField(f"agg#{i}", d.ftype))
        agg_plan = LogicalAggregation(
            group_exprs, aggs, PlanSchema(agg_fields), [child]
        )

        # 4. projection over agg output: replace agg calls / group exprs
        ngroups = len(group_exprs)

        def resolve_over_agg(e: ast.Expr) -> PlanExpr:
            key = ast_key(e)
            if key in agg_keys:
                i = ngroups + agg_keys[key]
                return Col(i, agg_plan.schema.fields[i].ftype,
                           repr(aggs[agg_keys[key]]))
            for gi, gkey in enumerate(group_keys):
                if key == gkey:
                    return Col(gi, group_exprs[gi].ftype,
                               agg_plan.schema.fields[gi].name)
            if isinstance(e, ast.ColumnRef):
                idx = agg_plan.schema.resolve(e.name, e.table)
                if idx is not None and idx < ngroups:
                    return Col(idx, agg_plan.schema.fields[idx].ftype, e.name)
                if e.table is None:
                    # select-field alias (MySQL allows these in HAVING/ORDER)
                    for fe, alias in pairs:
                        if alias and alias.lower() == e.name.lower():
                            return resolve_over_agg(fe)
                raise PlanError(
                    f"column {e} must appear in GROUP BY or an aggregate"
                )
            return self._resolve_composite(e, resolve_over_agg)

        exprs = []
        fields = []
        for e, alias in pairs:
            pe = resolve_over_agg(e)
            exprs.append(pe)
            fields.append(ResultField(_output_name(e, alias), pe.ftype))
        plan: LogicalPlan = LogicalProjection(exprs, PlanSchema(fields), [agg_plan])

        # 5. HAVING: filter between agg and projection (resolved in agg scope)
        if stmt.having is not None:
            cond = resolve_over_agg(stmt.having)
            # insert selection under the projection
            sel = LogicalSelection(
                self._split_conjuncts(cond), agg_plan.schema, [agg_plan]
            )
            plan.children[0] = sel
        # stash for order-by resolution
        plan._agg_resolver = resolve_over_agg  # type: ignore[attr-defined]
        return plan

    def _build_distinct(self, child: LogicalPlan) -> LogicalPlan:
        """DISTINCT = group by every output column (reference lowers it the
        same way, planner/core/logical_plan_builder.go buildDistinct)."""
        group = [
            Col(i, f.ftype, f.name) for i, f in enumerate(child.schema.fields)
        ]
        return LogicalAggregation(group, [], child.schema, [child])

    def _build_sort(self, stmt: ast.SelectStmt, plan: LogicalPlan) -> LogicalPlan:
        out_schema = plan.schema
        resolver: Optional[Callable] = getattr(plan, "_agg_resolver", None)
        proj = plan if isinstance(plan, LogicalProjection) else None
        items: list[tuple[PlanExpr, bool]] = []
        hidden: list[PlanExpr] = []  # appended projection cols for sort-only refs
        for item in stmt.order_by:
            e = item.expr
            pe: Optional[PlanExpr] = None
            if isinstance(e, ast.Literal) and e.tag == "int":
                k = int(e.value)
                if not (1 <= k <= len(out_schema)):
                    raise PlanError(f"ORDER BY position {k} out of range")
                pe = Col(k - 1, out_schema.fields[k - 1].ftype)
            elif isinstance(e, ast.ColumnRef) and e.table is None:
                idx = out_schema.resolve(e.name)
                if idx is not None:
                    pe = Col(idx, out_schema.fields[idx].ftype, e.name)
            if pe is None and proj is not None:
                # match select expressions structurally
                key = ast_key(e)
                pairs = self._expand_fields(stmt, proj.children[0].schema) \
                    if resolver is None else None
                if pairs is not None:
                    for i, (fe, _) in enumerate(pairs):
                        if ast_key(fe) == key:
                            pe = Col(i, out_schema.fields[i].ftype)
                            break
            if pe is None:
                if resolver is not None:
                    under = resolver(e)
                    # add as hidden projection column
                    assert proj is not None
                    proj.exprs.append(under)
                    hid_idx = len(proj.schema.fields)
                    proj.schema.fields.append(
                        ResultField(f"__sort#{len(hidden)}", under.ftype)
                    )
                    pe = Col(hid_idx, under.ftype)
                    hidden.append(under)
                elif proj is not None:
                    under = self.resolve(e, proj.children[0].schema)
                    proj.exprs.append(under)
                    hid_idx = len(proj.schema.fields)
                    proj.schema.fields.append(
                        ResultField(f"__sort#{len(hidden)}", under.ftype)
                    )
                    pe = Col(hid_idx, under.ftype)
                    hidden.append(under)
                else:
                    pe = self.resolve(e, out_schema)
            items.append((pe, item.desc))
        sort = LogicalSort(items, plan.schema, [plan])
        if hidden:
            # visible width shrinks back after sort via a trimming projection
            vis = len(plan.schema.fields) - len(hidden)
            exprs = [Col(i, plan.schema.fields[i].ftype) for i in range(vis)]
            trim_schema = PlanSchema(plan.schema.fields[:vis])
            return LogicalProjection(exprs, trim_schema, [sort])
        return sort

    # ==================== expression resolution ====================
    def resolve(self, e: ast.Expr, schema: PlanSchema) -> PlanExpr:
        def r(node: ast.Expr) -> PlanExpr:
            if isinstance(node, ast.ColumnRef):
                idx = schema.resolve(node.name, node.table)
                if idx is None:
                    raise PlanError(f"unknown column {node}",
                                    errno=ER_BAD_FIELD)
                return Col(idx, schema.fields[idx].ftype, str(node))
            return self._resolve_composite(node, r)

        return r(e)

    def _resolve_composite(
        self, node: ast.Expr, r: Callable[[ast.Expr], PlanExpr]
    ) -> PlanExpr:
        """Resolve every non-ColumnRef node, delegating children to r."""
        if isinstance(node, ast.Literal):
            return _literal_const(node)
        if isinstance(node, ast.BinaryOp):
            return self._resolve_binary(node, r)
        if isinstance(node, ast.UnaryOp):
            if node.op == "NOT":
                arg = _coerce_bool(r(node.operand))
                return bool_call("not", [arg])
            arg = r(node.operand)
            if not is_numeric(arg.ftype):
                raise PlanError(f"unary - over {arg.ftype!r}")
            return _fold(Call("neg", [arg], arg.ftype))
        if isinstance(node, ast.IsNull):
            arg = r(node.operand)
            out = bool_call("isnull", [arg])
            return bool_call("not", [out]) if node.negated else out
        if isinstance(node, ast.Between):
            lo = self._resolve_cmp("ge", r(node.operand), r(node.low))
            hi = self._resolve_cmp("le", r(node.operand), r(node.high))
            out = bool_call("and", [lo, hi])
            return bool_call("not", [out]) if node.negated else out
        if isinstance(node, ast.InList):
            arg = r(node.operand)
            items = [r(i) for i in node.items]
            if not all(isinstance(i, Const) for i in items):
                # general IN lowers to OR of equalities
                out: PlanExpr = self._resolve_cmp("eq", arg, items[0])
                for it in items[1:]:
                    out = bool_call("or", [out, self._resolve_cmp("eq", arg, it)])
            else:
                consts = [self._coerce_const(c, arg.ftype) for c in items]
                if arg.ftype.is_decimal:
                    # values whose scale exceeds the column's can never
                    # equal a stored value — drop them (exact semantics)
                    consts = [
                        c for c in consts
                        if not (c.ftype.is_decimal
                                and c.ftype.scale > arg.ftype.scale)
                    ]  # empty list => never matches (both evaluators)
                out = bool_call("in_values", [arg],
                                extra=[c.value for c in consts])
            return bool_call("not", [out]) if node.negated else out
        if isinstance(node, ast.Like):
            arg = r(node.operand)
            if not arg.ftype.is_string:
                raise PlanError("LIKE requires a string operand")
            pat = r(node.pattern)
            if not isinstance(pat, Const):
                raise PlanError("LIKE pattern must be a constant")
            out = bool_call("like", [arg], extra=str(pat.value))
            return bool_call("not", [out]) if node.negated else out
        if isinstance(node, ast.FuncCall):
            if node.name in _AGG_NAMES:
                raise PlanError(f"aggregate {node.name} not allowed here")
            return self._resolve_scalar_func(node, r)
        if isinstance(node, ast.Case):
            return self._resolve_case(node, r)
        if isinstance(node, ast.Cast):
            arg = r(node.operand)
            return _fold(Call("cast", [arg], node.target))
        if isinstance(node, ast.IntervalExpr):
            raise PlanError("INTERVAL only valid in +/- date arithmetic")
        if isinstance(node, ast.SubqueryExpr):
            if node.exists:
                raise PlanError("EXISTS is only valid as a WHERE condition")
            sub = self.build_select(node.query)  # raises if correlated
            if len(sub.schema) != 1:
                raise PlanError("scalar subquery must return one column")
            return ScalarSubq(sub, sub.schema.fields[0].ftype)
        if isinstance(node, ast.InSubquery):
            raise PlanError("IN (subquery) is only valid as a WHERE "
                            "condition")
        raise PlanError(f"unsupported expression {type(node).__name__}")

    def _resolve_binary(
        self, node: ast.BinaryOp, r: Callable[[ast.Expr], PlanExpr]
    ) -> PlanExpr:
        op = node.op
        if op in ("AND", "OR"):
            left = _coerce_bool(r(node.left))
            right = _coerce_bool(r(node.right))
            return _fold(bool_call(op.lower(), [left, right]))
        if op in ("XOR",):
            left = _coerce_bool(r(node.left))
            right = _coerce_bool(r(node.right))
            return _fold(bool_call("ne", [left, right]))
        if op in _CMP_OPS:
            return self._resolve_cmp(_CMP_OPS[op], r(node.left), r(node.right))
        if op in _ARITH_OPS:
            # interval arithmetic on dates
            if isinstance(node.right, ast.IntervalExpr) and op in ("+", "-"):
                return self._resolve_date_arith(r(node.left), node.right, op, r)
            if isinstance(node.left, ast.IntervalExpr) and op == "+":
                return self._resolve_date_arith(r(node.right), node.left, op, r)
            a, b = r(node.left), r(node.right)
            tag = _ARITH_OPS[op]
            try:
                ftype = arith_result_type(tag, a.ftype, b.ftype)
            except ExprError as e:
                raise err_wrap(PlanError, e) from None
            return _fold(Call(tag, [a, b], ftype))
        raise PlanError(f"unsupported operator {op}")

    def _resolve_cmp(self, tag: str, a: PlanExpr, b: PlanExpr) -> PlanExpr:
        # constant-side coercion: string consts vs temporal/decimal columns
        if isinstance(b, Const) and not isinstance(a, Const):
            b = self._coerce_const(b, a.ftype)
        elif isinstance(a, Const) and not isinstance(b, Const):
            a = self._coerce_const(a, b.ftype)
            a, b = b, a
            tag = _CMP_SWAP[tag]
        if not comparable(a.ftype, b.ftype):
            raise PlanError(f"incomparable types {a.ftype!r} vs {b.ftype!r}")
        return _fold(bool_call(tag, [a, b]))

    def _coerce_const(self, c: Const, target: FieldType) -> Const:
        """Fold a literal into the physical domain of the other operand."""
        if c.value is None:
            return Const(None, target)
        if target.kind == TypeKind.JSON and c.ftype.is_string:
            # stored JSON is normalized; normalize the literal the same
            # way or equality on the just-inserted spelling never matches
            import json as _json
            try:
                return Const(_json.dumps(_json.loads(str(c.value)),
                                         sort_keys=True,
                                         separators=(", ", ": ")), target)
            except ValueError:
                return c  # non-JSON literal: compare as plain text
        if target.kind == TypeKind.SET and c.ftype.is_string:
            # 'a,b' literal -> element bitmask for SET-column compares
            from ..chunk.column import _encode_scalar
            try:
                return Const(_encode_scalar(target, str(c.value), None),
                             target)
            except ValueError:
                return Const(-1, target)  # unknown elems: never equal
        if target.kind == TypeKind.DATE and c.ftype.is_string:
            return Const(parse_date(str(c.value)), target)
        if target.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP) and \
                c.ftype.is_string:
            return Const(parse_datetime(str(c.value)), target)
        if target.is_decimal and c.ftype.is_integer:
            return Const(int(c.value) * target.decimal_multiplier, target)
        if target.is_decimal and c.ftype.is_decimal:
            if c.ftype.scale <= target.scale:
                # exact widening into the column's scale (required for
                # IN-lists, which compare raw unscaled values)
                mult = 10 ** (target.scale - c.ftype.scale)
                return Const(int(c.value) * mult, target)
            div = 10 ** (c.ftype.scale - target.scale)
            if int(c.value) % div == 0:
                return Const(int(c.value) // div, target)  # e.g. 3.250 @ s2
            return c  # not representable at the column scale
        if target.is_float and (c.ftype.is_integer or c.ftype.is_decimal):
            v = c.value
            if c.ftype.is_decimal:
                v = Decimal(v, c.ftype.scale).to_float()
            return Const(float(v), target)
        if target.is_integer and c.ftype.is_decimal:
            return c  # numeric compare handles mixed scale
        return c

    def _resolve_date_arith(
        self,
        date_expr: PlanExpr,
        interval: ast.IntervalExpr,
        op: str,
        r: Callable[[ast.Expr], PlanExpr],
    ) -> PlanExpr:
        if date_expr.ftype.is_string and isinstance(date_expr, Const):
            date_expr = Const(parse_date(str(date_expr.value)),
                              FieldType(TypeKind.DATE))
        if date_expr.ftype.kind != TypeKind.DATE:
            raise PlanError("interval arithmetic supports DATE operands")
        amount = r(interval.value)
        if not isinstance(amount, Const):
            raise PlanError("INTERVAL amount must be constant")
        n = int(amount.value) if not amount.ftype.is_string else int(
            str(amount.value))
        if op == "-":
            n = -n
        unit = interval.unit
        if unit in ("DAY", "WEEK"):
            days = n * (7 if unit == "WEEK" else 1)
            if isinstance(date_expr, Const):
                return Const(int(date_expr.value) + days, date_expr.ftype)
            return Call("date_add_days", [date_expr], date_expr.ftype,
                        extra=days)
        if unit in ("MONTH", "QUARTER", "YEAR"):
            months = n * {"MONTH": 1, "QUARTER": 3, "YEAR": 12}[unit]
            if isinstance(date_expr, Const):
                d = decode_date(int(date_expr.value))
                return Const(encode_date(_add_months(d, months)),
                             date_expr.ftype)
            raise PlanError("month/year interval over columns not supported yet")
        raise PlanError(f"unsupported interval unit {unit}")

    def _resolve_scalar_func(
        self, node: ast.FuncCall, r: Callable[[ast.Expr], PlanExpr]
    ) -> PlanExpr:
        name = node.name
        args = [r(a) for a in node.args]

        def need(n: int) -> None:
            if len(args) != n:
                raise PlanError(f"{name} expects {n} argument(s)")

        if name in ("YEAR", "MONTH", "DAY", "DAYOFMONTH"):
            need(1)
            if not args[0].ftype.is_temporal:
                raise PlanError(f"{name} requires a temporal argument")
            tag = {"YEAR": "year", "MONTH": "month", "DAY": "day",
                   "DAYOFMONTH": "day"}[name]
            return _fold(Call(tag, args, FieldType(TypeKind.BIGINT)))
        if name == "ABS":
            need(1)
            return _fold(Call("abs", args, args[0].ftype))
        if name == "IF":
            need(3)
            cond = _coerce_bool(args[0])
            ft = _unify_types(args[1].ftype, args[2].ftype)
            return _fold(Call("if", [cond, args[1], args[2]], ft))
        if name == "IFNULL":
            need(2)
            ft = _unify_types(args[0].ftype, args[1].ftype)
            return _fold(Call("ifnull", args, ft))
        if name == "COALESCE":
            if not args:
                raise PlanError("COALESCE needs arguments")
            ft = args[0].ftype
            for a in args[1:]:
                ft = _unify_types(ft, a.ftype)
            return _fold(Call("coalesce", args, ft))
        if name == "SUBSTRING":
            if len(args) not in (2, 3):
                raise PlanError("SUBSTRING expects 2 or 3 arguments")
            if not args[0].ftype.is_string:
                raise PlanError("SUBSTRING requires a string argument")
            for a in args[1:]:
                if not isinstance(a, Const):
                    raise PlanError("SUBSTRING position/length must be "
                                    "constant")
            start = int(args[1].value)
            length = int(args[2].value) if len(args) == 3 else None
            from ..types.field_type import varchar_type
            return Call("substring", [args[0]], varchar_type(),
                        extra=(start, length))
        # ---- JSON function family (host-evaluated; reference:
        # types/json/binary.go + expression/builtin_json.go) ----------
        from ..types.field_type import varchar_type as _vt
        if name == "JSON_EXTRACT":
            if len(args) != 2 or not isinstance(args[1], Const):
                raise PlanError(
                    "JSON_EXTRACT expects (doc, constant path)")
            return Call("json_extract", [args[0]], _vt(),
                        extra=str(args[1].value))
        if name == "JSON_UNQUOTE":
            need(1)
            return Call("json_unquote", args, _vt())
        if name == "JSON_VALID":
            need(1)
            return Call("json_valid", args, FieldType(TypeKind.BIGINT))
        if name == "JSON_TYPE":
            need(1)
            return Call("json_type", args, _vt())
        if name == "JSON_LENGTH":
            need(1)
            return Call("json_length", args, FieldType(TypeKind.BIGINT))
        if name in ("JSON_OBJECT", "JSON_ARRAY"):
            for a in args:
                if not isinstance(a, Const):
                    raise PlanError(f"{name} supports constant arguments")
            import json as _json
            if name == "JSON_ARRAY":
                doc = _json.dumps([a.value for a in args])
            else:
                if len(args) % 2:
                    raise PlanError("JSON_OBJECT needs key/value pairs")
                doc = _json.dumps(
                    {str(args[i].value): args[i + 1].value
                     for i in range(0, len(args), 2)}, sort_keys=True)
            return Const(doc, _vt())
        if name == "FIND_IN_SET":
            need(2)
            return Call("find_in_set", args, FieldType(TypeKind.BIGINT))
        out = self._resolve_builtin(name, args, need)
        if out is not None:
            return out
        # breadth layer: the declarative host-function registry
        # (copr/funcs.py). LOCATE's 3-arg form shares a name with the
        # vectorized 2-arg core — registered under an alias.
        from ..copr.funcs import lookup
        reg_name = "LOCATE3" if name == "LOCATE" and len(args) == 3 \
            else name
        fd = lookup(reg_name)
        if fd is not None:
            if not fd.min_args <= len(args) <= fd.max_args:
                raise PlanError(
                    f"{name} expects {fd.min_args}..{fd.max_args} "
                    f"argument(s)")
            from ..types.field_type import varchar_type
            ret = {"str": varchar_type(),
                   "int": FieldType(TypeKind.BIGINT),
                   "float": FieldType(TypeKind.DOUBLE),
                   "date": FieldType(TypeKind.DATE)}.get(fd.ret)
            if ret is None:  # argN: result typed like that argument
                i = 1 if fd.ret == "arg1" and len(args) > 1 else 0
                ret = args[i].ftype
            return _fold(Call(f"fx:{fd.name}", args, ret))
        raise PlanError(f"unsupported function {name}")

    def _resolve_builtin(self, name: str, args: list[PlanExpr],
                         need) -> Optional[PlanExpr]:
        """The everyday MySQL scalar library (reference:
        expression/builtin_string.go / builtin_math.go /
        builtin_time.go / builtin_compare.go — host-evaluated here, the
        device gate keeps them off the pushdown path)."""
        from ..types.field_type import varchar_type as _vt

        bigint = FieldType(TypeKind.BIGINT)
        double = FieldType(TypeKind.DOUBLE)

        # ---- string functions ----
        if name in ("UPPER", "UCASE", "LOWER", "LCASE", "TRIM", "LTRIM",
                    "RTRIM", "REVERSE"):
            need(1)
            op = {"UPPER": "upper", "UCASE": "upper", "LOWER": "lower",
                  "LCASE": "lower", "TRIM": "trim", "LTRIM": "ltrim",
                  "RTRIM": "rtrim", "REVERSE": "reverse"}[name]
            return (Call(op, args, _vt()))
        if name in ("CONCAT", "CONCAT_WS"):
            if len(args) < (2 if name == "CONCAT_WS" else 1):
                raise PlanError(f"{name} needs more arguments")
            return (Call(name.lower(), args, _vt()))
        if name in ("LEFT", "RIGHT", "REPEAT"):
            need(2)
            return (Call(name.lower(), args, _vt()))
        if name == "REPLACE":
            need(3)
            return (Call("replace", args, _vt()))
        if name in ("LPAD", "RPAD"):
            need(3)
            return (Call(name.lower(), args, _vt()))
        if name in ("LENGTH", "CHAR_LENGTH", "CHARACTER_LENGTH",
                    "OCTET_LENGTH", "ASCII"):
            need(1)
            op = {"LENGTH": "length", "OCTET_LENGTH": "length",
                  "CHAR_LENGTH": "char_length",
                  "CHARACTER_LENGTH": "char_length",
                  "ASCII": "ascii"}[name]
            return Call(op, args, bigint)
        if (name == "LOCATE" and len(args) == 2) or name == "INSTR":
            need(2)
            if name == "INSTR":  # INSTR(str, substr) = LOCATE(substr, str)
                args = [args[1], args[0]]
            return Call("locate", args, bigint)

        # ---- math functions ----
        if name in ("ROUND", "TRUNCATE"):
            if len(args) not in (1, 2):
                raise PlanError(f"{name} expects 1 or 2 arguments")
            d = 0
            if len(args) == 2:
                if not isinstance(args[1], Const):
                    raise PlanError(f"{name} digits must be constant")
                if args[1].value is None:  # MySQL: NULL digits -> NULL
                    return Const(None, args[0].ftype)
                d = int(args[1].value)
            at = args[0].ftype
            if at.is_float:
                ft = double
            elif at.is_decimal:
                ft = FieldType(TypeKind.DECIMAL, flen=at.flen,
                               scale=max(0, min(d, at.scale)))
            else:
                ft = bigint
            return Call(name.lower(), [args[0]], ft, extra=d)
        if name in ("FLOOR", "CEIL", "CEILING"):
            need(1)
            ft = double if args[0].ftype.is_float else bigint
            op = "floor" if name == "FLOOR" else "ceil"
            return Call(op, args, ft)
        if name in ("SQRT", "EXP", "LN", "LOG2", "LOG10"):
            need(1)
            return Call(name.lower(), args, double)
        if name == "RAND" and args:
            # RAND(seed): per-STATEMENT seeded sequence, one draw per row
            # (reference: builtin_math.go randWithSeed). The registry's
            # per-row call model would repeat the first draw.
            need(1)
            if not isinstance(args[0], Const):
                raise PlanError("RAND seed must be constant")
            return Call("rand_seeded", args, double)
        if name == "LOG":
            if len(args) == 1:
                return Call("ln", args, double)
            need(2)  # LOG(base, x)
            return Call("log_base", args, double)
        if name in ("POW", "POWER"):
            need(2)
            return Call("pow", args, double)
        if name == "SIGN":
            need(1)
            return Call("sign", args, bigint)
        if name == "PI":
            need(0)
            import math
            return Const(math.pi, double)
        if name in ("GREATEST", "LEAST"):
            if len(args) < 2:
                raise PlanError(f"{name} needs at least 2 arguments")
            ft = args[0].ftype
            for a in args[1:]:
                ft = _unify_types(ft, a.ftype)
            return Call(name.lower(), args, ft)
        if name == "NULLIF":
            need(2)
            # NULLIF(a, b) = IF(a = b, NULL, a)
            cond = self._resolve_cmp("eq", args[0], args[1])
            return Call("if", [cond, Const(None, args[0].ftype),
                               args[0]], args[0].ftype)

        # ---- date/time functions ----
        if name in ("DAYOFWEEK", "WEEKDAY", "DAYOFYEAR", "QUARTER"):
            need(1)
            a = _coerce_date_arg(args[0], name)
            return Call(name.lower(), [a], bigint)
        if name in ("HOUR", "MINUTE", "SECOND"):
            need(1)
            a = args[0]
            if a.ftype.is_string and isinstance(a, Const):
                a = Const(_parse_time_us(str(a.value)),
                          FieldType(TypeKind.TIME))
            if a.ftype.kind not in (TypeKind.DATETIME,
                                    TypeKind.TIMESTAMP, TypeKind.TIME):
                raise PlanError(f"{name} requires a time argument")
            return Call(name.lower(), [a], bigint)
        if name == "DATE":
            need(1)
            a = _coerce_date_arg(args[0], name)
            return Call("to_date", [a], FieldType(TypeKind.DATE))
        if name == "LAST_DAY":
            need(1)
            a = _coerce_date_arg(args[0], name)
            return Call("last_day", [a], FieldType(TypeKind.DATE))
        if name == "DATEDIFF":
            need(2)
            coerced = [_coerce_date_arg(a, name) for a in args]
            return Call("datediff", coerced, bigint)
        return None

    def _resolve_case(
        self, node: ast.Case, r: Callable[[ast.Expr], PlanExpr]
    ) -> PlanExpr:
        # CASE x WHEN v ... lowers to CASE WHEN x = v ...
        branches: list[PlanExpr] = []
        result_t: Optional[FieldType] = None
        for when, then in node.branches:
            if node.operand is not None:
                cond = self._resolve_cmp("eq", r(node.operand), r(when))
            else:
                cond = _coerce_bool(r(when))
            tv = r(then)
            result_t = tv.ftype if result_t is None else _unify_types(
                result_t, tv.ftype)
            branches.extend([cond, tv])
        if node.else_expr is not None:
            ev = r(node.else_expr)
            result_t = ev.ftype if result_t is None else _unify_types(
                result_t, ev.ftype)
            branches.append(ev)
        assert result_t is not None
        return _fold(Call("case", branches, result_t))

    # ---- helpers -----------------------------------------------------------
    def _split_conjuncts(self, e: PlanExpr) -> list[PlanExpr]:
        if isinstance(e, Call) and e.op == "and":
            return self._split_conjuncts(e.args[0]) + \
                self._split_conjuncts(e.args[1])
        return [e]


# ==================== module helpers ====================

def _output_name(e: ast.Expr, alias: Optional[str]) -> str:
    if alias:
        return alias.lower()
    if isinstance(e, ast.ColumnRef):
        return e.name.lower()
    return _short_sql(e)


def _short_sql(e: ast.Expr) -> str:
    if isinstance(e, ast.FuncCall):
        inner = "*" if e.is_star else ", ".join(_short_sql(a) for a in e.args)
        return f"{e.name.lower()}({inner})"
    if isinstance(e, ast.ColumnRef):
        return e.name.lower()
    if isinstance(e, ast.Literal):
        return str(e.value)
    if isinstance(e, ast.BinaryOp):
        return f"{_short_sql(e.left)} {e.op.lower()} {_short_sql(e.right)}"
    return type(e).__name__.lower()


def _contains_window(e: ast.Expr) -> bool:
    return any(True for _ in _find_windows(e))


def _find_windows(e: ast.Expr):
    if isinstance(e, ast.FuncCall) and e.window is not None:
        yield e
        return
    for attr in ("left", "right", "operand", "low", "high", "pattern",
                 "value", "else_expr"):
        sub = getattr(e, attr, None)
        if isinstance(sub, ast.Expr):
            yield from _find_windows(sub)
    for attr in ("args", "values", "when_thens"):
        seq = getattr(e, attr, None)
        if isinstance(seq, list):
            for x in seq:
                if isinstance(x, ast.Expr):
                    yield from _find_windows(x)
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, ast.Expr):
                            yield from _find_windows(y)


def _replace_windows(e: ast.Expr, wmap: dict):
    """Structurally replace windowed calls with their __win#i refs."""
    import dataclasses as _dc

    if isinstance(e, ast.FuncCall) and e.window is not None:
        return wmap[ast_key(e)]
    if not _dc.is_dataclass(e):
        return e
    changed = False
    kwargs = {}
    for fld in _dc.fields(e):
        v = getattr(e, fld.name)
        if isinstance(v, ast.Expr):
            nv = _replace_windows(v, wmap)
            changed |= nv is not v
            kwargs[fld.name] = nv
        elif isinstance(v, list):
            nv = []
            for x in v:
                if isinstance(x, ast.Expr):
                    y = _replace_windows(x, wmap)
                    changed |= y is not x
                    nv.append(y)
                elif isinstance(x, tuple):
                    ny = tuple(_replace_windows(z, wmap)
                               if isinstance(z, ast.Expr) else z for z in x)
                    changed |= ny != x
                    nv.append(ny)
                else:
                    nv.append(x)
            kwargs[fld.name] = nv
        else:
            kwargs[fld.name] = v
    return type(e)(**kwargs) if changed else e


def _contains_agg(e: ast.Expr) -> bool:
    return any(True for _ in _find_aggs(e))


def _find_aggs(e: ast.Expr):
    if isinstance(e, ast.FuncCall) and e.name in _AGG_NAMES:
        if e.window is None:
            yield e
        return
    for attr in ("left", "right", "operand", "low", "high", "pattern",
                 "value", "else_expr"):
        sub = getattr(e, attr, None)
        if isinstance(sub, ast.Expr):
            yield from _find_aggs(sub)
    for attr in ("args", "items"):
        subs = getattr(e, attr, None)
        if isinstance(subs, list):
            for s in subs:
                if isinstance(s, ast.Expr):
                    yield from _find_aggs(s)
    if isinstance(e, ast.Case):
        for w, t in e.branches:
            yield from _find_aggs(w)
            yield from _find_aggs(t)


def _literal_const(node: ast.Literal) -> Const:
    tag, v = node.tag, node.value
    if tag == "null" or v is None:
        return Const(None, FieldType(TypeKind.NULL))
    if tag == "int":
        return Const(int(v), FieldType(TypeKind.BIGINT, nullable=False))
    if tag == "decimal":
        d: Decimal = v if isinstance(v, Decimal) else Decimal.parse(str(v))
        return Const(d.unscaled,
                     FieldType(TypeKind.DECIMAL, flen=18, scale=d.scale,
                               nullable=False))
    if tag == "float":
        return Const(float(v), FieldType(TypeKind.DOUBLE, nullable=False))
    if tag == "string":
        return Const(str(v), FieldType(TypeKind.VARCHAR, nullable=False))
    if tag == "bool":
        return Const(int(bool(v)), FieldType(TypeKind.BOOLEAN, nullable=False))
    if tag == "date":
        return Const(parse_date(str(v)), FieldType(TypeKind.DATE,
                                                   nullable=False))
    if tag == "datetime":
        return Const(parse_datetime(str(v)),
                     FieldType(TypeKind.DATETIME, nullable=False))
    raise PlanError(f"unknown literal tag {tag}")


def _coerce_bool(e: PlanExpr) -> PlanExpr:
    if e.ftype.kind == TypeKind.BOOLEAN:
        return e
    if is_numeric(e.ftype):
        zero = Const(0, FieldType(TypeKind.BIGINT, nullable=False))
        return bool_call("ne", [e, zero])
    raise PlanError(f"cannot use {e.ftype!r} as a condition")


def _unify_types(a: FieldType, b: FieldType) -> FieldType:
    if a.kind == TypeKind.NULL:
        return b
    if b.kind == TypeKind.NULL:
        return a
    if a.kind == b.kind:
        if a.is_decimal:
            return a if a.scale >= b.scale else b
        return a
    if is_numeric(a) and is_numeric(b):
        from .expr import _NUMERIC_RANK
        if _NUMERIC_RANK[a.kind] >= _NUMERIC_RANK[b.kind]:
            hi, lo = a, b
        else:
            hi, lo = b, a
        if hi.is_decimal and lo.is_decimal:
            return hi if hi.scale >= lo.scale else lo
        return hi
    if a.is_string and b.is_string:
        return a
    raise PlanError(f"cannot unify types {a!r} and {b!r}")


def _ast_conjuncts(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.BinaryOp) and e.op == "AND":
        return _ast_conjuncts(e.left) + _ast_conjuncts(e.right)
    return [e]


def _contains_subquery(e: ast.Expr) -> bool:
    if isinstance(e, (ast.SubqueryExpr, ast.InSubquery)):
        return True
    for child in vars(e).values():
        if isinstance(child, ast.Expr) and _contains_subquery(child):
            return True
        if isinstance(child, (list, tuple)):
            for item in child:
                if isinstance(item, ast.Expr) and _contains_subquery(item):
                    return True
                if isinstance(item, tuple) and any(
                        isinstance(x, ast.Expr) and _contains_subquery(x)
                        for x in item):
                    return True
    return False


def _flip_cmp(op: str) -> str:
    return {"=": "=", "<>": "<>", "!=": "!=", "<": ">", "<=": ">=",
            ">": "<", ">=": "<="}[op]


def _as_equi_pair(cond: PlanExpr, nleft: int) -> Optional[tuple[int, int]]:
    if isinstance(cond, Call) and cond.op == "eq":
        a, b = cond.args
        if isinstance(a, Col) and isinstance(b, Col):
            if a.idx < nleft <= b.idx:
                return (a.idx, b.idx - nleft)
            if b.idx < nleft <= a.idx:
                return (b.idx, a.idx - nleft)
    return None


def _add_months(d: _dt.date, months: int) -> _dt.date:
    m = d.month - 1 + months
    y = d.year + m // 12
    m = m % 12 + 1
    # clamp day to month end (MySQL DATE_ADD semantics)
    for day in (d.day, 30, 29, 28):
        try:
            return _dt.date(y, m, day)
        except ValueError:
            continue
    raise ValueError("unreachable")


# ---- constant folding -------------------------------------------------------

_FOLD_NUMERIC = {"add", "sub", "mul", "neg"}


def _fold(e: Call) -> PlanExpr:
    """Fold constant subtrees. Conservative: only pure numeric/bool ops with
    all-constant args; decimal ops fold via host Decimal for exactness."""
    if not all(isinstance(a, Const) for a in e.args):
        return e
    args: list[Const] = e.args  # type: ignore[assignment]
    if any(a.value is None for a in args):
        if e.op == "isnull":
            return Const(1, e.ftype)
        if e.op in _FOLD_NUMERIC or e.op in ("div", "eq", "ne", "lt", "le",
                                             "gt", "ge"):
            return Const(None, e.ftype)
        return e
    try:
        if e.op in ("add", "sub", "mul", "div") and all(
            a.ftype.is_decimal or a.ftype.is_integer for a in args
        ):
            def as_dec(c: Const) -> Decimal:
                if c.ftype.is_decimal:
                    return Decimal(int(c.value), c.ftype.scale)
                return Decimal.from_int(int(c.value))
            a, b = as_dec(args[0]), as_dec(args[1])
            out = {"add": a + b, "sub": a - b, "mul": a * b}.get(e.op)
            if e.op == "div":
                out = a.div(b)
            assert out is not None
            if e.ftype.is_decimal:
                return Const(out.rescale(e.ftype.scale).unscaled, e.ftype)
            return Const(out.rescale(0).unscaled, e.ftype)
        if e.op in ("add", "sub", "mul", "div") and any(
            a.ftype.is_float for a in args
        ):
            x, y = float(args[0].value), float(args[1].value)
            val = {"add": x + y, "sub": x - y, "mul": x * y,
                   "div": x / y if y != 0 else None}[e.op]
            return Const(val, e.ftype)
        if e.op == "neg":
            return Const(-args[0].value, e.ftype)
        if e.op == "isnull":
            return Const(0, e.ftype)
        if e.op in ("eq", "ne", "lt", "le", "gt", "ge") and all(
            a.ftype.is_integer or a.ftype.is_decimal or a.ftype.is_float or
            a.ftype.is_temporal for a in args
        ):
            def as_num(c: Const):
                if c.ftype.is_decimal:
                    return Decimal(int(c.value), c.ftype.scale)
                return c.value
            x, y = as_num(args[0]), as_num(args[1])
            if isinstance(x, Decimal) and not isinstance(y, Decimal):
                y = Decimal.from_int(int(y))
            if isinstance(y, Decimal) and not isinstance(x, Decimal):
                x = Decimal.from_int(int(x))
            res = {"eq": x == y, "ne": x != y, "lt": x < y, "le": x <= y,
                   "gt": x > y, "ge": x >= y}[e.op]
            return Const(int(res), e.ftype)
    except (ZeroDivisionError, OverflowError, ExprError):
        return e
    return e


_INT_ORDER = [TypeKind.BOOLEAN, TypeKind.TINYINT, TypeKind.SMALLINT,
              TypeKind.INT, TypeKind.BIGINT]


def _union_ftype(a: FieldType, b: FieldType) -> FieldType:
    """Result type of a UNION column pair (conservative subset of MySQL's
    aggregation rules: same family merges; mixed families are rejected at
    plan time rather than silently coerced)."""
    if a.kind == TypeKind.NULL:
        return FieldType(b.kind, flen=b.flen, scale=b.scale)
    if b.kind == TypeKind.NULL:
        return FieldType(a.kind, flen=a.flen, scale=a.scale)
    if a.is_string and b.is_string:
        return FieldType(TypeKind.VARCHAR, flen=max(a.flen, b.flen))
    if a.is_float or b.is_float:
        if (a.is_float or a.is_integer or a.is_decimal) and \
                (b.is_float or b.is_integer or b.is_decimal):
            return FieldType(TypeKind.DOUBLE)
        raise PlanError("UNION over incompatible column types")
    if a.is_decimal or b.is_decimal:
        if not ((a.is_decimal or a.is_integer)
                and (b.is_decimal or b.is_integer)):
            raise PlanError("UNION over incompatible column types")
        sa = a.scale if a.is_decimal else 0
        sb = b.scale if b.is_decimal else 0
        ia = (a.flen - a.scale) if a.is_decimal else 19
        ib = (b.flen - b.scale) if b.is_decimal else 19
        scale = max(sa, sb)
        return FieldType(TypeKind.DECIMAL,
                         flen=min(max(ia, ib) + scale, 18 + scale),
                         scale=scale)
    if a.is_integer and b.is_integer:
        k = max(a.kind, b.kind, key=lambda x: _INT_ORDER.index(x)
                if x in _INT_ORDER else 99)
        if k not in _INT_ORDER:
            k = TypeKind.BIGINT
        return FieldType(k)
    if a.kind == b.kind:
        return FieldType(a.kind, flen=max(a.flen, b.flen),
                         scale=max(a.scale, b.scale))
    raise PlanError("UNION over incompatible column types")
