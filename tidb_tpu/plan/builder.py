"""AST -> logical plan: name resolution, type inference, agg extraction.

Counterpart of the reference's logical plan builder (reference:
planner/core/logical_plan_builder.go + planbuilder.go — buildSelect,
buildAggregation, buildProjection, havingWindowAndOrderbyExprResolver).
Strict ONLY_FULL_GROUP_BY semantics: a non-aggregated column must appear in
GROUP BY.

Constant folding runs inline during resolution (reference:
expression/constant_fold.go) — required for plan-time temporal arithmetic
like `date '1998-12-01' - interval '90' day`.
"""

from __future__ import annotations

import datetime as _dt
from typing import Callable, Optional

from ..catalog.schema import Catalog, TableInfo
from ..sql import ast
from ..types.field_type import FieldType, TypeKind, boolean_type
from ..types.value import Decimal, decode_date, encode_date, parse_date, parse_datetime
from .expr import (
    AggDesc,
    Call,
    Col,
    Const,
    ExprError,
    PlanExpr,
    agg_result_type,
    arith_result_type,
    bool_call,
    comparable,
    is_numeric,
)
from .logical import (
    LogicalAggregation,
    LogicalJoin,
    LogicalLimit,
    LogicalPlan,
    LogicalProjection,
    LogicalScan,
    LogicalSelection,
    LogicalSort,
)
from .schema import PlanSchema, ResultField

_AGG_NAMES = {"COUNT", "SUM", "AVG", "MIN", "MAX"}

_ARITH_OPS = {"+": "add", "-": "sub", "*": "mul", "/": "div",
              "DIV": "intdiv", "%": "mod"}
_CMP_OPS = {"=": "eq", "<=>": "eq", "<>": "ne", "<": "lt", "<=": "le",
            ">": "gt", ">=": "ge"}
_CMP_SWAP = {"eq": "eq", "ne": "ne", "lt": "gt", "le": "ge", "gt": "lt",
             "ge": "le"}


class PlanError(Exception):
    pass


def ast_key(node: object) -> str:
    """Structural identity for AST expressions (group-by matching)."""
    return repr(node).lower()


class PlanBuilder:
    def __init__(self, catalog: Catalog, current_db: str = "test") -> None:
        self.catalog = catalog
        self.current_db = current_db

    # ==================== SELECT ====================
    def build_select(self, stmt: ast.SelectStmt) -> LogicalPlan:
        if stmt.from_ is None:
            plan = self._build_dual(stmt)
        else:
            plan = self.build_table_refs(stmt.from_)

        if stmt.where is not None:
            conds = self._split_conjuncts(self.resolve(stmt.where, plan.schema))
            plan = LogicalSelection(conds, plan.schema, [plan])

        has_agg = bool(stmt.group_by) or any(
            f.expr is not None and _contains_agg(f.expr) for f in stmt.fields
        ) or (stmt.having is not None and _contains_agg(stmt.having))

        if has_agg:
            plan = self._build_aggregate(stmt, plan)
        else:
            if stmt.having is not None:
                raise PlanError("HAVING without aggregation/group-by")
            plan = self._build_projection(stmt, plan)

        if stmt.distinct:
            plan = self._build_distinct(plan)

        if stmt.order_by:
            plan = self._build_sort(stmt, plan)

        if stmt.limit is not None or stmt.offset:
            limit = stmt.limit if stmt.limit is not None else 2**62
            plan = LogicalLimit(limit, stmt.offset, plan.schema, [plan])
        return plan

    # ---- FROM -------------------------------------------------------------
    def build_table_refs(self, ref: ast.TableRef) -> LogicalPlan:
        if isinstance(ref, ast.TableName):
            return self._build_scan(ref)
        if isinstance(ref, ast.Join):
            return self._build_join(ref)
        if isinstance(ref, ast.SubqueryTable):
            sub = self.build_select(ref.query)
            alias = (ref.alias or "").lower()
            fields = [
                ResultField(f.name, f.ftype, alias) for f in sub.schema.fields
            ]
            sub.schema = PlanSchema(fields)
            return sub
        raise PlanError(f"unsupported table reference {type(ref).__name__}")

    def _build_scan(self, tn: ast.TableName) -> LogicalScan:
        db = tn.db or self.current_db
        try:
            info = self.catalog.table(db, tn.name)
        except KeyError as e:
            raise PlanError(str(e)) from None
        alias = (tn.alias or tn.name).lower()
        fields = [
            ResultField(c.name.lower(), c.ftype, alias, source_offset=c.offset)
            for c in info.columns
        ]
        return LogicalScan(info, alias, PlanSchema(fields))

    def _build_join(self, j: ast.Join) -> LogicalPlan:
        left = self.build_table_refs(j.left)
        right = self.build_table_refs(j.right)
        merged = PlanSchema(left.schema.fields + right.schema.fields)
        eq: list[tuple[int, int]] = []
        others: list[PlanExpr] = []
        nleft = len(left.schema)
        if j.using:
            for name in j.using:
                li = left.schema.resolve(name)
                ri = right.schema.resolve(name)
                if li is None or ri is None:
                    raise PlanError(f"USING column {name} not found on both sides")
                eq.append((li, ri))
        elif j.on is not None:
            for cond in self._split_conjuncts(self.resolve(j.on, merged)):
                pair = _as_equi_pair(cond, nleft)
                if pair is not None:
                    eq.append(pair)
                else:
                    others.append(cond)
        kind = j.kind if j.kind != "CROSS" else "INNER"
        if j.kind == "CROSS" and not eq and not others:
            kind = "CROSS"
        return LogicalJoin(kind, eq, others, merged, [left, right])

    def _build_dual(self, stmt: ast.SelectStmt) -> LogicalPlan:
        """SELECT without FROM: a one-row, zero-column pseudo scan."""
        return LogicalScan(
            TableInfo(id=-1, name="dual", columns=[]), "dual", PlanSchema([])
        )

    # ---- projection / aggregation -----------------------------------------
    def _expand_fields(
        self, stmt: ast.SelectStmt, child_schema: PlanSchema
    ) -> list[tuple[ast.Expr, Optional[str]]]:
        """Expand wildcards into (expr, alias) pairs."""
        out: list[tuple[ast.Expr, Optional[str]]] = []
        for f in stmt.fields:
            if f.expr is not None:
                out.append((f.expr, f.alias))
                continue
            for rf in child_schema.fields:
                if f.wildcard_table and rf.table_alias != f.wildcard_table.lower():
                    continue
                out.append((ast.ColumnRef(rf.name, table=rf.table_alias or None),
                            None))
            if not out:
                raise PlanError("wildcard expanded to no columns")
        return out

    def _build_projection(
        self, stmt: ast.SelectStmt, child: LogicalPlan
    ) -> LogicalProjection:
        pairs = self._expand_fields(stmt, child.schema)
        exprs: list[PlanExpr] = []
        fields: list[ResultField] = []
        for e, alias in pairs:
            pe = self.resolve(e, child.schema)
            exprs.append(pe)
            fields.append(ResultField(_output_name(e, alias), pe.ftype))
        return LogicalProjection(exprs, PlanSchema(fields), [child])

    def _build_aggregate(
        self, stmt: ast.SelectStmt, child: LogicalPlan
    ) -> LogicalPlan:
        child_schema = child.schema
        # 1. resolve group-by expressions (positional ints and aliases allowed)
        pairs = self._expand_fields(stmt, child_schema)
        group_ast: list[ast.Expr] = []
        for g in stmt.group_by:
            if isinstance(g, ast.Literal) and g.tag == "int":
                k = int(g.value)
                if not (1 <= k <= len(pairs)):
                    raise PlanError(f"GROUP BY position {k} out of range")
                group_ast.append(pairs[k - 1][0])
            elif isinstance(g, ast.ColumnRef) and g.table is None and any(
                alias and alias.lower() == g.name.lower() for _, alias in pairs
            ):
                idx = next(i for i, (_, a) in enumerate(pairs)
                           if a and a.lower() == g.name.lower())
                group_ast.append(pairs[idx][0])
            else:
                group_ast.append(g)
        group_exprs = [self.resolve(g, child_schema) for g in group_ast]
        group_keys = [ast_key(g) for g in group_ast]

        # 2. collect aggregate descriptors across select/having/order exprs
        aggs: list[AggDesc] = []
        agg_keys: dict[str, int] = {}

        def collect(e: ast.Expr) -> None:
            for call in _find_aggs(e):
                key = ast_key(call)
                if key in agg_keys:
                    continue
                func = call.name.lower()
                if call.is_star:
                    arg = None
                elif len(call.args) == 1:
                    arg = self.resolve(call.args[0], child_schema)
                else:
                    raise PlanError(f"{call.name} takes one argument")
                if func != "count" and arg is None:
                    raise PlanError(f"{call.name}(*) is not valid")
                desc = AggDesc(func, arg, agg_result_type(func, arg),
                               call.distinct, name=key)
                agg_keys[key] = len(aggs)
                aggs.append(desc)

        for e, _ in pairs:
            collect(e)
        if stmt.having is not None:
            collect(stmt.having)
        for item in stmt.order_by:
            collect(item.expr)
        if not aggs and not group_exprs:
            raise PlanError("aggregation without aggregates or group by")

        # 3. agg node schema: [group cols..., agg results...]
        agg_fields = []
        for i, (g, ga) in enumerate(zip(group_exprs, group_ast)):
            name = ga.name.lower() if isinstance(ga, ast.ColumnRef) else f"group#{i}"
            tbl = (ga.table or "").lower() if isinstance(ga, ast.ColumnRef) else ""
            agg_fields.append(ResultField(name, g.ftype, tbl))
        for i, d in enumerate(aggs):
            agg_fields.append(ResultField(f"agg#{i}", d.ftype))
        agg_plan = LogicalAggregation(
            group_exprs, aggs, PlanSchema(agg_fields), [child]
        )

        # 4. projection over agg output: replace agg calls / group exprs
        ngroups = len(group_exprs)

        def resolve_over_agg(e: ast.Expr) -> PlanExpr:
            key = ast_key(e)
            if key in agg_keys:
                i = ngroups + agg_keys[key]
                return Col(i, agg_plan.schema.fields[i].ftype,
                           repr(aggs[agg_keys[key]]))
            for gi, gkey in enumerate(group_keys):
                if key == gkey:
                    return Col(gi, group_exprs[gi].ftype,
                               agg_plan.schema.fields[gi].name)
            if isinstance(e, ast.ColumnRef):
                idx = agg_plan.schema.resolve(e.name, e.table)
                if idx is not None and idx < ngroups:
                    return Col(idx, agg_plan.schema.fields[idx].ftype, e.name)
                if e.table is None:
                    # select-field alias (MySQL allows these in HAVING/ORDER)
                    for fe, alias in pairs:
                        if alias and alias.lower() == e.name.lower():
                            return resolve_over_agg(fe)
                raise PlanError(
                    f"column {e} must appear in GROUP BY or an aggregate"
                )
            return self._resolve_composite(e, resolve_over_agg)

        exprs = []
        fields = []
        for e, alias in pairs:
            pe = resolve_over_agg(e)
            exprs.append(pe)
            fields.append(ResultField(_output_name(e, alias), pe.ftype))
        plan: LogicalPlan = LogicalProjection(exprs, PlanSchema(fields), [agg_plan])

        # 5. HAVING: filter between agg and projection (resolved in agg scope)
        if stmt.having is not None:
            cond = resolve_over_agg(stmt.having)
            # insert selection under the projection
            sel = LogicalSelection(
                self._split_conjuncts(cond), agg_plan.schema, [agg_plan]
            )
            plan.children[0] = sel
        # stash for order-by resolution
        plan._agg_resolver = resolve_over_agg  # type: ignore[attr-defined]
        return plan

    def _build_distinct(self, child: LogicalPlan) -> LogicalPlan:
        """DISTINCT = group by every output column (reference lowers it the
        same way, planner/core/logical_plan_builder.go buildDistinct)."""
        group = [
            Col(i, f.ftype, f.name) for i, f in enumerate(child.schema.fields)
        ]
        return LogicalAggregation(group, [], child.schema, [child])

    def _build_sort(self, stmt: ast.SelectStmt, plan: LogicalPlan) -> LogicalPlan:
        out_schema = plan.schema
        resolver: Optional[Callable] = getattr(plan, "_agg_resolver", None)
        proj = plan if isinstance(plan, LogicalProjection) else None
        items: list[tuple[PlanExpr, bool]] = []
        hidden: list[PlanExpr] = []  # appended projection cols for sort-only refs
        for item in stmt.order_by:
            e = item.expr
            pe: Optional[PlanExpr] = None
            if isinstance(e, ast.Literal) and e.tag == "int":
                k = int(e.value)
                if not (1 <= k <= len(out_schema)):
                    raise PlanError(f"ORDER BY position {k} out of range")
                pe = Col(k - 1, out_schema.fields[k - 1].ftype)
            elif isinstance(e, ast.ColumnRef) and e.table is None:
                idx = out_schema.resolve(e.name)
                if idx is not None:
                    pe = Col(idx, out_schema.fields[idx].ftype, e.name)
            if pe is None and proj is not None:
                # match select expressions structurally
                key = ast_key(e)
                pairs = self._expand_fields(stmt, proj.children[0].schema) \
                    if resolver is None else None
                if pairs is not None:
                    for i, (fe, _) in enumerate(pairs):
                        if ast_key(fe) == key:
                            pe = Col(i, out_schema.fields[i].ftype)
                            break
            if pe is None:
                if resolver is not None:
                    under = resolver(e)
                    # add as hidden projection column
                    assert proj is not None
                    proj.exprs.append(under)
                    hid_idx = len(proj.schema.fields)
                    proj.schema.fields.append(
                        ResultField(f"__sort#{len(hidden)}", under.ftype)
                    )
                    pe = Col(hid_idx, under.ftype)
                    hidden.append(under)
                elif proj is not None:
                    under = self.resolve(e, proj.children[0].schema)
                    proj.exprs.append(under)
                    hid_idx = len(proj.schema.fields)
                    proj.schema.fields.append(
                        ResultField(f"__sort#{len(hidden)}", under.ftype)
                    )
                    pe = Col(hid_idx, under.ftype)
                    hidden.append(under)
                else:
                    pe = self.resolve(e, out_schema)
            items.append((pe, item.desc))
        sort = LogicalSort(items, plan.schema, [plan])
        if hidden:
            # visible width shrinks back after sort via a trimming projection
            vis = len(plan.schema.fields) - len(hidden)
            exprs = [Col(i, plan.schema.fields[i].ftype) for i in range(vis)]
            trim_schema = PlanSchema(plan.schema.fields[:vis])
            return LogicalProjection(exprs, trim_schema, [sort])
        return sort

    # ==================== expression resolution ====================
    def resolve(self, e: ast.Expr, schema: PlanSchema) -> PlanExpr:
        def r(node: ast.Expr) -> PlanExpr:
            if isinstance(node, ast.ColumnRef):
                idx = schema.resolve(node.name, node.table)
                if idx is None:
                    raise PlanError(f"unknown column {node}")
                return Col(idx, schema.fields[idx].ftype, str(node))
            return self._resolve_composite(node, r)

        return r(e)

    def _resolve_composite(
        self, node: ast.Expr, r: Callable[[ast.Expr], PlanExpr]
    ) -> PlanExpr:
        """Resolve every non-ColumnRef node, delegating children to r."""
        if isinstance(node, ast.Literal):
            return _literal_const(node)
        if isinstance(node, ast.BinaryOp):
            return self._resolve_binary(node, r)
        if isinstance(node, ast.UnaryOp):
            if node.op == "NOT":
                arg = _coerce_bool(r(node.operand))
                return bool_call("not", [arg])
            arg = r(node.operand)
            if not is_numeric(arg.ftype):
                raise PlanError(f"unary - over {arg.ftype!r}")
            return _fold(Call("neg", [arg], arg.ftype))
        if isinstance(node, ast.IsNull):
            arg = r(node.operand)
            out = bool_call("isnull", [arg])
            return bool_call("not", [out]) if node.negated else out
        if isinstance(node, ast.Between):
            lo = self._resolve_cmp("ge", r(node.operand), r(node.low))
            hi = self._resolve_cmp("le", r(node.operand), r(node.high))
            out = bool_call("and", [lo, hi])
            return bool_call("not", [out]) if node.negated else out
        if isinstance(node, ast.InList):
            arg = r(node.operand)
            items = [r(i) for i in node.items]
            if not all(isinstance(i, Const) for i in items):
                # general IN lowers to OR of equalities
                out: PlanExpr = self._resolve_cmp("eq", arg, items[0])
                for it in items[1:]:
                    out = bool_call("or", [out, self._resolve_cmp("eq", arg, it)])
            else:
                consts = [self._coerce_const(c, arg.ftype) for c in items]
                if arg.ftype.is_decimal:
                    # values whose scale exceeds the column's can never
                    # equal a stored value — drop them (exact semantics)
                    consts = [
                        c for c in consts
                        if not (c.ftype.is_decimal
                                and c.ftype.scale > arg.ftype.scale)
                    ]  # empty list => never matches (both evaluators)
                out = bool_call("in_values", [arg],
                                extra=[c.value for c in consts])
            return bool_call("not", [out]) if node.negated else out
        if isinstance(node, ast.Like):
            arg = r(node.operand)
            if not arg.ftype.is_string:
                raise PlanError("LIKE requires a string operand")
            pat = r(node.pattern)
            if not isinstance(pat, Const):
                raise PlanError("LIKE pattern must be a constant")
            out = bool_call("like", [arg], extra=str(pat.value))
            return bool_call("not", [out]) if node.negated else out
        if isinstance(node, ast.FuncCall):
            if node.name in _AGG_NAMES:
                raise PlanError(f"aggregate {node.name} not allowed here")
            return self._resolve_scalar_func(node, r)
        if isinstance(node, ast.Case):
            return self._resolve_case(node, r)
        if isinstance(node, ast.Cast):
            arg = r(node.operand)
            return _fold(Call("cast", [arg], node.target))
        if isinstance(node, ast.IntervalExpr):
            raise PlanError("INTERVAL only valid in +/- date arithmetic")
        if isinstance(node, (ast.SubqueryExpr, ast.InSubquery)):
            raise PlanError("subqueries are not supported yet")
        raise PlanError(f"unsupported expression {type(node).__name__}")

    def _resolve_binary(
        self, node: ast.BinaryOp, r: Callable[[ast.Expr], PlanExpr]
    ) -> PlanExpr:
        op = node.op
        if op in ("AND", "OR"):
            left = _coerce_bool(r(node.left))
            right = _coerce_bool(r(node.right))
            return _fold(bool_call(op.lower(), [left, right]))
        if op in ("XOR",):
            left = _coerce_bool(r(node.left))
            right = _coerce_bool(r(node.right))
            return _fold(bool_call("ne", [left, right]))
        if op in _CMP_OPS:
            return self._resolve_cmp(_CMP_OPS[op], r(node.left), r(node.right))
        if op in _ARITH_OPS:
            # interval arithmetic on dates
            if isinstance(node.right, ast.IntervalExpr) and op in ("+", "-"):
                return self._resolve_date_arith(r(node.left), node.right, op, r)
            if isinstance(node.left, ast.IntervalExpr) and op == "+":
                return self._resolve_date_arith(r(node.right), node.left, op, r)
            a, b = r(node.left), r(node.right)
            tag = _ARITH_OPS[op]
            try:
                ftype = arith_result_type(tag, a.ftype, b.ftype)
            except ExprError as e:
                raise PlanError(str(e)) from None
            return _fold(Call(tag, [a, b], ftype))
        raise PlanError(f"unsupported operator {op}")

    def _resolve_cmp(self, tag: str, a: PlanExpr, b: PlanExpr) -> PlanExpr:
        # constant-side coercion: string consts vs temporal/decimal columns
        if isinstance(b, Const) and not isinstance(a, Const):
            b = self._coerce_const(b, a.ftype)
        elif isinstance(a, Const) and not isinstance(b, Const):
            a = self._coerce_const(a, b.ftype)
            a, b = b, a
            tag = _CMP_SWAP[tag]
        if not comparable(a.ftype, b.ftype):
            raise PlanError(f"incomparable types {a.ftype!r} vs {b.ftype!r}")
        return _fold(bool_call(tag, [a, b]))

    def _coerce_const(self, c: Const, target: FieldType) -> Const:
        """Fold a literal into the physical domain of the other operand."""
        if c.value is None:
            return Const(None, target)
        if target.kind == TypeKind.DATE and c.ftype.is_string:
            return Const(parse_date(str(c.value)), target)
        if target.kind in (TypeKind.DATETIME, TypeKind.TIMESTAMP) and \
                c.ftype.is_string:
            return Const(parse_datetime(str(c.value)), target)
        if target.is_decimal and c.ftype.is_integer:
            return Const(int(c.value) * target.decimal_multiplier, target)
        if target.is_decimal and c.ftype.is_decimal:
            if c.ftype.scale <= target.scale:
                # exact widening into the column's scale (required for
                # IN-lists, which compare raw unscaled values)
                mult = 10 ** (target.scale - c.ftype.scale)
                return Const(int(c.value) * mult, target)
            div = 10 ** (c.ftype.scale - target.scale)
            if int(c.value) % div == 0:
                return Const(int(c.value) // div, target)  # e.g. 3.250 @ s2
            return c  # not representable at the column scale
        if target.is_float and (c.ftype.is_integer or c.ftype.is_decimal):
            v = c.value
            if c.ftype.is_decimal:
                v = Decimal(v, c.ftype.scale).to_float()
            return Const(float(v), target)
        if target.is_integer and c.ftype.is_decimal:
            return c  # numeric compare handles mixed scale
        return c

    def _resolve_date_arith(
        self,
        date_expr: PlanExpr,
        interval: ast.IntervalExpr,
        op: str,
        r: Callable[[ast.Expr], PlanExpr],
    ) -> PlanExpr:
        if date_expr.ftype.is_string and isinstance(date_expr, Const):
            date_expr = Const(parse_date(str(date_expr.value)),
                              FieldType(TypeKind.DATE))
        if date_expr.ftype.kind != TypeKind.DATE:
            raise PlanError("interval arithmetic supports DATE operands")
        amount = r(interval.value)
        if not isinstance(amount, Const):
            raise PlanError("INTERVAL amount must be constant")
        n = int(amount.value) if not amount.ftype.is_string else int(
            str(amount.value))
        if op == "-":
            n = -n
        unit = interval.unit
        if unit in ("DAY", "WEEK"):
            days = n * (7 if unit == "WEEK" else 1)
            if isinstance(date_expr, Const):
                return Const(int(date_expr.value) + days, date_expr.ftype)
            return Call("date_add_days", [date_expr], date_expr.ftype,
                        extra=days)
        if unit in ("MONTH", "QUARTER", "YEAR"):
            months = n * {"MONTH": 1, "QUARTER": 3, "YEAR": 12}[unit]
            if isinstance(date_expr, Const):
                d = decode_date(int(date_expr.value))
                return Const(encode_date(_add_months(d, months)),
                             date_expr.ftype)
            raise PlanError("month/year interval over columns not supported yet")
        raise PlanError(f"unsupported interval unit {unit}")

    def _resolve_scalar_func(
        self, node: ast.FuncCall, r: Callable[[ast.Expr], PlanExpr]
    ) -> PlanExpr:
        name = node.name
        args = [r(a) for a in node.args]

        def need(n: int) -> None:
            if len(args) != n:
                raise PlanError(f"{name} expects {n} argument(s)")

        if name in ("YEAR", "MONTH", "DAY", "DAYOFMONTH"):
            need(1)
            if not args[0].ftype.is_temporal:
                raise PlanError(f"{name} requires a temporal argument")
            tag = {"YEAR": "year", "MONTH": "month", "DAY": "day",
                   "DAYOFMONTH": "day"}[name]
            return _fold(Call(tag, args, FieldType(TypeKind.BIGINT)))
        if name == "ABS":
            need(1)
            return _fold(Call("abs", args, args[0].ftype))
        if name == "IF":
            need(3)
            cond = _coerce_bool(args[0])
            ft = _unify_types(args[1].ftype, args[2].ftype)
            return _fold(Call("if", [cond, args[1], args[2]], ft))
        if name == "IFNULL":
            need(2)
            ft = _unify_types(args[0].ftype, args[1].ftype)
            return _fold(Call("ifnull", args, ft))
        if name == "COALESCE":
            if not args:
                raise PlanError("COALESCE needs arguments")
            ft = args[0].ftype
            for a in args[1:]:
                ft = _unify_types(ft, a.ftype)
            return _fold(Call("coalesce", args, ft))
        raise PlanError(f"unsupported function {name}")

    def _resolve_case(
        self, node: ast.Case, r: Callable[[ast.Expr], PlanExpr]
    ) -> PlanExpr:
        # CASE x WHEN v ... lowers to CASE WHEN x = v ...
        branches: list[PlanExpr] = []
        result_t: Optional[FieldType] = None
        for when, then in node.branches:
            if node.operand is not None:
                cond = self._resolve_cmp("eq", r(node.operand), r(when))
            else:
                cond = _coerce_bool(r(when))
            tv = r(then)
            result_t = tv.ftype if result_t is None else _unify_types(
                result_t, tv.ftype)
            branches.extend([cond, tv])
        if node.else_expr is not None:
            ev = r(node.else_expr)
            result_t = ev.ftype if result_t is None else _unify_types(
                result_t, ev.ftype)
            branches.append(ev)
        assert result_t is not None
        return _fold(Call("case", branches, result_t))

    # ---- helpers -----------------------------------------------------------
    def _split_conjuncts(self, e: PlanExpr) -> list[PlanExpr]:
        if isinstance(e, Call) and e.op == "and":
            return self._split_conjuncts(e.args[0]) + \
                self._split_conjuncts(e.args[1])
        return [e]


# ==================== module helpers ====================

def _output_name(e: ast.Expr, alias: Optional[str]) -> str:
    if alias:
        return alias.lower()
    if isinstance(e, ast.ColumnRef):
        return e.name.lower()
    return _short_sql(e)


def _short_sql(e: ast.Expr) -> str:
    if isinstance(e, ast.FuncCall):
        inner = "*" if e.is_star else ", ".join(_short_sql(a) for a in e.args)
        return f"{e.name.lower()}({inner})"
    if isinstance(e, ast.ColumnRef):
        return e.name.lower()
    if isinstance(e, ast.Literal):
        return str(e.value)
    if isinstance(e, ast.BinaryOp):
        return f"{_short_sql(e.left)} {e.op.lower()} {_short_sql(e.right)}"
    return type(e).__name__.lower()


def _contains_agg(e: ast.Expr) -> bool:
    return any(True for _ in _find_aggs(e))


def _find_aggs(e: ast.Expr):
    if isinstance(e, ast.FuncCall) and e.name in _AGG_NAMES:
        yield e
        return
    for attr in ("left", "right", "operand", "low", "high", "pattern",
                 "value", "else_expr"):
        sub = getattr(e, attr, None)
        if isinstance(sub, ast.Expr):
            yield from _find_aggs(sub)
    for attr in ("args", "items"):
        subs = getattr(e, attr, None)
        if isinstance(subs, list):
            for s in subs:
                if isinstance(s, ast.Expr):
                    yield from _find_aggs(s)
    if isinstance(e, ast.Case):
        for w, t in e.branches:
            yield from _find_aggs(w)
            yield from _find_aggs(t)


def _literal_const(node: ast.Literal) -> Const:
    tag, v = node.tag, node.value
    if tag == "null" or v is None:
        return Const(None, FieldType(TypeKind.NULL))
    if tag == "int":
        return Const(int(v), FieldType(TypeKind.BIGINT, nullable=False))
    if tag == "decimal":
        d: Decimal = v if isinstance(v, Decimal) else Decimal.parse(str(v))
        return Const(d.unscaled,
                     FieldType(TypeKind.DECIMAL, flen=18, scale=d.scale,
                               nullable=False))
    if tag == "float":
        return Const(float(v), FieldType(TypeKind.DOUBLE, nullable=False))
    if tag == "string":
        return Const(str(v), FieldType(TypeKind.VARCHAR, nullable=False))
    if tag == "bool":
        return Const(int(bool(v)), FieldType(TypeKind.BOOLEAN, nullable=False))
    if tag == "date":
        return Const(parse_date(str(v)), FieldType(TypeKind.DATE,
                                                   nullable=False))
    if tag == "datetime":
        return Const(parse_datetime(str(v)),
                     FieldType(TypeKind.DATETIME, nullable=False))
    raise PlanError(f"unknown literal tag {tag}")


def _coerce_bool(e: PlanExpr) -> PlanExpr:
    if e.ftype.kind == TypeKind.BOOLEAN:
        return e
    if is_numeric(e.ftype):
        zero = Const(0, FieldType(TypeKind.BIGINT, nullable=False))
        return bool_call("ne", [e, zero])
    raise PlanError(f"cannot use {e.ftype!r} as a condition")


def _unify_types(a: FieldType, b: FieldType) -> FieldType:
    if a.kind == TypeKind.NULL:
        return b
    if b.kind == TypeKind.NULL:
        return a
    if a.kind == b.kind:
        if a.is_decimal:
            return a if a.scale >= b.scale else b
        return a
    if is_numeric(a) and is_numeric(b):
        from .expr import _NUMERIC_RANK
        if _NUMERIC_RANK[a.kind] >= _NUMERIC_RANK[b.kind]:
            hi, lo = a, b
        else:
            hi, lo = b, a
        if hi.is_decimal and lo.is_decimal:
            return hi if hi.scale >= lo.scale else lo
        return hi
    if a.is_string and b.is_string:
        return a
    raise PlanError(f"cannot unify types {a!r} and {b!r}")


def _as_equi_pair(cond: PlanExpr, nleft: int) -> Optional[tuple[int, int]]:
    if isinstance(cond, Call) and cond.op == "eq":
        a, b = cond.args
        if isinstance(a, Col) and isinstance(b, Col):
            if a.idx < nleft <= b.idx:
                return (a.idx, b.idx - nleft)
            if b.idx < nleft <= a.idx:
                return (b.idx, a.idx - nleft)
    return None


def _add_months(d: _dt.date, months: int) -> _dt.date:
    m = d.month - 1 + months
    y = d.year + m // 12
    m = m % 12 + 1
    # clamp day to month end (MySQL DATE_ADD semantics)
    for day in (d.day, 30, 29, 28):
        try:
            return _dt.date(y, m, day)
        except ValueError:
            continue
    raise ValueError("unreachable")


# ---- constant folding -------------------------------------------------------

_FOLD_NUMERIC = {"add", "sub", "mul", "neg"}


def _fold(e: Call) -> PlanExpr:
    """Fold constant subtrees. Conservative: only pure numeric/bool ops with
    all-constant args; decimal ops fold via host Decimal for exactness."""
    if not all(isinstance(a, Const) for a in e.args):
        return e
    args: list[Const] = e.args  # type: ignore[assignment]
    if any(a.value is None for a in args):
        if e.op == "isnull":
            return Const(1, e.ftype)
        if e.op in _FOLD_NUMERIC or e.op in ("div", "eq", "ne", "lt", "le",
                                             "gt", "ge"):
            return Const(None, e.ftype)
        return e
    try:
        if e.op in ("add", "sub", "mul", "div") and all(
            a.ftype.is_decimal or a.ftype.is_integer for a in args
        ):
            def as_dec(c: Const) -> Decimal:
                if c.ftype.is_decimal:
                    return Decimal(int(c.value), c.ftype.scale)
                return Decimal.from_int(int(c.value))
            a, b = as_dec(args[0]), as_dec(args[1])
            out = {"add": a + b, "sub": a - b, "mul": a * b}.get(e.op)
            if e.op == "div":
                out = a.div(b)
            assert out is not None
            if e.ftype.is_decimal:
                return Const(out.rescale(e.ftype.scale).unscaled, e.ftype)
            return Const(out.rescale(0).unscaled, e.ftype)
        if e.op in ("add", "sub", "mul", "div") and any(
            a.ftype.is_float for a in args
        ):
            x, y = float(args[0].value), float(args[1].value)
            val = {"add": x + y, "sub": x - y, "mul": x * y,
                   "div": x / y if y != 0 else None}[e.op]
            return Const(val, e.ftype)
        if e.op == "neg":
            return Const(-args[0].value, e.ftype)
        if e.op == "isnull":
            return Const(0, e.ftype)
        if e.op in ("eq", "ne", "lt", "le", "gt", "ge") and all(
            a.ftype.is_integer or a.ftype.is_decimal or a.ftype.is_float or
            a.ftype.is_temporal for a in args
        ):
            def as_num(c: Const):
                if c.ftype.is_decimal:
                    return Decimal(int(c.value), c.ftype.scale)
                return c.value
            x, y = as_num(args[0]), as_num(args[1])
            if isinstance(x, Decimal) and not isinstance(y, Decimal):
                y = Decimal.from_int(int(y))
            if isinstance(y, Decimal) and not isinstance(x, Decimal):
                x = Decimal.from_int(int(x))
            res = {"eq": x == y, "ne": x != y, "lt": x < y, "le": x <= y,
                   "gt": x > y, "ge": x >= y}[e.op]
            return Const(int(res), e.ftype)
    except (ZeroDivisionError, OverflowError, ExprError):
        return e
    return e
