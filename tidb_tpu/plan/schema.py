"""Plan output schemas: named, typed column lists for name resolution.

Counterpart of the reference's `expression.Schema` + output names
(reference: expression/schema.go) — every plan node exposes one.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..types.field_type import FieldType


@dataclass
class ResultField:
    name: str  # column name (lowered)
    ftype: FieldType
    table_alias: str = ""  # qualifier (table alias or name, lowered)
    # for scans: offset of the column in the stored table row
    source_offset: int = -1


@dataclass
class PlanSchema:
    fields: list[ResultField] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.fields)

    def resolve(self, name: str, table: Optional[str] = None) -> Optional[int]:
        """Index of the column matching [table.]name; None if absent.
        Raises on ambiguity."""
        lname = name.lower()
        ltable = table.lower() if table else None
        hits = [
            i
            for i, f in enumerate(self.fields)
            if f.name == lname and (ltable is None or f.table_alias == ltable)
        ]
        if not hits:
            return None
        if len(hits) > 1:
            raise KeyError(f"ambiguous column: {name}")
        return hits[0]
