from .expr import Col, Const, Call, AggDesc, ExprError
from .schema import ResultField, PlanSchema
from .dag import CopDAG, DAGScan, DAGSelection, DAGAggregation, DAGTopN, DAGLimit
from .logical import (
    LogicalPlan,
    LogicalScan,
    LogicalSelection,
    LogicalProjection,
    LogicalAggregation,
    LogicalJoin,
    LogicalSort,
    LogicalLimit,
)
from .builder import PlanBuilder, PlanError
from .physical import (
    PhysicalPlan,
    PhysTableRead,
    PhysSelection,
    PhysProjection,
    PhysHashAgg,
    PhysHashJoin,
    PhysSort,
    PhysLimit,
    optimize,
    explain_plan,
)

__all__ = [
    "Col", "Const", "Call", "AggDesc", "ExprError",
    "ResultField", "PlanSchema",
    "CopDAG", "DAGScan", "DAGSelection", "DAGAggregation", "DAGTopN", "DAGLimit",
    "LogicalPlan", "LogicalScan", "LogicalSelection", "LogicalProjection",
    "LogicalAggregation", "LogicalJoin", "LogicalSort", "LogicalLimit",
    "PlanBuilder", "PlanError",
    "PhysicalPlan", "PhysTableRead", "PhysSelection", "PhysProjection",
    "PhysHashAgg", "PhysHashJoin", "PhysSort", "PhysLimit",
    "optimize", "explain_plan",
]
