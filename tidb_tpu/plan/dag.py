"""CopDAG: the pushdown plan IR shipped to the TiTPU coprocessor.

Counterpart of the reference's `tipb.DAGRequest` executor list (reference:
planner/core/plan_to_pb.go:39-326 builds TableScan -> Selection ->
Aggregation/TopN/Limit chains; the storage side interprets or compiles them,
store/mockstore/unistore/cophandler/closure_exec.go). Here the DAG is a
typed Python structure the kernel compiler lowers to one fused JAX program;
a protobuf wire form comes with the C++/multi-host tier.

Expression trees inside the DAG reference the scan's output columns by
index (Col.idx is an offset into `DAGScan.col_offsets`' output order).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..types.field_type import FieldType
from .expr import AggDesc, PlanExpr


@dataclass
class DAGScan:
    table_id: int
    # offsets into the stored table's columns, in output order
    col_offsets: list[int]
    # index access ranges (plan/ranger.ScanRanges); None = full scan.
    # With ranges the coprocessor gathers matching rows via the index
    # permutation and runs the rest of the DAG host-side over the (small)
    # subset (reference: IndexLookUp double read, executor/distsql.go:353)
    ranges: Optional[object] = None


@dataclass
class DAGSelection:
    # conjunctive conditions over the scan output
    conditions: list[PlanExpr]


@dataclass
class DAGAggregation:
    group_by: list[PlanExpr]
    aggs: list[AggDesc]


# ---- partial-aggregate column layout ---------------------------------------
# Most aggregates ship (val, cnt) column pairs from the coprocessor to the
# final merge. APPROX_COUNT_DISTINCT ships its HLL sketch instead:
# byte-packed max-rank registers in HLL_WORDS int64 words, then cnt — the
# only representation that merges correctly across partial producers
# (overlay batches, partitions, shards); a scalar estimate would not
# (reference: executor/aggfuncs/func_hybrid_count_distinct.go keeps the
# sketch through partial merge for the same reason).

HLL_WORDS = 32  # 256 registers / 8 per int64 word (one byte per register)


def agg_partial_width(d: AggDesc) -> int:
    """Number of partial columns the aggregate contributes (incl. cnt)."""
    return (HLL_WORDS + 1) if d.func == "approx_count_distinct" else 2


def agg_partial_starts(aggs: list[AggDesc], ngroups: int) -> list[int]:
    """Per-agg first partial-column index in the partial chunk layout
    [group cols..., per-agg partial cols...]."""
    starts = []
    o = ngroups
    for d in aggs:
        starts.append(o)
        o += agg_partial_width(d)
    return starts


@dataclass
class DAGTopN:
    # (expr, desc) sort items over scan output, then keep n
    items: list[tuple[PlanExpr, bool]]
    n: int


@dataclass
class DAGLimit:
    n: int


@dataclass
class CopDAG:
    """scan -> [selection] -> [agg | topn | limit] -> [projection exprs]."""

    scan: DAGScan
    selection: Optional[DAGSelection] = None
    agg: Optional[DAGAggregation] = None
    topn: Optional[DAGTopN] = None
    limit: Optional[DAGLimit] = None
    # post-ops projection evaluated device-side when no agg (scan output ->
    # projected exprs); with agg, projection happens host-side over agg output
    projections: Optional[list[PlanExpr]] = None
    output_types: list[FieldType] = field(default_factory=list)

    def describe(self) -> str:
        rng = f" {self.scan.ranges.describe()}" if self.scan.ranges else ""
        parts = [f"scan(t{self.scan.table_id} cols={self.scan.col_offsets}{rng})"]
        if self.selection:
            parts.append(f"sel({len(self.selection.conditions)} conds)")
        if self.agg:
            parts.append(
                f"agg(groups={len(self.agg.group_by)}, aggs={self.agg.aggs})"
            )
        if self.topn:
            parts.append(f"topn({self.topn.n})")
        if self.limit:
            parts.append(f"limit({self.limit.n})")
        if self.projections:
            parts.append(f"proj({len(self.projections)})")
        return " -> ".join(parts)
