"""Partition expansion + pruning: partitioned scans become unions of
physical per-partition scans.

Counterpart of the reference's partition handling (reference: the
planner's partition pruning, planner/core/rule_partition_processor.go —
a partitioned LogicalDataSource expands into a union of per-partition
data sources with non-matching partitions pruned; the executor side is
table/tables/partition.go). Here each partition is a real TableStore
with its own device epoch cache, so the expansion gives every surviving
partition its own coprocessor scan.

Runs after predicate pushdown (scan-level conjuncts sit directly above
the scans) and before join reorder/pruning.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .expr import PlanExpr
from .logical import (
    LogicalPlan,
    LogicalScan,
    LogicalSelection,
    LogicalUnion,
)
from .schema import PlanSchema


def expand_partitions(plan: LogicalPlan) -> LogicalPlan:
    # the Selection-over-Scan shape must be inspected BEFORE recursion
    # replaces the scan child with a union
    if isinstance(plan, LogicalSelection) and \
            isinstance(plan.children[0], LogicalScan):
        scan = plan.children[0]
        part = getattr(scan.table, "partition", None)
        if part is not None:
            keep = prune_partitions(part, plan.conditions, scan)
            return _scan_union(scan, keep, plan.conditions)
    plan.children = [expand_partitions(c) for c in plan.children]
    if isinstance(plan, LogicalScan):
        part = getattr(plan.table, "partition", None)
        if part is not None:
            return _scan_union(plan, part.defs, [])
    return plan


def _const_num(c) -> Optional[float]:
    """A Const's value in the SQL numeric domain the partition bounds
    live in (decimal literals carry scaled integers physically)."""
    from .expr import Const

    if not isinstance(c, Const) or c.value is None:
        return None
    if getattr(c.ftype, "is_decimal", False):
        return c.value / (10 ** c.ftype.scale)
    if isinstance(c.value, (int, float)):
        return c.value
    return None


def prune_partitions(part, conditions: list[PlanExpr], scan: LogicalScan):
    """Partitions that can hold rows satisfying the conjuncts
    (reference: rule_partition_processor.go pruning on hash equality and
    range intervals). Falls back to all partitions when the conjuncts
    don't bound the partition column. Constant values normalize out of
    their physical encodings (scaled decimals) before comparing with the
    partition bounds."""
    from .expr import Call, Col, Const

    # scan schema is the full column list at this point: position ->
    # table offset through source_offset
    pos = next((i for i, f in enumerate(scan.schema.fields)
                if f.source_offset == part.col_offset), None)
    if pos is None:
        return list(part.defs)

    def col_const(c):
        """(op, numeric const) for `pcol OP const` conjuncts."""
        if not isinstance(c, Call) or c.op not in (
                "eq", "lt", "le", "gt", "ge", "in_values"):
            return None
        if c.op == "in_values":
            a = c.args[0]
            if isinstance(a, Col) and a.idx == pos:
                return ("in", list(c.extra))
            return None
        a, b = c.args
        op = c.op
        if isinstance(b, Col) and isinstance(a, Const):
            a, b = b, a
            op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                  "eq": "eq"}[op]
        if not (isinstance(a, Col) and a.idx == pos
                and isinstance(b, Const)):
            return None
        v = _const_num(b)
        if v is None:
            return None
        return (op, v)

    lo = hi = None
    lo_incl = hi_incl = True
    eq_vals: Optional[list] = None
    for c in conditions:
        hit = col_const(c)
        if hit is None:
            continue
        op, v = hit
        if op == "in":
            eq_vals = [x for x in v if isinstance(x, (int, float))]
        elif op == "eq":
            eq_vals = [v]
        elif op in ("gt", "ge"):
            if lo is None or v > lo:
                lo, lo_incl = v, op == "ge"
        elif op in ("lt", "le"):
            if hi is None or v < hi:
                hi, hi_incl = v, op == "le"

    if eq_vals is not None:
        keep = []
        for v in eq_vals:
            if float(v) != int(v):
                continue  # fractional value never equals an int column
            try:
                d = part.route(int(v))
            except (ValueError, TypeError):
                continue
            if d not in keep:
                keep.append(d)
        return keep
    if part.kind == "range" and (lo is not None or hi is not None):
        keep = []
        prev_bound = None
        for d in part.defs:
            # partition covers [prev_bound, d.less_than). Comparisons
            # stay exact for any numeric bound type (no integer ±1
            # tricks — a float bound like d < 10.5 must not prune the
            # partition holding d = 10); at worst they keep an extra
            # partition, never drop a matching one.
            p_lo = prev_bound
            p_hi = d.less_than
            prev_bound = d.less_than
            if lo is not None and p_hi is not None and p_hi <= lo:
                continue  # entirely below the requested range
            if hi is not None and p_lo is not None:
                if p_lo > hi or (not hi_incl and p_lo >= hi):
                    continue  # entirely above
            keep.append(d)
        return keep
    return list(part.defs)


def _scan_union(scan: LogicalScan, defs, conditions: list[PlanExpr]
                ) -> LogicalPlan:
    if not defs:
        defs = [scan.table.partition.defs[0]]  # provably-empty: 1 scan
    children: list[LogicalPlan] = []
    for d in defs:
        child_info = dataclasses.replace(
            scan.table, id=d.id, name=f"{scan.table.name}#{d.name}",
            partition=None)
        cscan = LogicalScan(child_info, scan.alias,
                            PlanSchema(list(scan.schema.fields)))
        node: LogicalPlan = cscan
        if conditions:
            # expression objects are read-only to the engine: sharing
            # them across partition branches is safe
            node = LogicalSelection(list(conditions), cscan.schema,
                                    [cscan])
        children.append(node)
    if len(children) == 1:
        return children[0]
    return LogicalUnion(PlanSchema(list(scan.schema.fields)), children)


__all__ = ["expand_partitions", "prune_partitions"]
