"""Join fragments: multi-table pushdown units for the TiTPU coprocessor.

The reference executes multi-table analytics by shipping plan fragments to
the columnar MPP tier — exchanges between TiFlash nodes, gathered by TiDB
(reference: planner/core/fragment.go:45 fragment expansion,
store/tikv/mpp.go:372 DispatchMPPTasks, executor/mpp_gather.go:103). The
TPU equivalent keeps whole snowflake join trees inside ONE fused device
program: dimension ("build") tables become device-resident lookup tables,
the fact ("probe") table streams through gather-joins, and the post-join
selection/aggregation reuses the single-table kernel machinery. On a
remote TPU every synchronous round trip costs ~100ms, so fusing the whole
join pipeline into one dispatch+fetch is the difference between one RTT
and five.

Eligibility (recognized bottom-up over the physical plan):

* INNER equi-joins only, one join key per edge;
* every table but one ("probe") is reachable through a join whose key on
  that table is unique — the PK handle or a single-column visible unique
  index — so each probe row matches at most one build row and the join is
  a static-shape gather (no dynamic output sizes for XLA);
* leaves are bare full scans (their pushed-down filters ride along and
  are applied to the build bitmaps);
* integer join keys (dictionary codes are per-table and don't unify).

Key density, int32 staging width, and MVCC overlay state are runtime
properties — the executor (copr/fragment.py) checks them per snapshot and
falls back to an equivalent host (numpy) fragment interpreter, never to a
different plan shape.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..types.field_type import FieldType, TypeKind
from .dag import DAGAggregation, DAGTopN
from .expr import AggDesc, Call, Col, Const, PlanExpr, ScalarSubq
from .physical import (
    PhysHashAgg,
    PhysHashJoin,
    PhysLimit,
    PhysProjection,
    PhysSelection,
    PhysSort,
    PhysTableRead,
    PhysicalPlan,
    _bare_scan,
    _partial_val_type,
    agg_pushable,
    expr_pushable,
)
from .schema import PlanSchema, ResultField


@dataclass
class FragTable:
    """One table of the fragment. col_offsets are store offsets in local
    column order; filters are this table's pushed-down conjuncts in LOCAL
    index space (Col.idx -> position in col_offsets)."""

    table: object  # TableInfo
    col_offsets: list[int]
    filters: list[PlanExpr] = field(default_factory=list)
    col_types: list[FieldType] = field(default_factory=list)


@dataclass
class FragJoin:
    """Gather-join of tables[build] onto the probe row stream.

    probe_key evaluates in the COMBINED column space of all previously
    placed tables; build_key_local indexes tables[build].col_offsets. The
    build key is unique per eligibility, so the join is
    idx = perm[key - lo]; found = idx >= 0."""

    build: int
    probe_key: PlanExpr
    build_key_local: int


@dataclass
class FragSemi:
    """Membership-gate edge (EXISTS / IN / NOT IN): probe-stream rows
    survive iff their key is (not) present in the build table's filtered
    key set. The build table contributes NO columns to the combined
    space — only a device-resident membership bitmap over its key span
    (copr/fragment.py stages it host-side per epoch, NULL-aware for the
    ANTI_NULL NOT-IN form). kind: "SEMI" | "ANTI" | "ANTI_NULL"."""

    table: FragTable
    probe_key: PlanExpr
    build_key_local: int
    kind: str


@dataclass
class HCTopN:
    """High-cardinality group-by hint: the aggregation's consumer is
    ORDER BY <score> LIMIT k, so the device may return only a candidate
    superset of the top-k groups (sorted-run kernel, copr/hcagg.py)
    instead of the full group set. score: ("group", j) ranks by group key
    j; ("agg", ai) ranks by aggregate ai's (approximate) value. The host
    layers above re-sort exactly.

    `items`, when set, is the COMPLETE resolved ORDER BY list
    [(kind, idx, desc), ...] with kind in ("group", "agg") — every item
    ranks by a group key or by an exactly-recombinable SUM/COUNT — and
    unlocks the fused final cut (copr/fragment.py `fat` mode,
    join+agg+topn): the device sorts the candidate buffer by the EXACT
    multi-key order (limb-pair digits for aggregates, rank tables for
    dictionary strings) so only the final k groups leave HBM. items[0]
    always matches `score`."""

    score: tuple[str, int]
    desc: bool
    k: int
    items: Optional[list] = None

    @property
    def cap(self) -> int:
        # candidate buffer absorbing f32 score ties near the k-th value
        return max(4 * self.k, self.k + 64)


@dataclass
class FragmentDAG:
    """tables[0] is the probe; joins place tables[1..] in order. The
    combined column space is concat(tables[i] columns) in table order;
    selection/agg/out_map all reference it."""

    tables: list[FragTable]
    joins: list[FragJoin]
    selection: list[PlanExpr] = field(default_factory=list)
    agg: Optional[DAGAggregation] = None
    # row mode: combined idx per output position (tree schema order)
    out_map: Optional[list[int]] = None
    output_types: list[FieldType] = field(default_factory=list)
    # row mode with a TopN consumer: sort items in COMBINED column space
    # + the limit — the device returns only the per-batch top n rows
    # (copr/fragment.py `topn` mode, join+topn); the host Sort/Limit
    # above merge the per-batch/tile/shard candidates exactly
    topn: Optional[DAGTopN] = None
    # set when the agg's consumer is a TopN: permits the high-cardinality
    # candidate path when the dense-segment gate rejects the group space
    hc: Optional[HCTopN] = None
    # set when the agg's consumer filters on an aggregate value (HAVING
    # sum(x) > c): the device may return only groups passing a safely
    # widened version of these predicates — the host Selection above
    # re-applies them exactly. Each entry is (agg_index, op, const) with
    # op in lt/le/gt/ge and const already scaled to the aggregate's
    # integer representation.
    having: Optional[list] = None
    # semi/anti membership gates applied after the joins (no columns)
    semis: list[FragSemi] = field(default_factory=list)
    HAVING_CAP = 65536  # candidate buffer for having/all-groups modes

    def combined_types(self) -> list[FieldType]:
        out: list[FieldType] = []
        for t in self.tables:
            out.extend(t.col_types)
        return out

    def describe(self) -> str:
        parts = [f"probe(t{self.tables[0].table.id} "
                 f"cols={self.tables[0].col_offsets})"]
        for j in self.joins:
            t = self.tables[j.build]
            parts.append(f"gather(t{t.table.id} key={j.probe_key!r})")
        for sm in self.semis:
            parts.append(f"{sm.kind.lower()}(t{sm.table.table.id} "
                         f"key={sm.probe_key!r})")
        if self.selection:
            parts.append(f"sel({len(self.selection)})")
        if self.agg is not None:
            parts.append(f"agg(groups={len(self.agg.group_by)}, "
                         f"aggs={self.agg.aggs})")
        if self.topn is not None:
            parts.append(f"topn({self.topn.n})")
        return " -> ".join(parts)


@dataclass
class PhysFragmentRead(PhysicalPlan):
    """Leaf executing a FragmentDAG on the coprocessor.

    Agg mode outputs the partial layout [group cols..., (val, cnt)...]
    merged by a PhysHashAgg("final") parent — identical contract to the
    single-table pushdown (PhysTableRead + dag.agg)."""

    frag: FragmentDAG
    schema: PlanSchema
    children: list[PhysicalPlan] = field(default_factory=list)
    est_rows: Optional[float] = None


# ==================== recognition ====================

_FRAG_KEY_KINDS = (TypeKind.TINYINT, TypeKind.SMALLINT, TypeKind.INT,
                   TypeKind.BIGINT, TypeKind.YEAR)


def _has_subq(e: PlanExpr) -> bool:
    if isinstance(e, ScalarSubq):
        return True
    if isinstance(e, Call):
        return any(_has_subq(a) for a in e.args)
    return False


@dataclass
class _Collected:
    leaves: list[PhysTableRead]
    # tree-space equality edges (absolute positions over concat'd leaves)
    edges: list[tuple[int, int]]
    # tree-space residual conjuncts (join ON residue + selections above)
    conds: list[PlanExpr]
    width: int
    # semi/anti membership edges: (probe tree position, build leaf,
    # build scan-local key, kind) — build leaves contribute no columns
    semis: list[tuple[int, PhysTableRead, int, str]] = \
        field(default_factory=list)


def _semi_build_leaf(node: PhysicalPlan):
    """Bare-scan build side of a semi/anti join; a trailing plain-Col
    projection (the planner trims the subquery to its key column) is
    tolerated. Returns (leaf, right-schema idx -> scan-local idx) or
    None."""
    if not isinstance(node, PhysTableRead):
        return None
    dag = node.dag
    if dag.scan.table_id < 0 or dag.scan.ranges is not None or \
            dag.agg is not None or dag.topn is not None or \
            dag.limit is not None:
        return None
    if getattr(node, "table", None) is None:
        return None
    if dag.selection and any(_has_subq(c)
                             for c in dag.selection.conditions):
        return None
    projs = dag.projections

    def local_of(i: int) -> Optional[int]:
        if projs is None:
            return i
        if i < len(projs) and isinstance(projs[i], Col):
            return projs[i].idx
        return None

    return node, local_of


def _collect_join_tree(node: PhysicalPlan) -> Optional[_Collected]:
    """Flatten a tree of INNER hash joins over bare scans; positions are
    absolute over the concatenated leaf columns in tree order. Semi/anti
    joins whose build side is a bare scan fold into membership edges
    (the probe subtree keeps its column space — semi output schema IS
    the left schema)."""
    if isinstance(node, PhysSelection):
        inner = _collect_join_tree(node.children[0])
        if inner is None:
            return None
        if any(_has_subq(c) for c in node.conditions):
            return None
        inner.conds = inner.conds + list(node.conditions)
        return inner
    if isinstance(node, PhysHashJoin) and \
            node.kind in ("SEMI", "ANTI", "ANTI_NULL"):
        left = _collect_join_tree(node.children[0])
        if left is None:
            return None
        if len(node.eq_conditions) != 1 or node.other_conditions:
            return None  # per-pair residuals can't gate via a bitmap
        leaf = _semi_build_leaf(node.children[1])
        if leaf is None:
            return None
        tr, local_of = leaf
        li, ri = node.eq_conditions[0]
        blocal = local_of(ri)
        if blocal is None:
            return None
        # integer key domains on both sides (dict codes don't unify)
        bft = _scan_types(tr)[blocal]
        pft = _tree_pos_type(left, li)
        if pft is None or pft.kind not in _FRAG_KEY_KINDS or \
                bft.kind not in _FRAG_KEY_KINDS:
            return None
        left.semis = left.semis + [(li, tr, blocal, node.kind)]
        return left
    if isinstance(node, PhysHashJoin):
        # CROSS nodes appear when the planner stages a cartesian pair whose
        # linking equalities live higher in the tree (e.g. Q9's
        # part x nation); they contribute leaves, later edges key them
        if node.kind not in ("INNER", "CROSS"):
            return None
        left = _collect_join_tree(node.children[0])
        right = _collect_join_tree(node.children[1])
        if left is None or right is None:
            return None
        lw = left.width
        edges = list(left.edges)
        edges += [(a + lw, b + lw) for a, b in right.edges]
        edges += [(li, ri + lw) for li, ri in node.eq_conditions]
        conds = list(left.conds) + [
            _shift_expr(c, lw) for c in right.conds]
        if node.other_conditions:
            if any(_has_subq(c) for c in node.other_conditions):
                return None
            conds += list(node.other_conditions)
        semis = list(left.semis) + [
            (p + lw, tr, bl, kind) for p, tr, bl, kind in right.semis]
        return _Collected(left.leaves + right.leaves, edges, conds,
                          lw + right.width, semis)
    if isinstance(node, PhysTableRead):
        if not _bare_scan(node) or node.dag.scan.ranges is not None:
            return None
        table = getattr(node, "table", None)
        if table is None:
            return None
        return _Collected([node], [], [],
                          len(node.dag.scan.col_offsets))
    return None


def _tree_pos_type(col: _Collected, pos: int) -> Optional[FieldType]:
    """Field type at an absolute tree position over the concat'd leaves."""
    for tr in col.leaves:
        w = len(tr.dag.scan.col_offsets)
        if pos < w:
            return tr.dag.output_types[pos]
        pos -= w
    return None


def _shift_expr(e: PlanExpr, by: int) -> PlanExpr:
    if by == 0:
        return e
    if isinstance(e, Col):
        return Col(e.idx + by, e.ftype)
    if isinstance(e, Call):
        return Call(e.op, [_shift_expr(a, by) for a in e.args], e.ftype,
                    e.extra)
    return e


def _subst_cols(e: PlanExpr, exprs: list[PlanExpr]) -> PlanExpr:
    """Compose an expression over a projection's output with the
    projection itself (Col i -> exprs[i])."""
    if isinstance(e, Col):
        return exprs[e.idx]
    if isinstance(e, Call):
        return Call(e.op, [_subst_cols(a, exprs) for a in e.args], e.ftype,
                    e.extra)
    return e


def _remap_expr(e: PlanExpr, remap: list[int]) -> PlanExpr:
    if isinstance(e, Col):
        return Col(remap[e.idx], e.ftype)
    if isinstance(e, Call):
        return Call(e.op, [_remap_expr(a, remap) for a in e.args], e.ftype,
                    e.extra)
    return e


def _unique_key_offset(table, local_off: int) -> bool:
    """Is the column at store offset local_off a unique key of table?"""
    if table.pk_handle_offset == local_off:
        return True
    for ix in table.indices:
        if ix.unique and ix.visible and ix.col_offsets == [local_off]:
            return True
    return False


def _try_assemble(col: _Collected) -> Optional[tuple[FragmentDAG, list[int]]]:
    """Pick a probe and a build order; returns (frag, treepos->combined)."""
    leaves = col.leaves
    n = len(leaves)
    if n < 2 and not col.semis:
        return None
    # leaf index + local position for every tree position
    leaf_of: list[tuple[int, int]] = []
    for i, tr in enumerate(leaves):
        for local in range(len(tr.dag.scan.col_offsets)):
            leaf_of.append((i, local))

    def leaf_field_type(i: int, local: int) -> FieldType:
        return leaves[i].dag.output_types[local]

    def key_ok(i: int, local: int) -> bool:
        off = leaves[i].dag.scan.col_offsets[local]
        ft = leaf_field_type(i, local)
        return ft.kind in _FRAG_KEY_KINDS and \
            _unique_key_offset(leaves[i].table, off)

    # candidates: prefer leaves that are never on a unique side (fact
    # tables), then larger estimated scans
    def probe_rank(i: int) -> tuple:
        never_unique = not any(
            (leaf_of[a][0] == i and key_ok(*leaf_of[a]))
            or (leaf_of[b][0] == i and key_ok(*leaf_of[b]))
            for a, b in col.edges)
        est = leaves[i].est_rows or 0.0
        return (0 if never_unique else 1, -est)

    for probe in sorted(range(n), key=probe_rank):
        placed = [probe]
        joins_plan: list[tuple[int, int, int]] = []  # (leaf, keypos, local)
        used_edges: set[int] = set()
        while len(placed) < n:
            advanced = False
            for ei, (a, b) in enumerate(col.edges):
                if ei in used_edges:
                    continue
                for probe_pos, build_pos in ((a, b), (b, a)):
                    pi, _ = leaf_of[probe_pos]
                    bi, blocal = leaf_of[build_pos]
                    if pi not in placed or bi in placed:
                        continue
                    if not key_ok(bi, blocal):
                        continue
                    pft = leaf_field_type(*leaf_of[probe_pos])
                    if pft.kind not in _FRAG_KEY_KINDS:
                        continue
                    placed.append(bi)
                    joins_plan.append((bi, probe_pos, blocal))
                    used_edges.add(ei)
                    advanced = True
                    break
                if advanced:
                    break
            if not advanced:
                break
        if len(placed) < n:
            continue

        # combined layout: placement order
        base_of_leaf: dict[int, int] = {}
        acc = 0
        for li in placed:
            base_of_leaf[li] = acc
            acc += len(leaves[li].dag.scan.col_offsets)
        remap = [base_of_leaf[leaf_of[p][0]] + leaf_of[p][1]
                 for p in range(col.width)]

        tables = []
        order_index = {li: k for k, li in enumerate(placed)}
        for li in placed:
            tr = leaves[li]
            filters = list(tr.dag.selection.conditions) \
                if tr.dag.selection else []
            tables.append(FragTable(
                tr.table, list(tr.dag.scan.col_offsets), filters,
                list(tr.dag.output_types)))
        joins = []
        for bi, probe_pos, blocal in joins_plan:
            joins.append(FragJoin(
                order_index[bi],
                Col(remap[probe_pos], leaf_field_type(*leaf_of[probe_pos])),
                blocal))
        # unused equality edges become plain selection conditions
        extra = []
        for ei, (a, b) in enumerate(col.edges):
            if ei not in used_edges:
                fa = leaf_field_type(*leaf_of[a])
                extra.append(Call("eq", [
                    Col(remap[a], fa), Col(remap[b], leaf_field_type(
                        *leaf_of[b]))], FieldType(TypeKind.BOOLEAN)))
        selection = [_remap_expr(c, remap) for c in col.conds] + extra
        frag = FragmentDAG(tables, joins, selection)
        for ppos, tr, blocal, kind in col.semis:
            frag.semis.append(FragSemi(
                FragTable(tr.table, list(tr.dag.scan.col_offsets),
                          list(tr.dag.selection.conditions)
                          if tr.dag.selection else [], _scan_types(tr)),
                Col(remap[ppos], leaf_field_type(*leaf_of[ppos])),
                blocal, kind))
        return frag, remap
    return None


def _match_agg_fragment(plan: PhysHashAgg, allow_single: bool = False
                        ) -> Optional[PhysHashAgg]:
    """HashAgg(complete) over [Projection?] over join tree -> final agg
    over a fragment read. allow_single admits one bare scan as a
    degenerate fragment (useful only with an hc TopN hint)."""
    # a projection between agg and joins (e.g. Q9's amount column)
    # composes into the agg expressions instead of blocking the match
    child = plan.children[0]
    proj = None
    if isinstance(child, PhysProjection) and \
            all(not _has_subq(e) for e in child.exprs):
        proj = child.exprs
        child = child.children[0]
    group_by = plan.group_by
    aggs = plan.aggs
    if proj is not None:
        group_by = [_subst_cols(g, proj) for g in group_by]
        aggs = [AggDesc(d.func,
                        None if d.arg is None else _subst_cols(d.arg, proj),
                        d.ftype, d.distinct, d.name, d.params)
                for d in plan.aggs]
    col = _collect_join_tree(child)
    if col is None or not agg_pushable(group_by, aggs) \
            or any(d.distinct for d in plan.aggs) \
            or any(d.func == "approx_count_distinct" for d in aggs):
        # hll sketches don't flow through the fragment partial machinery
        # (streamseg/hcagg are sum-shaped); the scan path carries them
        return None
    if len(col.leaves) == 1 and not col.semis:
        if not allow_single:
            return None
        tr = col.leaves[0]
        frag = FragmentDAG([FragTable(
            tr.table, list(tr.dag.scan.col_offsets),
            list(tr.dag.selection.conditions) if tr.dag.selection else [],
            list(tr.dag.output_types))], [],
            [c for c in col.conds])
        remap = list(range(col.width))
    else:
        asm = _try_assemble(col)
        if asm is None:
            return None
        frag, remap = asm
    frag.agg = DAGAggregation(
        [_remap_expr(g, remap) for g in group_by],
        [AggDesc(d.func,
                 None if d.arg is None else _remap_expr(d.arg, remap),
                 d.ftype, d.distinct, d.name, d.params)
         for d in aggs])
    fields = []
    for i, g in enumerate(group_by):
        fields.append(ResultField(f"gk#{i}", g.ftype))
    for i, d in enumerate(aggs):
        fields.append(ResultField(f"pv#{i}", _partial_val_type(d)))
        fields.append(ResultField(
            f"pc#{i}", FieldType(TypeKind.BIGINT, nullable=False)))
    frag.output_types = [f.ftype for f in fields]
    tr = PhysFragmentRead(frag, PlanSchema(fields))
    return PhysHashAgg("final", plan.group_by, plan.aggs,
                       plan.schema, [tr])


_HC_SCORE_FUNCS = ("sum", "count", "avg")

_FLIP = {"gt": "lt", "lt": "gt", "ge": "le", "le": "ge"}


def _having_entries(conds: list[PlanExpr], agg_node: PhysHashAgg):
    """Extract device-checkable HAVING predicates: comparisons of one
    SUM/COUNT aggregate against a constant, with the threshold converted
    to the aggregate's integer representation. Unconvertible conjuncts
    are simply not pushed — the host Selection re-applies every conjunct
    exactly, so the device filter only needs to be a superset."""
    from ..types.field_type import TypeKind
    from ..types.value import Decimal as Dec

    ngroups = len(agg_node.group_by)
    out = []
    for c in conds:
        if not (isinstance(c, Call) and c.op in _FLIP and
                len(c.args) == 2):
            continue
        a, b = c.args
        op = c.op
        if isinstance(a, Const) and isinstance(b, Col):
            a, b, op = b, a, _FLIP[op]
        if not (isinstance(a, Col) and isinstance(b, Const)):
            continue
        ai = a.idx - ngroups
        if ai < 0 or ai >= len(agg_node.aggs):
            continue
        d = agg_node.aggs[ai]
        if d.func not in ("sum", "count"):
            continue
        # normalize the constant to an exact Decimal (a Const's value is
        # already in ITS OWN ftype's integer representation)
        v = b.value
        try:
            if isinstance(v, Dec):
                dv = v
            elif b.ftype.kind == TypeKind.DECIMAL:
                dv = Dec(int(v), b.ftype.scale)
            elif isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            elif isinstance(v, int):
                dv = Dec(v, 0)
            else:
                dv = Dec.parse(repr(float(v)))
        except (TypeError, ValueError, OverflowError):
            continue
        # the device computes sums in the ARGUMENT's integer
        # representation (the partial layout); the final output type may
        # carry a different (wider) scale
        ft = d.arg.ftype if d.func == "sum" and d.arg is not None \
            else d.ftype
        sc = ft.scale if ft.kind == TypeKind.DECIMAL else 0
        thr = dv.rescale(sc).unscaled
        out.append((ai, op, thr))
    return out


def _resolve_hc_items(sort_node, proj, agg_node) -> Optional[list]:
    """Resolve EVERY sort item to ("group", gi, desc) / ("agg", ai, desc)
    for the fused final cut. Group items may be strings (the executor
    compares dictionary RANKS, order-preserving) but not floats;
    aggregate items must be SUM/COUNT/AVG — sums and counts recombine
    exactly from the candidate limb-pair digits, and AVG compares as the
    exact rational sum/cnt via base-4096 long division on device
    (copr/topnpack.avg_sort_keys; the executor gates it on the
    row-count bound that keeps every division step int32-exact).
    Returns None when any item falls outside that set."""
    ngroups = len(agg_node.group_by)
    out = []
    for e, desc in sort_node.items:
        if proj is not None:
            e = _subst_cols(e, proj.exprs)
        if not isinstance(e, Col):
            return None
        if e.idx < ngroups:
            if agg_node.group_by[e.idx].ftype.is_float:
                return None
            out.append(("group", e.idx, bool(desc)))
        else:
            ai = e.idx - ngroups
            if ai >= len(agg_node.aggs) or \
                    agg_node.aggs[ai].func not in \
                    ("sum", "count", "avg") or \
                    (agg_node.aggs[ai].arg is not None and
                     agg_node.aggs[ai].arg.ftype.is_float):
                return None
            out.append(("agg", ai, bool(desc)))
    return out


def _attach_hc(limit_node, sort_node, proj, agg_node,
               rewritten: PhysHashAgg) -> bool:
    """Resolve the TopN's primary sort item to a device score and attach
    the high-cardinality hint to the fragment under `rewritten`.
    Returns False (no mutation of `rewritten`) when the item cannot score
    on device."""
    frag = rewritten.children[0].frag
    e, desc = sort_node.items[0]
    if proj is not None:
        e = _subst_cols(e, proj.exprs)
    if not isinstance(e, Col):
        return False
    ngroups = len(agg_node.group_by)
    if e.idx < ngroups:
        g = agg_node.group_by[e.idx]
        # dictionary codes are not order-preserving; floats stay host
        if g.ftype.is_string or g.ftype.is_float:
            return False
        score = ("group", e.idx)
    else:
        ai = e.idx - ngroups
        if ai >= len(agg_node.aggs) or \
                agg_node.aggs[ai].func not in _HC_SCORE_FUNCS:
            return False
        score = ("agg", ai)
    frag.hc = HCTopN(score, desc, limit_node.limit)
    # full ORDER BY list resolvable -> the executor may run the fused
    # final cut (join+agg+topn) and return only the k winning groups
    frag.hc.items = _resolve_hc_items(sort_node, proj, agg_node)
    return True


def apply_fragments(plan: PhysicalPlan) -> PhysicalPlan:
    """Top-down, largest-pattern-first rewrite: an aggregation over a join
    tree must be matched at the AGG level before any inner join subtree is
    consumed as a row fragment (bottom-up would fuse the joins alone and
    strand the aggregation on the host). A matched fragment consumes its
    whole subtree; on no match, recurse into children."""
    # TopN over aggregation: Limit(Sort([Proj?](HashAgg))). Matched above
    # the agg so the fragment learns its consumer only needs the top-k
    # groups (high-cardinality candidate path); Sort/Limit stay on the
    # host and re-sort the (few) surviving groups exactly.
    sort_node = None
    if isinstance(plan, PhysLimit) and plan.offset == 0:
        node0 = plan.children[0]
        if isinstance(node0, PhysSort) and node0.items:
            sort_node = node0
        elif isinstance(node0, PhysProjection) and \
                all(isinstance(e, Col) for e in node0.exprs) and \
                isinstance(node0.children[0], PhysSort) and \
                node0.children[0].items:
            # ORDER BY a hidden column: the planner trims it with a
            # plain-Col projection between Limit and Sort — transparent
            # to the TopN patterns below
            sort_node = node0.children[0]
    if sort_node is not None:
        below = sort_node.children[0]
        proj = None
        if isinstance(below, PhysProjection) and \
                all(not _has_subq(x) for x in below.exprs):
            proj = below
            below = below.children[0]
        if isinstance(below, PhysHashAgg) and below.mode == "complete":
            rewritten = _match_agg_fragment(below, allow_single=True)
            if rewritten is not None:
                attached = _attach_hc(plan, sort_node, proj, below,
                                      rewritten)
                single = len(rewritten.children[0].frag.tables) == 1
                if attached or not single:
                    # a join fragment is worthwhile on its own; the
                    # degenerate single-table fragment only serves the hc
                    # hint — keep the original plan if it didn't attach
                    if proj is not None:
                        proj.children = [rewritten]
                    else:
                        sort_node.children = [rewritten]
                    return plan
        if isinstance(below, PhysHashAgg) and below.mode == "final" and \
                len(below.children) == 1 and \
                isinstance(below.children[0], PhysTableRead):
            # single-table agg already pushed into a CopDAG: lift it into a
            # degenerate fragment so the high-cardinality candidate path
            # can serve ORDER BY ... LIMIT k when the dense gate rejects
            tr = below.children[0]
            dag = tr.dag
            if dag.agg is not None and dag.scan.ranges is None and \
                    getattr(tr, "table", None) is not None and \
                    dag.topn is None and dag.limit is None:
                frag = FragmentDAG([FragTable(
                    tr.table, list(dag.scan.col_offsets),
                    list(dag.selection.conditions) if dag.selection else [],
                    _scan_types(tr))], [])
                frag.agg = dag.agg
                frag.output_types = list(dag.output_types)
                frag_tr = PhysFragmentRead(frag, tr.schema)
                old_children = below.children
                below.children = [frag_tr]
                if not _attach_hc(plan, sort_node, proj, below, below):
                    # the degenerate single-table fragment is useful ONLY
                    # with the hc hint — keep the CopDAG pushdown otherwise
                    below.children = old_children
                return plan

        # TopN over a bare join tree (no aggregation): fuse the joins as
        # a row fragment CARRYING the sort+limit, so the device's fused
        # program selects the top-n rows itself (multi-key composite,
        # copr/topnpack.py) and only n rows per batch/shard leave HBM.
        # The host Sort+Limit stay above and merge candidates exactly
        # (a trim projection between them composes into the sort items).
        # Float keys never pack (f32 order breaks exactness) and huge
        # limits would dominate the fetch, so both keep the plain row
        # fragment whose full bitmask the host replays.
        if plan.limit <= 16384:
            items = [( _subst_cols(e, proj.exprs) if proj is not None
                       else e, d) for e, d in sort_node.items]
            if all(expr_pushable(e) and not _has_subq(e)
                   and not e.ftype.is_float for e, _ in items):
                col = _collect_join_tree(below)
                if col is not None and len(col.leaves) > 1:
                    asm = _try_assemble(col)
                    if asm is not None:
                        frag, remap = asm
                        frag.out_map = list(remap)
                        frag.output_types = list(_tree_types(col))
                        frag.topn = DAGTopN(
                            [(_remap_expr(e, remap), bool(d))
                             for e, d in items], plan.limit)
                        tr = PhysFragmentRead(frag, below.schema)
                        if proj is not None:
                            proj.children = [tr]
                        else:
                            sort_node.children = [tr]
                        return plan

    # HAVING over an aggregation: push a safely-widened version of the
    # aggregate-vs-constant predicates into the fragment so the device
    # returns only (a superset of) the passing groups; this Selection
    # stays and re-applies the predicates exactly (reference: HAVING
    # evaluates above the aggregate, planner/core/logical_plan_builder.go
    # buildSelection over LogicalAggregation)
    if isinstance(plan, PhysSelection) and plan.children and \
            isinstance(plan.children[0], PhysHashAgg):
        below = plan.children[0]
        if below.mode == "complete":
            entries = _having_entries(plan.conditions, below)
            if entries:
                rewritten = _match_agg_fragment(below, allow_single=True)
                if rewritten is not None:
                    rewritten.children[0].frag.having = entries
                    plan.children = [rewritten]
                    return plan
        elif below.mode == "final" and len(below.children) == 1 and \
                isinstance(below.children[0], PhysTableRead):
            tr = below.children[0]
            dag = tr.dag
            entries = _having_entries(plan.conditions, below)
            huge = (tr.est_rows or 0) > 2e8
            if entries and not huge and dag.agg is not None and \
                    dag.scan.ranges is None and \
                    getattr(tr, "table", None) is not None and \
                    dag.topn is None and dag.limit is None:
                frag = FragmentDAG([FragTable(
                    tr.table, list(dag.scan.col_offsets),
                    list(dag.selection.conditions) if dag.selection
                    else [], _scan_types(tr))], [])
                frag.agg = dag.agg
                frag.output_types = list(dag.output_types)
                frag.having = entries
                below.children = [PhysFragmentRead(frag, tr.schema)]
                return plan

    if isinstance(plan, PhysHashAgg) and plan.mode == "complete":
        rewritten = _match_agg_fragment(plan)
        if rewritten is not None:
            return rewritten
        plan.children = [apply_fragments(c) for c in plan.children]
        return plan

    if isinstance(plan, (PhysSelection, PhysHashJoin)):
        col = _collect_join_tree(plan)
        if col is not None:
            asm = _try_assemble(col)
            if asm is not None:
                frag, remap = asm
                frag.out_map = list(remap)
                frag.output_types = [
                    leaf_ft for leaf_ft in _tree_types(col)]
                return PhysFragmentRead(frag, plan.schema)
    plan.children = [apply_fragments(c) for c in plan.children]
    return plan


def _tree_types(col: _Collected) -> list[FieldType]:
    out: list[FieldType] = []
    for tr in col.leaves:
        out.extend(tr.dag.output_types)
    return out


def _scan_types(tr: PhysTableRead) -> list[FieldType]:
    """Field types of the scanned columns (local order) from the table
    schema — dag.output_types holds the partial-agg layout when an agg was
    pushed, not the scan columns."""
    by_off = {c.offset: c.ftype for c in tr.table.columns}
    return [by_off[off] for off in tr.dag.scan.col_offsets]
