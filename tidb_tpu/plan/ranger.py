"""Ranger: derive index access ranges from conjunctive predicates.

Counterpart of the reference's util/ranger (detacher.go/points.go/ranger.go)
which detaches index-usable conditions and builds key ranges. This version
extracts *equality point* prefixes only — `col = const` and
`col IN (consts)` over a prefix of the index columns — which is the
high-confidence case that needs no statistics to justify: point lookups
beat a full columnar scan at any table size. Interval ranges join once the
statistics subsystem can estimate their selectivity (SURVEY.md §2
statistics/ inventory).

Inputs are resolved conjuncts over the *scan output schema*; `col_map`
translates Col.idx (position in the scan's output) to stored-table column
offsets, since column pruning may have re-mapped them.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Optional

from ..catalog.schema import IndexInfo, TableInfo
from .expr import Call, Col, Const, PlanExpr

# cap on the cartesian product of IN-lists across index columns — beyond
# this a scan is likely cheaper than many point probes (the reference
# similarly bounds ranges via MaxAccessPathCount/range mem quotas)
MAX_POINTS = 1024


@dataclass
class ScanRanges:
    """Access ranges on one index. Two forms:

    * points mode: every tuple is a full value list for the first
      len(tuple) index columns (physical domain, strings raw — encoded by
      the searcher)
    * interval mode: one (lo, hi, lo_incl, hi_incl) interval on the FIRST
      index column (numeric/temporal only; None bound = unbounded on that
      side) — chosen only when statistics justify it
    """

    index: IndexInfo
    points: list[tuple]
    interval: Optional[tuple] = None  # (lo, hi, lo_incl, hi_incl)

    def describe(self) -> str:
        if self.interval is not None:
            lo, hi, li, hi_i = self.interval
            lb = ("[" if li else "(") + (str(lo) if lo is not None else "-inf")
            ub = (str(hi) if hi is not None else "+inf") + ("]" if hi_i else ")")
            return f"index:{self.index.name} range {lb},{ub}"
        return (f"index:{self.index.name}"
                f"({len(self.points)} point{'s' if len(self.points) != 1 else ''})")


def _eq_values(cond: PlanExpr, col_map: dict[int, int]) -> Optional[
        tuple[int, list]]:
    """(table_offset, candidate values) if cond is `col = const` or
    `col IN (consts)` with non-NULL constants."""
    if not isinstance(cond, Call):
        return None
    if cond.op == "eq":
        a, b = cond.args
        if isinstance(a, Const) and isinstance(b, Col):
            a, b = b, a
        if isinstance(a, Col) and isinstance(b, Const) and b.value is not None:
            off = col_map.get(a.idx)
            if off is not None:
                return off, [b.value]
        return None
    if cond.op == "in_values" and isinstance(cond.args[0], Col):
        off = col_map.get(cond.args[0].idx)
        if off is None:
            return None
        # extra holds already-coerced physical values (builder strips Consts)
        vals = [c.value if isinstance(c, Const) else c
                for c in (cond.extra or [])]
        if not vals or any(v is None for v in vals):
            return None
        return off, vals
    return None


def extract_points(
    table: TableInfo,
    index: IndexInfo,
    conditions: list[PlanExpr],
    col_map: dict[int, int],
) -> Optional[ScanRanges]:
    """Longest equality-point prefix of `index` satisfiable from the
    conjuncts; None when the first index column has no equality."""
    by_off: dict[int, list] = {}
    for c in conditions:
        hit = _eq_values(c, col_map)
        if hit is None:
            continue
        off, vals = hit
        if off in by_off:
            # two equalities on one column: intersect candidate sets
            keep = [v for v in by_off[off] if v in vals]
            by_off[off] = keep
        else:
            by_off[off] = vals
    prefix: list[list] = []
    for off in index.col_offsets:
        vals = by_off.get(off)
        if vals is None:
            break
        prefix.append(vals)
    if not prefix:
        return None
    n_points = 1
    for vals in prefix:
        n_points *= len(vals)
        if n_points > MAX_POINTS:
            return None
    if n_points == 0:
        return ScanRanges(index, [])  # contradictory equalities: empty scan
    return ScanRanges(index, list(itertools.product(*prefix)))


_CMP_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}


def extract_interval(offset: int, conditions: list[PlanExpr],
                     col_map: dict[int, int]) -> Optional[tuple]:
    """Merged (lo, hi, lo_incl, hi_incl) interval on the column at table
    offset `offset` from comparison conjuncts; None when no comparison
    bounds it. BETWEEN arrives here already lowered to ge+le."""
    lo = hi = None
    lo_incl = hi_incl = True
    found = False
    for c in conditions:
        if not isinstance(c, Call) or c.op not in ("lt", "le", "gt", "ge"):
            continue
        a, b = c.args
        op = c.op
        if isinstance(a, Const) and isinstance(b, Col):
            a, b, op = b, a, _CMP_FLIP[op]
        if not (isinstance(a, Col) and isinstance(b, Const)):
            continue
        if col_map.get(a.idx) != offset or b.value is None:
            continue
        v = b.value
        found = True
        if op in ("gt", "ge"):
            incl = op == "ge"
            if lo is None or v > lo or (v == lo and not incl):
                lo, lo_incl = v, incl
        else:
            incl = op == "le"
            if hi is None or v < hi or (v == hi and not incl):
                hi, hi_incl = v, incl
    return (lo, hi, lo_incl, hi_incl) if found else None


def full_unique_match(table: TableInfo, ranges: ScanRanges) -> bool:
    """True when the ranges pin every column of a unique index — the
    point-get / batch-point-get case (reference:
    planner/core/point_get_plan.go:413)."""
    idx = ranges.index
    if not (idx.unique or idx.primary):
        return False
    return all(len(p) == len(idx.col_offsets) for p in ranges.points)
