"""Greedy join reordering over INNER/CROSS join groups.

Counterpart of the reference's join-reorder rule (reference:
planner/core/rule_join_reorder.go — the greedy solver joinReorderGreedy;
the DP solver is gated behind tidb_opt_join_reorder_threshold and not
replicated here). Runs after predicate pushdown, so comma-join WHERE
equalities have already become join eq_conditions.

Shape: flatten a maximal INNER/CROSS group into leaves + a global
condition pool, pick a left-deep order (LEADING hint wins, else greedy
smallest-first preferring connected leaves), rebuild the tree placing
each condition at the first join where its columns are available, and
restore the original column order with a projection so parents are
untouched. Reordering is stats-driven: with no row-count estimates and
no hint the syntactic order stands.
"""

from __future__ import annotations

from typing import Optional

from .expr import Call, Col, PlanExpr
from .logical import (
    LogicalJoin,
    LogicalPlan,
    LogicalScan,
    LogicalSelection,
    LogicalProjection,
)
from .schema import PlanSchema


def reorder_joins(plan: LogicalPlan, stats=None) -> LogicalPlan:
    if isinstance(plan, LogicalJoin) and plan.kind in ("INNER", "CROSS"):
        leaves, conds = _flatten(plan)
        leaves = [reorder_joins(l, stats) for l in leaves]
        hint = getattr(plan, "_leading_hint", None)
        if len(leaves) >= 2 and (hint or len(leaves) >= 3):
            order = _choose_order(leaves, conds, stats, hint)
            if order is not None and order != list(range(len(leaves))):
                return _rebuild(leaves, conds, order)
        return plan
    plan.children = [reorder_joins(c, stats) for c in plan.children]
    return plan


def _flatten(node: LogicalPlan):
    """(leaves, conds): leaves in syntactic order; conds as
    ('eq', gl, gr) | ('other', expr) with column positions global over
    the leaf concatenation."""
    if isinstance(node, LogicalJoin) and node.kind in ("INNER", "CROSS"):
        lleaves, lconds = _flatten(node.children[0])
        rleaves, rconds = _flatten(node.children[1])
        nleft = sum(len(l.schema) for l in lleaves)
        conds = list(lconds)
        for c in rconds:
            if c[0] == "eq":
                conds.append(("eq", c[1] + nleft, c[2] + nleft))
            else:
                conds.append(("other", _shift(c[1], nleft)))
        for li, ri in node.eq_conditions:
            conds.append(("eq", li, ri + nleft))
        for e in node.other_conditions:
            conds.append(("other", e))
        return lleaves + rleaves, conds
    return [node], []


def _shift(e: PlanExpr, by: int) -> PlanExpr:
    if isinstance(e, Col):
        return Col(e.idx + by, e.ftype, e.name)
    if isinstance(e, Call):
        return Call(e.op, [_shift(a, by) for a in e.args], e.ftype, e.extra)
    return e


def _leaf_alias(leaf: LogicalPlan) -> Optional[str]:
    if isinstance(leaf, LogicalScan):
        return leaf.alias
    if isinstance(leaf, LogicalSelection) and \
            isinstance(leaf.children[0], LogicalScan):
        return leaf.children[0].alias
    return None


def _leaf_rows(leaf: LogicalPlan, stats) -> Optional[float]:
    scan = leaf
    n_conds = 0
    if isinstance(leaf, LogicalSelection) and \
            isinstance(leaf.children[0], LogicalScan):
        n_conds = len(leaf.conditions)
        scan = leaf.children[0]
    if isinstance(scan, LogicalScan) and stats is not None:
        ts = stats.table_stats(scan.table.id)
        if ts is not None:
            # conjunct-count damping stands in for real selectivity
            # (the reference multiplies per-conjunct selectivities,
            # statistics/selectivity.go)
            return max(ts.row_count * (0.25 ** n_conds), 1.0)
    return None


def _choose_order(leaves, conds, stats, hint) -> Optional[list[int]]:
    n = len(leaves)
    bases = _bases(leaves)

    def leaf_of(g: int) -> int:
        lo = 0
        while lo + 1 < n and bases[lo + 1] <= g:
            lo += 1
        return lo

    # leaf adjacency through eq conditions
    adj: dict[int, set[int]] = {i: set() for i in range(n)}
    for c in conds:
        if c[0] == "eq":
            a, b = leaf_of(c[1]), leaf_of(c[2])
            if a != b:
                adj[a].add(b)
                adj[b].add(a)

    ests = [_leaf_rows(l, stats) for l in leaves]
    order: list[int] = []
    if hint:
        by_alias = {_leaf_alias(l): i for i, l in enumerate(leaves)}
        for name in hint:
            i = by_alias.get(name)
            if i is None or i in order:
                return None  # unknown alias: hint can't be honored
            order.append(i)
    if not order:
        if any(e is None for e in ests):
            return None  # no stats: syntactic order stands
        order.append(min(range(n), key=lambda i: (ests[i], i)))
    remaining = [i for i in range(n) if i not in order]
    cur_rows = max((e for i in order for e in [ests[i]] if e is not None),
                   default=1.0)
    while remaining:
        placed = set(order)

        def cost(i: int) -> tuple[float, int]:
            e = ests[i] if ests[i] is not None else 1e5
            connected = bool(adj[i] & placed)
            return (e if connected else cur_rows * e, i)

        nxt = min(remaining, key=cost)
        remaining.remove(nxt)
        order.append(nxt)
        e = ests[nxt] if ests[nxt] is not None else 1e5
        cur_rows = max(cur_rows, e)
    return order


def _bases(leaves) -> list[int]:
    bases = []
    acc = 0
    for l in leaves:
        bases.append(acc)
        acc += len(l.schema)
    return bases


def _rebuild(leaves, conds, order) -> LogicalPlan:
    """Left-deep tree in `order`; conditions placed at the first join
    where their columns are available; a projection restores the
    original output column order."""
    n = len(leaves)
    bases = _bases(leaves)
    widths = [len(l.schema) for l in leaves]

    def leaf_of(g: int) -> int:
        lo = 0
        while lo + 1 < n and bases[lo + 1] <= g:
            lo += 1
        return lo

    new_base: dict[int, int] = {}
    acc = 0
    for i in order:
        new_base[i] = acc
        acc += widths[i]

    def new_pos(g: int) -> int:
        i = leaf_of(g)
        return new_base[i] + (g - bases[i])

    def cols_of(c) -> set[int]:
        if c[0] == "eq":
            return {c[1], c[2]}
        out: set[int] = set()
        _collect(c[1], out)
        return out

    pending = list(conds)
    first = order[0]
    cur = leaves[first]
    # conditions entirely within the first leaf become a selection on it
    mine_idx = [k for k, c in enumerate(pending)
                if c[0] == "other"
                and cols_of(c)
                and all(leaf_of(g) == first for g in cols_of(c))]
    if mine_idx:
        remapped = [_remap_global(pending[k][1], new_pos)
                    for k in mine_idx]
        cur = LogicalSelection(remapped, cur.schema, [cur])
        drop = set(mine_idx)
        pending = [c for k, c in enumerate(pending) if k not in drop]

    placed = {first}
    for i in order[1:]:
        nleft = len(cur.schema)
        placed.add(i)
        eq_here: list[tuple[int, int]] = []
        others_here: list[PlanExpr] = []
        rest = []
        for c in pending:
            gs = cols_of(c)
            if any(leaf_of(g) not in placed for g in gs):
                rest.append(c)
                continue
            if c[0] == "eq":
                a, b = c[1], c[2]
                if leaf_of(a) == i:
                    a, b = b, a
                if leaf_of(b) == i and leaf_of(a) != i:
                    eq_here.append((new_pos(a), new_pos(b) - nleft))
                else:  # both sides already inside cur (or inside i)
                    lt = leaves[leaf_of(a)].schema.fields[
                        a - bases[leaf_of(a)]].ftype
                    others_here.append(Call(
                        "eq", [Col(new_pos(a), lt), Col(new_pos(b), lt)],
                        _bool_type()))
            else:
                others_here.append(_remap_global(c[1], new_pos))
        pending = rest
        kind = "INNER" if (eq_here or others_here) else "CROSS"
        schema = PlanSchema(cur.schema.fields + leaves[i].schema.fields)
        cur = LogicalJoin(kind, eq_here, others_here, schema,
                          [cur, leaves[i]])
    assert not pending, "join reorder lost conditions"

    total = sum(widths)
    orig_fields = []
    for i in range(n):
        orig_fields.extend(leaves[i].schema.fields)
    exprs = [Col(new_pos(g), orig_fields[g].ftype, orig_fields[g].name)
             for g in range(total)]
    if all(e.idx == g for g, e in enumerate(exprs)):
        return cur
    return LogicalProjection(exprs, PlanSchema(orig_fields), [cur])


def _collect(e: PlanExpr, out: set[int]) -> None:
    if isinstance(e, Col):
        out.add(e.idx)
    elif isinstance(e, Call):
        for a in e.args:
            _collect(a, out)


def _remap_global(e: PlanExpr, new_pos) -> PlanExpr:
    if isinstance(e, Col):
        return Col(new_pos(e.idx), e.ftype, e.name)
    if isinstance(e, Call):
        return Call(e.op, [_remap_global(a, new_pos) for a in e.args],
                    e.ftype, e.extra)
    return e


def _bool_type():
    from ..types.field_type import FieldType, TypeKind
    return FieldType(TypeKind.BIGINT)


__all__ = ["reorder_joins"]
