"""Point-plan fast path: the TryFastPlan bypass for high-QPS OLTP.

Counterpart of the reference's point-get fast plan (reference:
planner/core/point_get_plan.go:413 TryFastPlan + executor/point_get.go):
an autocommit SELECT/UPDATE/DELETE whose WHERE is a full PK (or unique
key) equality — and a literal-only INSERT VALUES — skips the whole
parse->plan->optimize->dispatch pipeline and executes directly against
the KV/MVCC layer:

* zero coprocessor involvement (the session's lazy `cop` property is
  never touched, so no JAX backend, no staging, no kernels);
* zero planner work on a plan-cache hit (the session LRU stores the
  recognized FastPlan under the same `_plan_cache_key` the physical
  plan cache uses, including the prepared-statement `#stmt{id}` keys);
* the row read is O(1): txn-visible deltas scanned newest-first, then
  the epoch's lazy HandleIndex — never a table-sized snapshot mask.

Recognition is deliberately conservative: anything it does not
understand (partitions, views, unique secondary indexes on INSERT,
expressions beyond simple row-local arithmetic, bindings in force)
returns None and the unchanged slow path answers. The device-work-free
contract is pinned by tests/test_fast_path_lint.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..chunk.column import _encode_scalar, decode_scalar
from ..kv.memdb import TOMBSTONE
from ..sql import ast

# schemas whose tables are virtual/refreshed views — never point-read
SYSTEM_SCHEMAS = frozenset({
    "information_schema", "performance_schema", "metrics_schema",
    "mysql",
})


@dataclass
class FastPlan:
    """A recognized point statement, bound to its literal values (the
    plan-cache key embeds the literals, so a cached FastPlan replays
    byte-identically)."""

    kind: str  # 'get' | 'update' | 'delete' | 'insert'
    info: Any  # TableInfo
    # point key: either the int-handle PK value...
    handle: Optional[int] = None
    # ...or a unique-key equality (host values, index lookup at exec)
    index: Any = None
    key_values: Optional[tuple] = None
    # extra `col = literal` conjuncts checked against the fetched row
    residual: list = field(default_factory=list)  # [(offset, host value)]
    # SELECT output
    select_offsets: list = field(default_factory=list)
    names: list = field(default_factory=list)
    ftypes: list = field(default_factory=list)
    limit: Optional[int] = None
    # UPDATE assignments: [(offset, expr AST)] evaluated row-locally
    assigns: list = field(default_factory=list)
    # INSERT: pre-extracted host value rows + target column offsets
    insert_rows: list = field(default_factory=list)
    col_order: list = field(default_factory=list)


# ---------------------------------------------------------------------------
# recognition
# ---------------------------------------------------------------------------

def try_plan(session, stmt) -> Optional[FastPlan]:
    """Recognize a point statement; None routes to the slow path.
    Session-level eligibility (autocommit, no user, sysvar) is the
    caller's job — this is the pure statement-shape check."""
    try:
        if isinstance(stmt, ast.SelectStmt):
            return _plan_select(session, stmt)
        if isinstance(stmt, ast.UpdateStmt):
            return _plan_update(session, stmt)
        if isinstance(stmt, ast.DeleteStmt):
            return _plan_delete(session, stmt)
        if isinstance(stmt, ast.InsertStmt):
            return _plan_insert(session, stmt)
    except Exception:  # noqa: BLE001 — recognition must never fail the
        return None    # statement; anything odd just takes the slow path
    return None


def _table_info(session, tn) -> Optional[Any]:
    if not isinstance(tn, ast.TableName):
        return None
    db = (tn.db or session.current_db).lower()
    if db in SYSTEM_SCHEMAS:
        return None
    try:
        info = session.catalog.table(db, tn.name)
    except KeyError:
        return None  # unknown table OR a view: slow path explains
    if getattr(info, "partition", None) is not None:
        return None  # partition routing stays on the planned path
    return info


def _literal_value(e) -> tuple[bool, Any]:
    """(ok, host value) for a Literal node (NULL -> bail: a point key
    compared with NULL never matches and MySQL's type rules around it
    are the slow path's business)."""
    if not isinstance(e, ast.Literal):
        return False, None
    if e.value is None:
        return False, None
    return True, e.value


def _split_eq_conjuncts(where, tn) -> Optional[dict]:
    """WHERE as {column name -> literal host value}, or None when any
    conjunct is not a plain `col = literal` over this table."""
    out: dict[str, Any] = {}
    stack = [where]
    alias = (tn.alias or tn.name).lower()
    while stack:
        e = stack.pop()
        if isinstance(e, ast.BinaryOp) and e.op == "AND":
            stack.append(e.left)
            stack.append(e.right)
            continue
        if not (isinstance(e, ast.BinaryOp) and e.op == "="):
            return None
        col, lit = e.left, e.right
        if isinstance(lit, ast.ColumnRef):
            col, lit = lit, col
        if not isinstance(col, ast.ColumnRef):
            return None
        if col.table is not None and col.table.lower() != alias:
            return None
        ok, v = _literal_value(lit)
        if not ok:
            return None
        name = col.name.lower()
        if name in out and out[name] != v:
            return None  # contradictory duplicates: let the planner
        out[name] = v
    return out


def _extract_key(session, info, tn, where) -> Optional[tuple]:
    """(handle, index, key_values, residual) from a full-key equality
    WHERE, or None."""
    if where is None:
        return None
    eq = _split_eq_conjuncts(where, tn)
    if not eq:
        return None
    by_offset: dict[int, Any] = {}
    for name, v in eq.items():
        c = info.column_by_name(name)
        if c is None:
            return None
        by_offset[c.offset] = v
    pk_off = info.pk_handle_offset
    if pk_off is not None and pk_off in by_offset:
        v = by_offset.pop(pk_off)
        if isinstance(v, bool) or not isinstance(v, int):
            return None  # non-int handle literal: slow-path coercion
        residual = _residuals(info, by_offset)
        if residual is None:
            return None
        return int(v), None, None, residual
    for ix in info.indices:
        if not ((ix.unique or ix.primary) and ix.visible):
            continue
        if all(off in by_offset for off in ix.col_offsets):
            vals = tuple(by_offset[off] for off in ix.col_offsets)
            # exact-comparable key types only: the searcher probes
            # PHYSICAL values, and only ints (identity) and strings
            # (dictionary lookup) need no coercion — decimal/temporal/
            # float keys keep the slow path's conversion rules
            ok = all(
                (info.columns[off].ftype.is_integer
                 and isinstance(v, int) and not isinstance(v, bool))
                or (info.columns[off].ftype.is_string
                    and isinstance(v, str))
                for off, v in zip(ix.col_offsets, vals))
            if not ok:
                return None
            for off in ix.col_offsets:
                by_offset.pop(off)
            residual = _residuals(info, by_offset)
            if residual is None:
                return None
            return None, ix, vals, residual
    return None


def _residuals(info, by_offset: dict) -> Optional[list]:
    """Leftover equality conjuncts as decoded-row comparisons; only
    exact-comparable types (ints/strings) qualify — float/temporal
    equality keeps the slow path's coercion rules."""
    out = []
    for off, v in by_offset.items():
        ft = info.columns[off].ftype
        if ft.is_string and isinstance(v, str):
            out.append((off, v))
        elif ft.is_integer and isinstance(v, int) \
                and not isinstance(v, bool):
            out.append((off, v))
        else:
            return None
    return out


def _plan_select(session, stmt: ast.SelectStmt) -> Optional[FastPlan]:
    if (stmt.group_by or stmt.having is not None or stmt.order_by
            or stmt.distinct or stmt.for_update
            or stmt.into_outfile is not None or stmt.hints
            or stmt.offset):
        return None
    if stmt.limit is not None and stmt.limit < 1:
        return None
    info = _table_info(session, stmt.from_)
    if info is None:
        return None
    key = _extract_key(session, info, stmt.from_, stmt.where)
    if key is None:
        return None
    handle, index, key_values, residual = key
    offsets: list[int] = []
    names: list[str] = []
    alias = (stmt.from_.alias or stmt.from_.name).lower()
    for f in stmt.fields:
        if f.expr is None:
            if f.wildcard_table is not None and \
                    f.wildcard_table.lower() != alias:
                return None
            for c in info.columns:
                offsets.append(c.offset)
                names.append(c.name)
            continue
        if not isinstance(f.expr, ast.ColumnRef):
            return None
        if f.expr.table is not None and f.expr.table.lower() != alias:
            return None
        c = info.column_by_name(f.expr.name)
        if c is None:
            return None
        offsets.append(c.offset)
        names.append(f.alias or f.expr.name)
    if not offsets:
        return None
    return FastPlan(
        kind="get", info=info, handle=handle, index=index,
        key_values=key_values, residual=residual,
        select_offsets=offsets, names=names,
        ftypes=[info.columns[o].ftype for o in offsets],
        limit=stmt.limit)


# assignment RHS: literals, same-table column refs and +,-,* arithmetic
# over them (the sysbench `SET k = k + 1` shape); everything else —
# functions, subqueries, division's type rules — keeps the slow path
_ARITH_OPS = frozenset({"+", "-", "*"})


def _assign_expr_ok(info, e, depth: int = 0) -> bool:
    if depth > 4:
        return False
    if isinstance(e, ast.Literal):
        # inside arithmetic only numeric literals qualify — string/
        # temporal coercion ('1' + 1) is the slow path's business
        return e.value is None or (
            isinstance(e.value, (int, float))
            and not isinstance(e.value, bool))
    if isinstance(e, ast.ColumnRef):
        c = info.column_by_name(e.name)
        if c is None:
            return False
        return c.ftype.is_integer or c.ftype.is_float
    if isinstance(e, ast.BinaryOp) and e.op in _ARITH_OPS:
        return _assign_expr_ok(info, e.left, depth + 1) and \
            _assign_expr_ok(info, e.right, depth + 1)
    return False


def _eval_assign(info, e, row_host) -> Any:
    """Evaluate a recognized assignment expression against the fetched
    row's host values (SQL NULL propagates)."""
    if isinstance(e, ast.Literal):
        return e.value
    if isinstance(e, ast.ColumnRef):
        return row_host[info.column_by_name(e.name).offset]
    left = _eval_assign(info, e.left, row_host)
    right = _eval_assign(info, e.right, row_host)
    if left is None or right is None:
        return None
    if e.op == "+":
        return left + right
    if e.op == "-":
        return left - right
    return left * right


def _unique_offsets(info) -> set:
    out = set()
    if info.pk_handle_offset is not None:
        out.add(info.pk_handle_offset)
    for ix in info.indices:
        if ix.unique or ix.primary:
            out.update(ix.col_offsets)
    return out


def _plan_update(session, stmt: ast.UpdateStmt) -> Optional[FastPlan]:
    info = _table_info(session, stmt.table)
    if info is None:
        return None
    key = _extract_key(session, info, stmt.table, stmt.where)
    if key is None:
        return None
    handle, index, key_values, residual = key
    uniq = _unique_offsets(info)
    assigns = []
    for a in stmt.assignments:
        c = info.column_by_name(a.column.name)
        if c is None or c.offset in uniq:
            return None  # key/unique rewrites need the constraint path
        if isinstance(a.value, ast.Literal):
            pass  # literal into ANY column type: encode coerces
        elif not _assign_expr_ok(info, a.value) or not (
                c.ftype.is_integer or c.ftype.is_float
                or c.ftype.is_decimal):
            # expression results flow only into numeric columns; the
            # slow path owns string/temporal coercion rules
            return None
        assigns.append((c.offset, a.value))
    if not assigns:
        return None
    return FastPlan(kind="update", info=info, handle=handle,
                    index=index, key_values=key_values,
                    residual=residual, assigns=assigns)


def _plan_delete(session, stmt: ast.DeleteStmt) -> Optional[FastPlan]:
    info = _table_info(session, stmt.table)
    if info is None:
        return None
    key = _extract_key(session, info, stmt.table, stmt.where)
    if key is None:
        return None
    handle, index, key_values, residual = key
    return FastPlan(kind="delete", info=info, handle=handle,
                    index=index, key_values=key_values,
                    residual=residual)


def _plan_insert(session, stmt: ast.InsertStmt) -> Optional[FastPlan]:
    if stmt.select is not None or stmt.is_replace or stmt.on_dup:
        return None
    if not stmt.rows:
        return None
    info = _table_info(session, stmt.table)
    if info is None:
        return None
    # unique SECONDARY indexes need the full _UniqueChecker/guard-key
    # machinery; the pk-handle dup check below covers handle-PK tables
    for ix in info.indices:
        if (ix.unique or ix.primary) and \
                list(ix.col_offsets) != [info.pk_handle_offset]:
            return None
    col_order = _insert_offsets(info, stmt.columns)
    if col_order is None:
        return None
    rows = []
    for value_row in stmt.rows:
        if len(value_row) != len(col_order):
            return None  # slow path raises the typed 1136
        vals = []
        for e in value_row:
            if not isinstance(e, ast.Literal):
                return None
            vals.append(e.value)
        rows.append(vals)
    return FastPlan(kind="insert", info=info, insert_rows=rows,
                    col_order=col_order)


def _insert_offsets(info, names) -> Optional[list]:
    if names is None:
        return list(range(info.num_columns))
    out = []
    for n in names:
        c = info.column_by_name(n)
        if c is None:
            return None
        out.append(c.offset)
    return out


# ---------------------------------------------------------------------------
# execution — straight against the KV/MVCC + columnar-delta layer
# ---------------------------------------------------------------------------

def execute(session, fp: FastPlan):
    """Run a FastPlan inside the session's normal autocommit txn
    machinery (same staging/retry/commit as the slow path — only the
    plan/dispatch pipeline is bypassed)."""
    if fp.kind == "get":
        from ..util.governor import PRI_POINT
        with session._admission(PRI_POINT):
            return session._run_in_txn(lambda: _exec_get(session, fp))
    if fp.kind == "update":
        return session._run_in_txn(lambda: _exec_update(session, fp))
    if fp.kind == "delete":
        return session._run_in_txn(lambda: _exec_delete(session, fp))
    assert fp.kind == "insert"
    return session._run_in_txn(lambda: _exec_insert(session, fp))


def _point_row(storage, store, handle: int, ts: int):
    """Visible physical row tuple for `handle` at `ts`, or None.

    O(deltas tail + one HandleIndex probe) — never materializes a
    snapshot. Same fold-seqlock discipline as Transaction.snapshot: a
    read racing an active columnar fold falls back to the commit lock."""
    for _ in range(4):
        seq = storage._fold_seq
        if seq & 1:
            break  # fold active: serialize on the lock below
        row = _point_row_unfenced(store, handle, ts)
        if storage._fold_seq == seq:
            return row
    with storage._commit_lock:
        return _point_row_unfenced(store, handle, ts)


def _point_row_unfenced(store, handle: int, ts: int):
    with store._lock:
        # newest-first over the un-compacted tail: the first version at
        # or below ts wins (deltas are commit-ts ordered)
        for commit_ts, h, row in reversed(store.deltas):
            if h == handle and commit_ts <= ts:
                return None if row is TOMBSTONE else row
        epoch = store.epoch
    pos = epoch.handle_pos.get(handle)
    if pos is None:
        return None
    out = []
    for off in range(len(epoch.columns)):
        valid = epoch.valids[off]
        if valid is not None and not valid[pos]:
            out.append(None)
        else:
            v = epoch.columns[off][pos]
            out.append(v.item() if hasattr(v, "item") else v)
    return tuple(out)


def _lookup_row(session, fp: FastPlan, txn):
    """(handle, physical row) for the plan's key at the txn's read ts,
    or (None, None). Residual equality conjuncts are applied here."""
    storage = session.storage
    store = storage.table_store(fp.info.id)
    ts = txn.stmt_read_ts if txn.stmt_read_ts is not None \
        else txn.start_ts
    if fp.handle is not None:
        handle = fp.handle
        row = _point_row(storage, store, handle, ts)
    else:
        # unique-key point: one index probe over a snapshot (the
        # searcher path the slow point read uses); still host-only
        from ..store.index import IndexSearcher
        snap = txn.snapshot(fp.info.id)
        hits = IndexSearcher(store, snap, fp.index).eq(fp.key_values)
        if len(hits) == 0:
            return None, None
        handle = int(hits[0])
        row = _point_row(storage, store, handle, ts)
    if row is None:
        return None, None
    for off, want in fp.residual:
        ft = fp.info.columns[off].ftype
        got = decode_scalar(ft, row[off], store.dictionaries[off]) \
            if row[off] is not None else None
        if got != want:
            return None, None
    return handle, row


def _exec_get(session, fp: FastPlan):
    from ..session.session import ResultSet

    txn = session._ensure_txn()
    handle, row = _lookup_row(session, fp, txn)
    heat = getattr(session.storage, "heat", None)
    if heat is not None and heat.enabled and row is not None:
        # OLTP point reads land on the keyspace heatmap by record key
        # (bytes ~ column count: physical width is not rematerialized
        # on this path, and the heat plane wants relative skew)
        from ..kv import tablecodec
        heat.note_read(tablecodec.record_key(fp.info.id, int(handle)),
                       rows=1, nbytes=8 * fp.info.num_columns)
    rows: list[tuple] = []
    if row is not None:
        store = session.storage.table_store(fp.info.id)
        rows.append(tuple(
            decode_scalar(fp.info.columns[o].ftype, row[o],
                          store.dictionaries[o])
            if row[o] is not None else None
            for o in fp.select_offsets))
    session._found_rows = len(rows)
    return ResultSet(fp.names, rows, column_types=list(fp.ftypes))


def _exec_update(session, fp: FastPlan):
    from ..errno import ER_BAD_NULL
    from ..session.session import ResultSet, SQLError

    txn = session._ensure_txn()
    handle, row = _lookup_row(session, fp, txn)
    if row is None:
        return ResultSet([], [], affected=0)
    info = fp.info
    store = session.storage.table_store(info.id)
    # host view of the row for expression RHS (decoded lazily would
    # save little: assignment exprs touch few columns, tables are thin)
    row_host = [
        decode_scalar(info.columns[i].ftype, row[i],
                      store.dictionaries[i]) if row[i] is not None
        else None
        for i in range(info.num_columns)]
    new_phys = list(row)
    for off, expr in fp.assigns:
        col = info.columns[off]
        v = _eval_assign(info, expr, row_host)
        if v is None:
            if not col.ftype.nullable:
                raise SQLError(f"column {col.name} cannot be null",
                               errno=ER_BAD_NULL)
            new_phys[off] = None
        else:
            new_phys[off] = _encode_scalar(col.ftype, v,
                                           store.dictionaries[off])
    txn.set_row(info.id, handle, tuple(new_phys))
    return ResultSet([], [], affected=1)


def _exec_delete(session, fp: FastPlan):
    from ..session.session import ResultSet

    txn = session._ensure_txn()
    handle, row = _lookup_row(session, fp, txn)
    if row is None:
        return ResultSet([], [], affected=0)
    txn.delete_row(fp.info.id, handle)
    return ResultSet([], [], affected=1)


def _exec_insert(session, fp: FastPlan):
    from ..errno import ER_DUP_ENTRY
    from ..session.session import ResultSet, SQLError

    info = fp.info
    txn = session._ensure_txn()
    storage = session.storage
    store = storage.table_store(info.id)
    seen: set[int] = set()  # handles written by THIS statement
    count = 0
    for values in fp.insert_rows:
        full = session._complete_row(info, fp.col_order, list(values),
                                     store)
        handle = session._row_handle(info, full, store)
        enc = store.encode_row(full)
        if info.pk_handle_offset is not None:
            dup = handle in seen or _point_row(
                storage, store, handle, txn.start_ts) is not None
            if dup:
                raise SQLError(
                    f"Duplicate entry '{handle}' for key 'PRIMARY'",
                    errno=ER_DUP_ENTRY)
        txn.set_row(info.id, handle, enc)
        seen.add(handle)
        count += 1
    return ResultSet([], [], affected=count)
