"""Owner election: who runs DDL jobs and background maintenance.

Counterpart of the reference's owner package (reference:
owner/manager.go:93 — etcd campaign/session for the DDL owner, with
the single-node mockManager at owner/mock.go:35 used by every
clusterless test). Two implementations matching the deployment shapes
this framework actually has:

* MockOwnerManager — single process: always the owner (the reference's
  mock.go pattern; in-memory stores use this).
* FileLockOwnerManager — multiple processes sharing one durable
  directory: POSIX flock on <dir>/<key>.lock. The kernel releases the
  lock when the holder dies, which is the liveness property etcd
  leases provide in the reference (a crashed owner's lease expires and
  a standby takes over).

A true multi-host DCN election (raft/etcd equivalent) plugs in behind
the same three-method surface when a distributed meta service exists.
"""

from __future__ import annotations

import os
import threading
from typing import Optional


class MockOwnerManager:
    """Single-process owner: campaigns always succeed (reference:
    owner/mock.go:35 mockManager)."""

    def __init__(self, key: str = "ddl") -> None:
        self.key = key
        self._lock = threading.RLock()  # serialize same-process workers
        self._owner_thread: Optional[int] = None
        self._depth = 0

    def campaign(self, timeout_s: float = 10.0) -> bool:
        if not self._lock.acquire(timeout=timeout_s):
            return False
        self._owner_thread = threading.get_ident()
        self._depth += 1
        return True

    def try_campaign(self) -> bool:
        if not self._lock.acquire(blocking=False):
            return False
        self._owner_thread = threading.get_ident()
        self._depth += 1
        return True

    def resign(self) -> None:
        try:
            self._depth -= 1
            if self._depth <= 0:
                self._owner_thread = None
                self._depth = 0
            self._lock.release()
        except RuntimeError:
            pass

    def is_owner(self) -> bool:
        """Is the CALLING thread the current owner (reference:
        mockManager.IsOwner)."""
        return self._owner_thread == threading.get_ident()

    def close(self) -> None:
        pass

    def __enter__(self):
        if not self.campaign():
            raise TimeoutError(f"could not become {self.key} owner")
        return self

    def __exit__(self, *exc) -> None:
        self.resign()


class FileLockOwnerManager:
    """flock-based owner for processes sharing a durable directory.

    Crash-safe: the OS drops the flock with the process, so ownership
    fails over without a TTL dance (reference analog: etcd lease expiry
    at owner/manager.go:124)."""

    def __init__(self, dir_path: str, key: str = "ddl") -> None:
        self.key = key
        self.path = os.path.join(dir_path, f"{key}.owner.lock")
        self._fd: Optional[int] = None
        self._thread_lock = threading.RLock()

    def _open(self) -> int:
        if self._fd is None:
            self._fd = os.open(self.path, os.O_CREAT | os.O_RDWR, 0o644)
        return self._fd

    def try_campaign(self) -> bool:
        import fcntl

        if not self._thread_lock.acquire(blocking=False):
            return False
        try:
            fcntl.flock(self._open(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            os.truncate(self._fd, 0)
            os.pwrite(self._fd, str(os.getpid()).encode(), 0)
            return True
        except OSError:
            self._thread_lock.release()
            return False

    def campaign(self, timeout_s: float = 10.0) -> bool:
        import time

        deadline = time.monotonic() + timeout_s
        while True:
            if self.try_campaign():
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def resign(self) -> None:
        import fcntl

        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
            except OSError:
                pass
        try:
            self._thread_lock.release()
        except RuntimeError:
            pass

    def owner_pid(self) -> Optional[int]:
        try:
            with open(self.path) as f:
                return int(f.read().strip() or 0) or None
        except (OSError, ValueError):
            return None

    def close(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def __enter__(self):
        if not self.campaign():
            raise TimeoutError(f"could not become {self.key} owner")
        return self

    def __exit__(self, *exc) -> None:
        self.resign()


def owner_manager(path: Optional[str], key: str = "ddl"):
    """The deployment-appropriate manager (reference: tests take the
    mock, real clusters take etcd — main.go wires by store type)."""
    if path is None:
        return MockOwnerManager(key)
    return FileLockOwnerManager(path, key)


__all__ = ["MockOwnerManager", "FileLockOwnerManager", "owner_manager"]
