"""Observability: metrics, runtime stats, slow-query log.

The reference wires these into its core loop rather than bolting them on:
~150 Prometheus collectors registered centrally (reference:
metrics/metrics.go:61), per-operator runtime stats feeding EXPLAIN ANALYZE
(util/execdetails/execdetails.go), and a slow-query log with per-stage
durations (executor/adapter.go:866 LogSlowQuery), queryable back through
the server. Same shape here: one process-wide registry, a per-statement
RuntimeStatsColl the engine fills, and an in-memory slow-log ring exposed
via SHOW SLOW QUERIES and the HTTP status port.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Optional

log = logging.getLogger("tidb_tpu.slowlog")


class Counter:
    __slots__ = ("name", "help", "_values", "_lock")

    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self):
        with self._lock:
            return list(self._values.items())


class Histogram:
    """Fixed-bucket latency histogram (Prometheus-style cumulative)."""

    BUCKETS = (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)
    __slots__ = ("name", "help", "_counts", "_sum", "_total", "_lock")

    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._counts = [0] * (len(self.BUCKETS) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._total += 1
            for i, b in enumerate(self.BUCKETS):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self):
        with self._lock:
            return list(self._counts), self._sum, self._total


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "") -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Counter):
                out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} counter")
                for key, v in sorted(m.samples()):
                    lbl = ",".join(f'{k}="{val}"' for k, val in key)
                    out.append(f"{m.name}{{{lbl}}} {v:g}" if lbl
                               else f"{m.name} {v:g}")
            else:
                counts, total_sum, total = m.snapshot()
                out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} histogram")
                acc = 0
                for b, c in zip(m.BUCKETS, counts):
                    acc += c
                    out.append(f'{m.name}_bucket{{le="{b}"}} {acc}')
                out.append(f'{m.name}_bucket{{le="+Inf"}} {total}')
                out.append(f"{m.name}_sum {total_sum:g}")
                out.append(f"{m.name}_count {total}")
        return "\n".join(out) + "\n"


# ---- statement digests (statements_summary) ---------------------------------

class StatementsSummary:
    """Aggregated per-digest statement statistics (reference:
    util/stmtsummary/statement_summary.go feeding
    INFORMATION_SCHEMA.STATEMENTS_SUMMARY). Digest = hash of the
    literal-normalized SQL; the ring is capped like the reference's
    max-stmt-count."""

    MAX_DIGESTS = 200

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}

    @staticmethod
    def normalize(sql: str) -> str:
        """Literals -> '?' through the real lexer (reference:
        parser.Normalize)."""
        from .sql.lexer import Lexer, TokenKind

        out: list[str] = []
        try:
            for t in Lexer(sql).tokens():
                if t.kind == TokenKind.EOF:
                    break
                if t.kind in (TokenKind.INT, TokenKind.DECIMAL,
                              TokenKind.FLOAT, TokenKind.STRING):
                    out.append("?")
                else:
                    out.append(t.text.lower()
                               if t.kind == TokenKind.KEYWORD else t.text)
        except Exception:
            return sql.strip()[:256]
        return " ".join(out)

    def record(self, sql: str, db: str, duration_s: float,
               rows: int = 0, failed: bool = False) -> None:
        import hashlib

        norm = self.normalize(sql)
        digest = hashlib.sha256(norm.encode()).hexdigest()[:32]
        now = time.strftime("%Y-%m-%d %H:%M:%S")
        ms = duration_s * 1e3
        with self._lock:
            ent = self._entries.get(digest)
            if ent is None:
                if len(self._entries) >= self.MAX_DIGESTS:
                    # evict the least-executed digest (cheap approximation
                    # of the reference's LRU-by-last-seen)
                    victim = min(self._entries,
                                 key=lambda k: self._entries[k]["exec_count"])
                    del self._entries[victim]
                ent = self._entries[digest] = {
                    "digest": digest, "schema_name": db,
                    "digest_text": norm[:512],
                    "sample_text": sql[:512],
                    "exec_count": 0, "errors": 0,
                    "sum_latency_ms": 0.0, "max_latency_ms": 0.0,
                    "sum_rows": 0,
                    "first_seen": now, "last_seen": now,
                }
            ent["exec_count"] += 1
            ent["errors"] += 1 if failed else 0
            ent["sum_latency_ms"] += ms
            ent["max_latency_ms"] = max(ent["max_latency_ms"], ms)
            ent["sum_rows"] += rows
            ent["last_seen"] = now

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


# ---- per-server observability state ----------------------------------------

class Observability:
    """One server's metrics + slow log + statement summaries. Owned by
    the Storage (one per 'cluster'), so two servers in one process don't
    clobber each other's counters — the round-2 verdict's module-global
    singleton problem. The module-level DEFAULT keeps process-wide
    consumers (the shared device coprocessor) working."""

    def __init__(self) -> None:
        self.metrics = Registry()
        self.queries = self.metrics.counter(
            "tidb_queries_total", "statements executed, by type")
        self.query_errors = self.metrics.counter(
            "tidb_query_errors_total", "statements that raised")
        self.query_seconds = self.metrics.histogram(
            "tidb_query_duration_seconds", "statement wall time")
        self.commits = self.metrics.counter(
            "tidb_commits_total", "transaction commits")
        self.conflicts = self.metrics.counter(
            "tidb_write_conflicts_total", "commit-time write conflicts")
        self.connections = self.metrics.counter(
            "tidb_connections_total", "wire connections accepted")
        self.slow_counter = self.metrics.counter(
            "tidb_slow_queries_total",
            "statements over the slow-log threshold")
        self._slow_log: deque = deque(maxlen=SLOW_LOG_MAX)
        self._slow_lock = threading.Lock()
        self.statements = StatementsSummary()

    def record_slow(self, sql: str, db: str, duration_s: float) -> None:
        self.slow_counter.inc()
        ent = {
            "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
            "db": db,
            "duration_ms": round(duration_s * 1e3, 1),
            "sql": sql if len(sql) <= 4096 else sql[:4096] + "...",
        }
        with self._slow_lock:
            self._slow_log.append(ent)
        # the reference writes a structured slow log line (adapter.go:866)
        log.warning("slow query (%.1fms) db=%s: %s",
                    duration_s * 1e3, db, ent["sql"][:400])

    def slow_queries(self) -> list[dict]:
        with self._slow_lock:
            return list(self._slow_log)

    def render(self) -> str:
        return self.metrics.render()


SLOW_LOG_MAX = 512
DEFAULT_SLOW_THRESHOLD_MS = 300

# process-wide default instance: code without a Storage in reach
DEFAULT = Observability()
METRICS = DEFAULT.metrics
QUERIES = DEFAULT.queries
QUERY_ERRORS = DEFAULT.query_errors
QUERY_SECONDS = DEFAULT.query_seconds
COMMITS = DEFAULT.commits
CONFLICTS = DEFAULT.conflicts
CONNECTIONS = DEFAULT.connections
SLOW_QUERIES = DEFAULT.slow_counter

# genuinely process-global metrics (ONE device per process) live in
# their own registry so /metrics can concatenate it with a server's
# registry without duplicating metric families
PROCESS_METRICS = Registry()
COPR_REQUESTS = PROCESS_METRICS.counter(
    "tidb_copr_requests_total",
    "coprocessor executions, by engine (device / host fallback)")
FRAG_FALLBACKS = PROCESS_METRICS.counter(
    "tidb_copr_fragment_fallbacks_total",
    "device-fragment gate rejections, by reason")


# ---- cross-layer span trees (TRACE) -----------------------------------------

class Span:
    """One timed span with children; durations in seconds."""

    __slots__ = ("name", "start", "end", "children", "note")

    def __init__(self, name: str, start: float) -> None:
        self.name = name
        self.start = start
        self.end = start
        self.children: list["Span"] = []
        self.note: Optional[str] = None


_span_tls = threading.local()


class SpanCollector:
    """Hierarchical span collection across layers (reference:
    sessionctx + tracing spans rendered by executor/trace.go; spans are
    opened by the layer doing the work — session, planner, executor,
    coprocessor client, storage — and nest via a thread-local stack).

    Activation is thread-local and scoped: when no collector is active,
    `span()` is a no-op `yield`, so the production path pays one TLS
    read per instrumented site."""

    def __init__(self, name: str = "trace") -> None:
        self.t0 = time.perf_counter()
        self.root = Span(name, 0.0)
        self._stack = [self.root]

    def __enter__(self) -> "SpanCollector":
        _span_tls.coll = self
        return self

    def __exit__(self, *exc) -> None:
        self.root.end = time.perf_counter() - self.t0
        _span_tls.coll = None

    def rows(self) -> list[tuple]:
        """(indented name, start_ms, duration_ms) depth-first."""
        out: list[tuple] = []

        def walk(s: Span, depth: int) -> None:
            label = "  " * depth + s.name + (
                f" [{s.note}]" if s.note else "")
            out.append((label, round(s.start * 1e3, 3),
                        round((s.end - s.start) * 1e3, 3)))
            for c in s.children:
                walk(c, depth + 1)

        walk(self.root, 0)
        return out


class _SpanCtx:
    __slots__ = ("name", "coll", "sp")

    def __init__(self, name: str) -> None:
        self.name = name
        self.coll = getattr(_span_tls, "coll", None)
        self.sp: Optional[Span] = None

    def __enter__(self) -> Optional[Span]:
        c = self.coll
        if c is None:
            return None
        self.sp = Span(self.name, time.perf_counter() - c.t0)
        c._stack[-1].children.append(self.sp)
        c._stack.append(self.sp)
        return self.sp

    def __exit__(self, *exc) -> None:
        c = self.coll
        if c is not None and self.sp is not None:
            self.sp.end = time.perf_counter() - c.t0
            c._stack.pop()


def span(name: str) -> _SpanCtx:
    """`with obs.span("copr.execute"):` — nests under the active
    collector's current span; no-op without an active TRACE."""
    return _SpanCtx(name)


# ---- per-statement runtime stats (EXPLAIN ANALYZE) --------------------------

class RuntimeStatsColl:
    """Per-plan-node runtime stats (reference:
    util/execdetails/execdetails.go RuntimeStatsColl): inclusive wall
    time, output rows, and which engine served a leaf (device kernel vs
    host fallback, with the gate's reason)."""

    def __init__(self) -> None:
        self.nodes: dict[int, dict] = {}

    def record(self, plan, seconds: float, rows: int,
               engine: Optional[str] = None) -> None:
        ent = self.nodes.setdefault(id(plan), {
            "time": 0.0, "rows": 0, "loops": 0, "engine": None})
        ent["time"] += seconds
        ent["rows"] += rows
        ent["loops"] += 1
        if engine:
            ent["engine"] = engine

    def for_plan(self, plan) -> Optional[dict]:
        return self.nodes.get(id(plan))


# ---- module-level delegates (default instance) ------------------------------

def record_slow(sql: str, db: str, duration_s: float) -> None:
    DEFAULT.record_slow(sql, db, duration_s)


def slow_queries() -> list[dict]:
    return DEFAULT.slow_queries()
