"""Observability: metrics, runtime stats, slow-query log.

The reference wires these into its core loop rather than bolting them on:
~150 Prometheus collectors registered centrally (reference:
metrics/metrics.go:61), per-operator runtime stats feeding EXPLAIN ANALYZE
(util/execdetails/execdetails.go), and a slow-query log with per-stage
durations (executor/adapter.go:866 LogSlowQuery), queryable back through
the server. Same shape here: one process-wide registry, a per-statement
RuntimeStatsColl the engine fills, and an in-memory slow-log ring exposed
via SHOW SLOW QUERIES and the HTTP status port.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Optional

log = logging.getLogger("tidb_tpu.slowlog")


class Counter:
    __slots__ = ("name", "help", "_values", "_lock")

    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self):
        with self._lock:
            return list(self._values.items())


class Histogram:
    """Fixed-bucket latency histogram (Prometheus-style cumulative)."""

    BUCKETS = (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)
    __slots__ = ("name", "help", "_counts", "_sum", "_total", "_lock")

    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._counts = [0] * (len(self.BUCKETS) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._total += 1
            for i, b in enumerate(self.BUCKETS):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self):
        with self._lock:
            return list(self._counts), self._sum, self._total


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "") -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Counter):
                out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} counter")
                for key, v in sorted(m.samples()):
                    lbl = ",".join(f'{k}="{val}"' for k, val in key)
                    out.append(f"{m.name}{{{lbl}}} {v:g}" if lbl
                               else f"{m.name} {v:g}")
            else:
                counts, total_sum, total = m.snapshot()
                out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} histogram")
                acc = 0
                for b, c in zip(m.BUCKETS, counts):
                    acc += c
                    out.append(f'{m.name}_bucket{{le="{b}"}} {acc}')
                out.append(f'{m.name}_bucket{{le="+Inf"}} {total}')
                out.append(f"{m.name}_sum {total_sum:g}")
                out.append(f"{m.name}_count {total}")
        return "\n".join(out) + "\n"


METRICS = Registry()

QUERIES = METRICS.counter("tidb_queries_total",
                          "statements executed, by type")
QUERY_ERRORS = METRICS.counter("tidb_query_errors_total",
                               "statements that raised")
QUERY_SECONDS = METRICS.histogram("tidb_query_duration_seconds",
                                  "statement wall time")
COPR_REQUESTS = METRICS.counter(
    "tidb_copr_requests_total",
    "coprocessor executions, by engine (device / host fallback)")
COMMITS = METRICS.counter("tidb_commits_total", "transaction commits")
CONFLICTS = METRICS.counter("tidb_write_conflicts_total",
                            "commit-time write conflicts")
CONNECTIONS = METRICS.counter("tidb_connections_total",
                              "wire connections accepted")
SLOW_QUERIES = METRICS.counter("tidb_slow_queries_total",
                               "statements over the slow-log threshold")


# ---- per-statement runtime stats (EXPLAIN ANALYZE) --------------------------

class RuntimeStatsColl:
    """Per-plan-node runtime stats (reference:
    util/execdetails/execdetails.go RuntimeStatsColl): inclusive wall
    time, output rows, and which engine served a leaf (device kernel vs
    host fallback, with the gate's reason)."""

    def __init__(self) -> None:
        self.nodes: dict[int, dict] = {}

    def record(self, plan, seconds: float, rows: int,
               engine: Optional[str] = None) -> None:
        ent = self.nodes.setdefault(id(plan), {
            "time": 0.0, "rows": 0, "loops": 0, "engine": None})
        ent["time"] += seconds
        ent["rows"] += rows
        ent["loops"] += 1
        if engine:
            ent["engine"] = engine

    def for_plan(self, plan) -> Optional[dict]:
        return self.nodes.get(id(plan))


# ---- slow query log ---------------------------------------------------------

SLOW_LOG_MAX = 512
_slow_log: deque = deque(maxlen=SLOW_LOG_MAX)
_slow_lock = threading.Lock()

DEFAULT_SLOW_THRESHOLD_MS = 300


def record_slow(sql: str, db: str, duration_s: float) -> None:
    SLOW_QUERIES.inc()
    ent = {
        "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
        "db": db,
        "duration_ms": round(duration_s * 1e3, 1),
        "sql": sql if len(sql) <= 4096 else sql[:4096] + "...",
    }
    with _slow_lock:
        _slow_log.append(ent)
    # the reference writes a structured slow log line (adapter.go:866)
    log.warning("slow query (%.1fms) db=%s: %s",
                duration_s * 1e3, db, ent["sql"][:400])


def slow_queries() -> list[dict]:
    with _slow_lock:
        return list(_slow_log)
