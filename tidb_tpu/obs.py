"""Observability: metrics, runtime stats, slow-query log.

The reference wires these into its core loop rather than bolting them on:
~150 Prometheus collectors registered centrally (reference:
metrics/metrics.go:61), per-operator runtime stats feeding EXPLAIN ANALYZE
(util/execdetails/execdetails.go), and a slow-query log with per-stage
durations (executor/adapter.go:866 LogSlowQuery), queryable back through
the server. Same shape here: one process-wide registry, a per-statement
RuntimeStatsColl the engine fills, and an in-memory slow-log ring exposed
via SHOW SLOW QUERIES and the HTTP status port.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Optional

log = logging.getLogger("tidb_tpu.slowlog")


class Counter:
    __slots__ = ("name", "help", "_values", "_lock")

    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self):
        with self._lock:
            return list(self._values.items())


class Gauge:
    """A value that can go up and down (Prometheus gauge). Labeled like
    Counter; `set` overwrites, `inc`/`dec` adjust — device-telemetry
    consumers use both (transfer bytes accumulate on the hot path,
    buffer bytes / RSS are overwritten by the sampler probes)."""

    __slots__ = ("name", "help", "_values", "_lock")

    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def get(self, **labels) -> float:
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._values.get(key, 0.0)

    def samples(self):
        with self._lock:
            return list(self._values.items())


class Histogram:
    """Fixed-bucket latency histogram (Prometheus-style cumulative).

    Optionally labeled: `observe(v, stage="compile")` keeps one bucket
    series per label set (reference: prometheus HistogramVec). The
    sub-millisecond buckets exist because dispatch stages (column-cache
    hits, jit-cache hits, staging of small epochs) live in the
    10µs–1ms range — with a 1ms floor they all collapse into bucket 0
    and the histogram says nothing."""

    BUCKETS = (0.00001, 0.00005, 0.0001, 0.00025, 0.0005,
               0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)
    __slots__ = ("name", "help", "buckets", "_series", "_lock")

    def __init__(self, name: str, help_: str, buckets=None) -> None:
        self.name = name
        self.help = help_
        # custom bucket bounds for non-latency distributions (e.g.
        # group-commit batch sizes); default: the latency ladder
        self.buckets = tuple(buckets) if buckets else self.BUCKETS
        # label tuple -> [counts list, sum, total]
        self._series: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0]
            s[1] += v
            s[2] += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    s[0][i] += 1
                    return
            s[0][-1] += 1

    def snapshot(self, **labels):
        """(counts, sum, total) for one label set (default: unlabeled)."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return [0] * (len(self.buckets) + 1), 0.0, 0
            return list(s[0]), s[1], s[2]

    def series(self):
        with self._lock:
            if not self._series:
                # a never-observed histogram still renders its (zero)
                # unlabeled series, like a prometheus client would
                return [((), [0] * (len(self.buckets) + 1), 0.0, 0)]
            return [(key, list(s[0]), s[1], s[2])
                    for key, s in sorted(self._series.items())]


def _label_name(name: str, key: tuple) -> str:
    """'name{k="v",...}' (or bare name) for a sorted label-key tuple."""
    lbl = ",".join(f'{k}="{val}"' for k, val in key)
    return f"{name}{{{lbl}}}" if lbl else name


def split_sample_name(name: str, family: str) -> Optional[str]:
    """Inverse of _label_name for one family: 'fam{k="v"}' -> 'k="v"',
    bare 'fam' -> '', a sample of any OTHER family -> None. The one
    parser of the flattened-sample convention — metrics_schema and the
    inspection rules both read flat_samples output through it."""
    if name == family:
        return ""
    if name.startswith(family + "{") and name.endswith("}"):
        return name[len(family) + 1:-1]
    return None


def _fmt_value(v: float) -> str:
    """Full-precision exposition value: %g's 6 significant digits would
    quantize byte-valued gauges (RSS ~1e9) so hard that scrape-to-scrape
    deltas vanish; integers render as integers, floats via repr."""
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_)
                self._metrics[name] = m
            elif not isinstance(m, Counter):
                raise TypeError(
                    f"metric {name} already registered as "
                    f"{type(m).__name__}")
            return m

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Gauge(name, help_)
                self._metrics[name] = m
            elif not isinstance(m, Gauge):
                raise TypeError(
                    f"metric {name} already registered as "
                    f"{type(m).__name__}")
            return m

    def histogram(self, name: str, help_: str = "",
                  buckets=None) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets=buckets)
                self._metrics[name] = m
            elif not isinstance(m, Histogram):
                raise TypeError(
                    f"metric {name} already registered as "
                    f"{type(m).__name__}")
            return m

    def families(self) -> list[str]:
        with self._lock:
            return list(self._metrics)

    def flat_samples(self) -> list[tuple[str, float]]:
        """Counter/gauge samples flattened to ('name{l=\"v\"}', value)
        pairs — the one flattening shared by the metrics-history
        sampler and the diag plane's load snapshot."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: list[tuple[str, float]] = []
        for m in metrics:
            if not isinstance(m, (Counter, Gauge)):
                continue  # histograms live on /metrics only
            for key, v in m.samples():
                out.append((_label_name(m.name, key), v))
        return out

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, (Counter, Gauge)):
                out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} "
                           f"{'gauge' if isinstance(m, Gauge) else 'counter'}")
                for key, v in sorted(m.samples()):
                    out.append(f"{_label_name(m.name, key)} "
                               f"{_fmt_value(v)}")
            else:
                out.append(f"# HELP {m.name} {m.help}")
                out.append(f"# TYPE {m.name} histogram")
                for key, counts, total_sum, total in m.series():
                    extra = "".join(f',{k}="{val}"' for k, val in key)
                    acc = 0
                    for b, c in zip(m.buckets, counts):
                        acc += c
                        out.append(
                            f'{m.name}_bucket{{le="{b}"{extra}}} {acc}')
                    out.append(
                        f'{m.name}_bucket{{le="+Inf"{extra}}} {total}')
                    lbl = ",".join(f'{k}="{val}"' for k, val in key)
                    sfx = f"{{{lbl}}}" if lbl else ""
                    out.append(f"{m.name}_sum{sfx} {_fmt_value(total_sum)}")
                    out.append(f"{m.name}_count{sfx} {total}")
        return "\n".join(out) + "\n"


# ---- statement digests (statements_summary) ---------------------------------

class StatementsSummary:
    """Aggregated per-digest statement statistics (reference:
    util/stmtsummary/statement_summary.go feeding
    INFORMATION_SCHEMA.STATEMENTS_SUMMARY). Digest = hash of the
    literal-normalized SQL; the ring is capped like the reference's
    max-stmt-count."""

    MAX_DIGESTS = 200
    # raw text -> normalized text memo: identical statement replay (the
    # OLTP point path's plan-cache-hit shape) skips the second lex of
    # every statement; bounded so random-literal floods cannot grow it.
    # Process-wide on purpose — normalization is a pure text function.
    NORM_CACHE_CAP = 512
    _norm_cache: dict = {}

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}

    @classmethod
    def normalize(cls, sql: str) -> str:
        cached = cls._norm_cache.get(sql)
        if cached is not None:
            return cached
        norm = cls._normalize_uncached(sql)
        if len(cls._norm_cache) >= cls.NORM_CACHE_CAP:
            # wholesale reset beats per-entry LRU bookkeeping here: the
            # cache exists for replayed text, which repopulates in one
            # statement each
            cls._norm_cache.clear()
        cls._norm_cache[sql] = norm
        return norm

    @staticmethod
    def _normalize_uncached(sql: str) -> str:
        """Literals -> '?' through the real lexer (reference:
        parser.Normalize)."""
        from .sql.lexer import Lexer, TokenKind

        out: list[str] = []
        try:
            for t in Lexer(sql).tokens():
                if t.kind == TokenKind.EOF:
                    break
                if t.kind in (TokenKind.INT, TokenKind.DECIMAL,
                              TokenKind.FLOAT, TokenKind.STRING):
                    out.append("?")
                else:
                    out.append(t.text.lower()
                               if t.kind == TokenKind.KEYWORD else t.text)
        except Exception:
            return sql.strip()[:256]
        return " ".join(out)

    def record(self, sql: str, db: str, duration_s: float,
               rows: int = 0, failed: bool = False,
               mem_peak: int = 0, spill_count: int = 0) -> None:
        import hashlib

        norm = self.normalize(sql)
        digest = hashlib.sha256(norm.encode()).hexdigest()[:32]
        now = time.strftime("%Y-%m-%d %H:%M:%S")
        ms = duration_s * 1e3
        with self._lock:
            ent = self._entries.get(digest)
            if ent is None:
                if len(self._entries) >= self.MAX_DIGESTS:
                    # evict the least-executed digest (cheap approximation
                    # of the reference's LRU-by-last-seen)
                    victim = min(self._entries,
                                 key=lambda k: self._entries[k]["exec_count"])
                    del self._entries[victim]
                ent = self._entries[digest] = {
                    "digest": digest, "schema_name": db,
                    "digest_text": norm[:512],
                    "sample_text": sql[:512],
                    "exec_count": 0, "errors": 0,
                    "sum_latency_ms": 0.0, "max_latency_ms": 0.0,
                    "sum_rows": 0,
                    "max_mem_bytes": 0, "sum_spill_count": 0,
                    "first_seen": now, "last_seen": now,
                }
            ent["exec_count"] += 1
            ent["errors"] += 1 if failed else 0
            ent["sum_latency_ms"] += ms
            ent["max_latency_ms"] = max(ent["max_latency_ms"], ms)
            ent["sum_rows"] += rows
            # per-digest working-set high-water + spills (reference:
            # stmtsummary's MaxMem / SumDisk columns)
            ent["max_mem_bytes"] = max(ent.get("max_mem_bytes", 0),
                                       int(mem_peak))
            ent["sum_spill_count"] = ent.get("sum_spill_count", 0) \
                + int(spill_count)
            ent["last_seen"] = now

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


# ---- Top SQL: continuous per-digest resource attribution --------------------

class TopSQL:
    """Windowed per-digest resource attribution (reference: TiDB's Top
    SQL — util/topsql collecting per-statement CPU/exec metrics into
    time buckets keyed by SQL digest, resource attribution that runs in
    PRODUCTION, not only under EXPLAIN ANALYZE).

    Shape: a ring of `n_windows` time buckets, each holding a digest ->
    entry map capped at `digest_cap`; statements past the cap fold into
    one "(other)" overflow entry so a digest storm cannot grow the map.
    Every completed statement feeds one record() with its wall time,
    per-stage dispatch seconds (PR 2's StageRecorder), per-operator
    wall/stage/transfer attribution, rows, and admission/governor
    outcomes.

    Disabled (the default) it is ZERO allocation on the statement path:
    record() returns before touching the lock or building anything, and
    the session call site checks `enabled` before assembling arguments.
    Thread-safe: one lock guards the ring; entries are plain dicts
    mutated under it."""

    DEFAULT_WINDOW_S = 60
    DEFAULT_WINDOWS = 6
    DEFAULT_DIGEST_CAP = 50
    OTHER = "(other)"
    STMT = "(stmt)"
    SESSION_OP = "(session)"

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 n_windows: int = DEFAULT_WINDOWS,
                 digest_cap: int = DEFAULT_DIGEST_CAP,
                 enabled: bool = False) -> None:
        self.enabled = bool(enabled)
        self.window_s = max(float(window_s), 1.0)
        self.digest_cap = max(int(digest_cap), 1)
        self._lock = threading.Lock()
        self._buckets: deque = deque(maxlen=max(int(n_windows), 1))

    def configure(self, enabled: Optional[bool] = None,
                  window_s: Optional[float] = None,
                  digest_cap: Optional[int] = None,
                  n_windows: Optional[int] = None) -> None:
        """Apply the performance.topsql-* config knobs (safe while
        running; a shrunk ring drops the oldest windows)."""
        if enabled is not None:
            self.enabled = bool(enabled)
        if window_s is not None:
            self.window_s = max(float(window_s), 1.0)
        if digest_cap is not None:
            self.digest_cap = max(int(digest_cap), 1)
        if n_windows is not None:
            with self._lock:
                self._buckets = deque(self._buckets,
                                      maxlen=max(int(n_windows), 1))

    def _bucket_locked(self, now: float) -> dict:
        win = int(now - (now % self.window_s))
        for b in reversed(self._buckets):
            if b["start"] == win:
                return b
        last = self._buckets[-1] if self._buckets else None
        if last is not None and win < last["start"]:
            # clock went backwards past the ring: charge the newest
            # window rather than resurrecting evicted history
            return last
        b = {"start": win, "digests": {}, "other": None}
        self._buckets.append(b)
        return b

    @staticmethod
    def _new_entry(digest: str, digest_text: str, db: str) -> dict:
        return {"digest": digest, "digest_text": digest_text,
                "schema_name": db, "exec_count": 0, "errors": 0,
                "sum_wall_s": 0.0, "max_wall_s": 0.0, "sum_rows": 0,
                "sheds": 0, "kills": 0,
                "stages": {}, "op_wall": {}, "op_stages": {},
                "op_bytes": {}, "op_mesh": {}, "waits": {}}

    def record(self, digest: str, digest_text: str, db: str,
               wall_s: float, stages: Optional[dict] = None,
               op_wall: Optional[dict] = None,
               op_stages: Optional[dict] = None,
               op_bytes: Optional[dict] = None,
               rows: int = 0, failed: bool = False, shed: bool = False,
               killed: bool = False,
               op_mesh: Optional[dict] = None,
               waits: Optional[dict] = None,
               now: Optional[float] = None) -> None:
        if not self.enabled:
            return
        ts = time.time() if now is None else float(now)
        with self._lock:
            b = self._bucket_locked(ts)
            ent = b["digests"].get(digest)
            if ent is None:
                if len(b["digests"]) < self.digest_cap:
                    ent = b["digests"][digest] = self._new_entry(
                        digest, digest_text, db)
                else:
                    # overflow: fold into the bucket's "(other)" entry
                    if b["other"] is None:
                        b["other"] = self._new_entry(
                            self.OTHER, self.OTHER, "")
                    ent = b["other"]
            ent["exec_count"] += 1
            ent["errors"] += 1 if failed else 0
            ent["sheds"] += 1 if shed else 0
            ent["kills"] += 1 if killed else 0
            ent["sum_wall_s"] += wall_s
            ent["max_wall_s"] = max(ent["max_wall_s"], wall_s)
            ent["sum_rows"] += int(rows)
            if stages:
                st = ent["stages"]
                for k, v in stages.items():
                    st[k] = st.get(k, 0.0) + v
            if op_wall:
                ow = ent["op_wall"]
                for k, v in op_wall.items():
                    ow[k] = ow.get(k, 0.0) + v
            if op_stages:
                target = ent["op_stages"]
                for op, d in op_stages.items():
                    td = target.setdefault(op, {})
                    for k, v in d.items():
                        td[k] = td.get(k, 0.0) + v
            if op_bytes:
                ob = ent["op_bytes"]
                for k, v in op_bytes.items():
                    ob[k] = ob.get(k, 0) + int(v)
            if op_mesh:
                # per-operator max-shard share of sharded dispatches
                # (the mesh flight recorder's balance signal): keep the
                # worst share seen for the digest
                om = ent.setdefault("op_mesh", {})
                for k, v in op_mesh.items():
                    om[k] = max(om.get(k, 0.0), float(v))
            if waits:
                # typed wait-state split — what makes a window
                # attributable to its dominant wait state
                tw = ent.setdefault("waits", {})
                for k, v in waits.items():
                    tw[k] = tw.get(k, 0.0) + v

    def snapshot(self) -> list[dict]:
        """Deep-copied buckets, oldest first."""
        import copy
        with self._lock:
            return [copy.deepcopy(b) for b in self._buckets]

    def clear(self) -> None:
        with self._lock:
            self._buckets.clear()

    @staticmethod
    def attributed_seconds(ent: dict) -> float:
        """Statement seconds attributed to SOMETHING named: exclusive
        per-operator wall plus the dispatch stages recorded outside any
        operator frame (plan_build et al under '(session)'). Operator
        wall and op-stage splits overlap by construction (the stages
        are the split OF the operator wall), so only the session-scoped
        stages add."""
        return sum(ent["op_wall"].values()) + sum(
            ent["op_stages"].get(TopSQL.SESSION_OP, {}).values())

    def table_rows(self) -> list[list]:
        """information_schema.tidb_top_sql rows: newest window first,
        digests by total wall desc; per digest one '(stmt)' summary row
        then one row per operator (heaviest first)."""
        rows: list[list] = []
        for b in reversed(self.snapshot()):
            win = time.strftime("%Y-%m-%d %H:%M:%S",
                                time.localtime(b["start"]))
            ents = sorted(b["digests"].values(),
                          key=lambda e: -e["sum_wall_s"])
            if b["other"] is not None:
                ents.append(b["other"])
            for e in ents:
                attributed = self.attributed_seconds(e)
                mesh = e.get("op_mesh") or {}
                # dominant wait state of the digest's window: which
                # typed wait (if any) owned the wall — 'state:frac'
                dst, dfrac = WaitProfile.dominant(e)
                dom = f"{dst}:{dfrac:.2f}" if dst else ""
                rows.append([
                    win, e["digest"], e["digest_text"], self.STMT,
                    e["exec_count"], round(e["sum_wall_s"] * 1e3, 3),
                    round(attributed * 1e3, 3),
                    sum(e["op_bytes"].values()),
                    fmt_stages(e["stages"])[:256], e["sum_rows"],
                    e["sheds"], e["kills"],
                    round(max(mesh.values(), default=0.0), 4), dom])
                ops = dict(e["op_wall"])
                sess = e["op_stages"].get(self.SESSION_OP)
                if sess:
                    ops[self.SESSION_OP] = sum(sess.values())
                for op in sorted(ops, key=lambda o: -ops[o]):
                    rows.append([
                        win, e["digest"], e["digest_text"], op,
                        e["exec_count"], round(e["sum_wall_s"] * 1e3, 3),
                        round(ops[op] * 1e3, 3),
                        e["op_bytes"].get(op, 0),
                        fmt_stages(e["op_stages"].get(op))[:256],
                        e["sum_rows"], e["sheds"], e["kills"],
                        round(mesh.get(op, 0.0), 4), ""])
        return rows

    def top_by_device(self, n: int = 5) -> list[dict]:
        """Top digests by device time (kernel + device_get stage sums)
        across the whole ring — the /status quick view. Reduces to
        scalars directly under the lock instead of deep-copying the
        ring: monitoring pollers hit this every few seconds and must
        not lengthen the lock hold against the statement feed."""
        acc: dict[str, dict] = {}
        with self._lock:
            for b in self._buckets:
                ents = list(b["digests"].values())
                if b["other"] is not None:
                    ents.append(b["other"])
                for e in ents:
                    dev = e["stages"].get("kernel", 0.0) + \
                        e["stages"].get("device_get", 0.0)
                    a = acc.get(e["digest"])
                    if a is None:
                        a = acc[e["digest"]] = {
                            "digest": e["digest"],
                            "digest_text": e["digest_text"],
                            "exec_count": 0, "device_ms": 0.0,
                            "wall_ms": 0.0, "transfer_bytes": 0}
                    a["exec_count"] += e["exec_count"]
                    a["device_ms"] += dev * 1e3
                    a["wall_ms"] += e["sum_wall_s"] * 1e3
                    a["transfer_bytes"] += sum(e["op_bytes"].values())
        out = sorted(acc.values(), key=lambda a: -a["device_ms"])[:n]
        for a in out:
            a["device_ms"] = round(a["device_ms"], 3)
            a["wall_ms"] = round(a["wall_ms"], 3)
        return out


# ---- wait-state profile: windowed per-digest wait attribution ---------------

class WaitProfile:
    """Windowed per-digest typed-wait attribution — the continuous
    (production, not only EXPLAIN ANALYZE) aggregation of WaitLedger
    totals, same ring shape as TopSQL: `n_windows` time buckets, each a
    digest -> entry map capped at `digest_cap` with an "(other)"
    overflow fold. Feeds information_schema.tidb_wait_profile, the
    /debug/waitprofile endpoint and the dominant-wait inspection rule.

    Disabled (the default) it is ZERO cost on the statement path:
    record() returns before the lock, and the session neither installs
    a WaitLedger nor assembles arguments (performance.wait-profile-
    enabled arms it, SIGHUP-hot-reloadable)."""

    DEFAULT_WINDOW_S = 60
    DEFAULT_WINDOWS = 6
    DEFAULT_DIGEST_CAP = 50
    OTHER = "(other)"

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 n_windows: int = DEFAULT_WINDOWS,
                 digest_cap: int = DEFAULT_DIGEST_CAP,
                 enabled: bool = False) -> None:
        self.enabled = bool(enabled)
        self.window_s = max(float(window_s), 1.0)
        self.digest_cap = max(int(digest_cap), 1)
        self._lock = threading.Lock()
        self._buckets: deque = deque(maxlen=max(int(n_windows), 1))

    def configure(self, enabled: Optional[bool] = None,
                  window_s: Optional[float] = None,
                  digest_cap: Optional[int] = None,
                  n_windows: Optional[int] = None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)
        if window_s is not None:
            self.window_s = max(float(window_s), 1.0)
        if digest_cap is not None:
            self.digest_cap = max(int(digest_cap), 1)
        if n_windows is not None:
            with self._lock:
                self._buckets = deque(self._buckets,
                                      maxlen=max(int(n_windows), 1))

    def _bucket_locked(self, now: float) -> dict:
        win = int(now - (now % self.window_s))
        for b in reversed(self._buckets):
            if b["start"] == win:
                return b
        last = self._buckets[-1] if self._buckets else None
        if last is not None and win < last["start"]:
            return last
        b = {"start": win, "digests": {}, "other": None}
        self._buckets.append(b)
        return b

    @staticmethod
    def _new_entry(digest: str, digest_text: str, db: str) -> dict:
        return {"digest": digest, "digest_text": digest_text,
                "schema_name": db, "exec_count": 0,
                "sum_wall_s": 0.0, "waits": {}}

    def record(self, digest: str, digest_text: str, db: str,
               wall_s: float, waits: dict,
               now: Optional[float] = None) -> None:
        if not self.enabled:
            return
        ts = time.time() if now is None else float(now)
        with self._lock:
            b = self._bucket_locked(ts)
            ent = b["digests"].get(digest)
            if ent is None:
                if len(b["digests"]) < self.digest_cap:
                    ent = b["digests"][digest] = self._new_entry(
                        digest, digest_text, db)
                else:
                    if b["other"] is None:
                        b["other"] = self._new_entry(
                            self.OTHER, self.OTHER, "")
                    ent = b["other"]
            ent["exec_count"] += 1
            ent["sum_wall_s"] += wall_s
            w = ent["waits"]
            for k, v in waits.items():
                w[k] = w.get(k, 0.0) + v

    def snapshot(self) -> list[dict]:
        """Deep-copied buckets, oldest first."""
        import copy
        with self._lock:
            return [copy.deepcopy(b) for b in self._buckets]

    def clear(self) -> None:
        with self._lock:
            self._buckets.clear()

    @staticmethod
    def dominant(ent: dict) -> tuple[str, float]:
        """(state, fraction-of-wall) of the entry's heaviest wait state
        — what the dominant-wait inspection rule and the TopSQL
        attribution column read. ('', 0.0) when nothing waited."""
        waits = ent.get("waits") or {}
        if not waits or ent.get("sum_wall_s", 0.0) <= 0:
            return "", 0.0
        state = max(waits, key=lambda k: waits[k])
        return state, min(waits[state] / ent["sum_wall_s"], 1.0)

    def table_rows(self) -> list[list]:
        """information_schema.tidb_wait_profile rows: newest window
        first, digests by total wall desc, one row per wait state
        (heaviest first)."""
        rows: list[list] = []
        for b in reversed(self.snapshot()):
            win = time.strftime("%Y-%m-%d %H:%M:%S",
                                time.localtime(b["start"]))
            ents = sorted(b["digests"].values(),
                          key=lambda e: -e["sum_wall_s"])
            if b["other"] is not None:
                ents.append(b["other"])
            for e in ents:
                wall = e["sum_wall_s"]
                waits = e["waits"]
                for st in sorted(waits, key=lambda k: -waits[k]):
                    frac = waits[st] / wall if wall > 0 else 0.0
                    rows.append([
                        win, e["digest"], e["digest_text"],
                        e["schema_name"], e["exec_count"],
                        round(wall * 1e3, 3), st,
                        round(waits[st] * 1e3, 3),
                        round(min(frac, 1.0), 4)])
        return rows


# ---- structured server event log --------------------------------------------

class EventLog:
    """Bounded ring of structured server events (reference: TiDB logs
    these as structured log lines; here they are queryable after the
    fact): governor kills, admission sheds, rpc breaker trips,
    elections/promotions, checkpoint/fsync stalls — each with conn and
    digest attribution where the producer has it, so PR 4/5's
    protective actions are explainable without grepping stderr."""

    DEFAULT_CAP = 512

    def __init__(self, cap: int = DEFAULT_CAP, metrics=None) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(cap), 1))
        self._seq = 0
        if metrics is not None:
            self.counter = metrics.counter(
                "tidb_server_events_total",
                "structured server events recorded, by kind")
        else:
            self.counter = None

    def configure(self, cap: Optional[int] = None) -> None:
        if cap:
            with self._lock:
                self._ring = deque(self._ring, maxlen=max(int(cap), 1))

    def record(self, kind: str, detail: str = "",
               severity: str = "info", conn_id: int = 0,
               digest: str = "") -> None:
        ent = {
            "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
            "unix": round(time.time(), 3),
            "kind": str(kind)[:32],
            "severity": str(severity)[:8],
            "conn_id": int(conn_id),
            "digest": str(digest)[:32],
            "detail": str(detail)[:512],
        }
        with self._lock:
            self._seq += 1
            ent["id"] = self._seq
            self._ring.append(ent)
        if self.counter is not None:
            self.counter.inc(kind=ent["kind"])

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# ---- per-server observability state ----------------------------------------

class Observability:
    """One server's metrics + slow log + statement summaries. Owned by
    the Storage (one per 'cluster'), so two servers in one process don't
    clobber each other's counters — the round-2 verdict's module-global
    singleton problem. The module-level DEFAULT keeps process-wide
    consumers (the shared device coprocessor) working."""

    def __init__(self) -> None:
        self.metrics = Registry()
        self.queries = self.metrics.counter(
            "tidb_queries_total", "statements executed, by type")
        self.query_errors = self.metrics.counter(
            "tidb_query_errors_total", "statements that raised")
        self.query_seconds = self.metrics.histogram(
            "tidb_query_duration_seconds", "statement wall time")
        self.commits = self.metrics.counter(
            "tidb_commits_total", "transaction commits")
        self.conflicts = self.metrics.counter(
            "tidb_write_conflicts_total", "commit-time write conflicts")
        self.connections = self.metrics.counter(
            "tidb_connections_total", "wire connections accepted")
        self.conn_rejects = self.metrics.counter(
            "tidb_server_connections_rejected_total",
            "connections rejected at the gate with errno 1040")
        self.slow_counter = self.metrics.counter(
            "tidb_slow_queries_total",
            "statements over the slow-log threshold")
        # OLTP fast path (plan/fastpath.py + the session plan cache):
        # per-session LRU lookups aggregate here so fast-path coverage
        # is observable server-wide
        self.plan_cache_hits = self.metrics.counter(
            "tidb_plan_cache_hits_total",
            "plan cache lookups answered from the LRU (point fast "
            "plans and full physical plans)")
        self.plan_cache_misses = self.metrics.counter(
            "tidb_plan_cache_misses_total",
            "plan cache lookups that (re)planned — cold key, stale "
            "schema/stats generation, or cache disabled for the "
            "statement shape")
        self.plan_cache_evictions = self.metrics.counter(
            "tidb_plan_cache_evictions_total",
            "plan cache entries evicted at capacity "
            "(performance.plan-cache-size), least-recently-used first")
        # cross-commit group fsync (kv/mvcc.py SyncPolicy.commit_sync):
        # commits amortized per disk barrier under sync-log=commit —
        # mean batch size == durable-QPS amplification over one fsync
        self.group_commit_batch = self.metrics.histogram(
            "tidb_group_commit_batch_size",
            "commits made durable by one WAL fsync under "
            "sync-log=commit (group-commit rendezvous batch size)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        # histogram twins for the metrics_schema tier (histograms stay
        # on /metrics): avg batch = commits/fsyncs, queryable in SQL
        self.group_commit_fsyncs = self.metrics.counter(
            "tidb_group_commit_fsyncs_total",
            "WAL fsync barriers paid at commit boundaries "
            "(sync-log=commit group rendezvous leaders)")
        self.group_commit_commits = self.metrics.counter(
            "tidb_group_commit_commits_total",
            "commits made durable through the group rendezvous; "
            "divided by tidb_group_commit_fsyncs_total this is the "
            "amortization factor")
        # follower read tier (rpc/replica.py router + rpc/apply.py):
        # routed-read outcomes on the router's server, apply lag on the
        # replica's (leaders legitimately report 0 lag)
        self.replica_reads = self.metrics.counter(
            "tidb_replica_reads_total",
            "snapshot reads routed to follower replicas, by outcome "
            "(served / stale_fallback / unreachable_fallback)")
        self.apply_lag = self.metrics.gauge(
            "tidb_follower_apply_lag_seconds",
            "age of this follower's applied/closed timestamp (how far "
            "behind the leader the serving replica runs; feeds the "
            "follower-apply-lag inspection rule)")
        self._slow_log: deque = deque(maxlen=SLOW_LOG_MAX)
        self._slow_lock = threading.Lock()
        self.statements = StatementsSummary()
        # conn_id -> last TRACE span tree (served by /debug/trace/<id>)
        self._traces: dict[int, dict] = {}
        # continuous per-digest resource attribution (Top SQL), off by
        # default — performance.topsql-enabled arms it
        self.topsql = TopSQL()
        # structured server event ring (governor kills, admission
        # sheds, breaker trips, elections, checkpoint/fsync stalls)
        self.events = EventLog(metrics=self.metrics)
        # windowed per-digest typed-wait attribution, off by default —
        # performance.wait-profile-enabled arms it
        self.waitprofile = WaitProfile()

    def record_slow(self, sql: str, db: str, duration_s: float,
                    plan_digest: str = "",
                    stages: Optional[dict[str, float]] = None,
                    mem_peak: int = 0, spill_count: int = 0,
                    op_wall: Optional[dict[str, float]] = None,
                    mesh_skew: float = 0.0,
                    waits: Optional[dict[str, float]] = None) -> None:
        self.slow_counter.inc()
        ent = {
            "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
            "db": db,
            "duration_ms": round(duration_s * 1e3, 1),
            "sql": sql if len(sql) <= 4096 else sql[:4096] + "...",
            # plan digest + per-stage dispatch breakdown (reference:
            # LogSlowQuery's Plan_digest and execution-detail durations)
            "plan_digest": plan_digest,
            "stages": {k: round(v * 1e3, 3)
                       for k, v in (stages or {}).items()},
            # per-operator exclusive wall (ms): which plan operator of
            # this digest spent the time — the slow-log half of the
            # Top SQL attribution plane
            "operators": {k: round(v * 1e3, 3)
                          for k, v in (op_wall or {}).items()},
            # statement working-set peak + spill count (reference:
            # LogSlowQuery's Mem_max / Disk_max) — what makes a
            # governor kill explainable after the fact
            "mem_max": int(mem_peak),
            "spill_count": int(spill_count),
            # worst max/mean shard-row ratio of the statement's sharded
            # dispatches (0 = no sharded dispatch) — the mesh flight
            # recorder's balance signal, so a slow sharded join shows
            # WHY (skew) next to where (operators)
            "mesh_skew": round(float(mesh_skew), 2),
            # typed wait-state split (ms): where the statement BLOCKED
            # (2PC phases, backoff, tso/lease/fsync waits) — the
            # critical-path half next to the dispatch stages
            "waits": {k: round(v * 1e3, 3)
                      for k, v in (waits or {}).items()},
        }
        with self._slow_lock:
            self._slow_log.append(ent)
        # the reference writes a structured slow log line
        # (adapter.go:866). The FULL entry rides the record as
        # `slow_entry` so the log.slow-query-file sink with
        # log.format=json emits the structure (digest, stages,
        # operators, mem/spill, mesh skew), not just this one-liner.
        log.warning("slow query (%.1fms) db=%s: %s",
                    duration_s * 1e3, db, ent["sql"][:400],
                    extra={"slow_entry": ent})

    def slow_queries(self) -> list[dict]:
        with self._slow_lock:
            return list(self._slow_log)

    def record_trace(self, conn_id: int, rows: list) -> None:
        """Keep the last TRACE span tree per connection so the status
        port can serve it (/debug/trace/<conn_id>)."""
        with self._slow_lock:
            # re-insert so eviction order is least-recently-TRACEd
            self._traces.pop(conn_id, None)
            self._traces[conn_id] = {
                "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
                "spans": [list(r) for r in rows],
            }
            while len(self._traces) > TRACE_RING_MAX:
                self._traces.pop(next(iter(self._traces)))

    def trace_for(self, conn_id: int) -> Optional[dict]:
        with self._slow_lock:
            return self._traces.get(conn_id)

    def render(self) -> str:
        return self.metrics.render()


SLOW_LOG_MAX = 512
TRACE_RING_MAX = 64
DEFAULT_SLOW_THRESHOLD_MS = 300

# process-wide default instance: code without a Storage in reach
DEFAULT = Observability()
METRICS = DEFAULT.metrics
QUERIES = DEFAULT.queries
QUERY_ERRORS = DEFAULT.query_errors
QUERY_SECONDS = DEFAULT.query_seconds
COMMITS = DEFAULT.commits
CONFLICTS = DEFAULT.conflicts
CONNECTIONS = DEFAULT.connections
SLOW_QUERIES = DEFAULT.slow_counter

# genuinely process-global metrics (ONE device per process) live in
# their own registry so /metrics can concatenate it with a server's
# registry without duplicating metric families
PROCESS_METRICS = Registry()
COPR_REQUESTS = PROCESS_METRICS.counter(
    "tidb_copr_requests_total",
    "coprocessor executions, by engine (device / host fallback)")
FRAG_FALLBACKS = PROCESS_METRICS.counter(
    "tidb_copr_fragment_fallbacks_total",
    "device-fragment gate rejections, by reason")
DISPATCH_STAGE_SECONDS = PROCESS_METRICS.histogram(
    "tidb_dispatch_stage_duration_seconds",
    "per-stage dispatch wall time (staging, compile, transfer, kernel, "
    "device_get, host_fallback), labeled by stage")
COL_CACHE = PROCESS_METRICS.counter(
    "tidb_copr_column_cache_total",
    "device column-staging cache lookups, by result (hit / miss)")
JIT_CACHE = PROCESS_METRICS.counter(
    "tidb_copr_jit_cache_total",
    "compiled-kernel cache lookups, by result (hit / miss)")
PROFILER_SAMPLES = PROCESS_METRICS.counter(
    "tidb_profiler_samples_total",
    "stack samples taken by the host sampling profiler")
REGISTRY_ROW_EVALS = PROCESS_METRICS.counter(
    "tidb_registry_row_eval_total",
    "rows evaluated by the per-row scalar-function registry fallback "
    "(copr/funcs.py), by function — nonzero means an expression left "
    "the vectorized path (the registry-row-eval inspection rule reads "
    "this)")
# rpc circuit breaker (rpc/client.py): process-wide like the copr
# counters — every RpcClient in this process reports here, and the
# breaker state itself is per-client on /status transport_health
RPC_BREAKER_TRIPS = PROCESS_METRICS.counter(
    "tidb_rpc_breaker_trips_total",
    "circuit-breaker opens after consecutive transport failures")
RPC_BREAKER_FAST_FAILS = PROCESS_METRICS.counter(
    "tidb_rpc_breaker_fast_failures_total",
    "calls failed fast by an open rpc circuit breaker")

# range-sharded write leadership (rpc/ranged.py): process-wide like the
# breaker counters — a process may host several RangeServers (tests do),
# so the gauge moves by inc/dec per leadership open/drop rather than set
RANGE_LEADERS = PROCESS_METRICS.gauge(
    "tidb_range_leaders",
    "ranges whose write leadership this process currently holds")
RANGE_TRANSFERS = PROCESS_METRICS.counter(
    "tidb_range_transfers_total",
    "range leadership acquisitions that deposed a different owner "
    "(term bumps; steady renewal never counts)")
RANGE_ORPHAN_RESOLUTIONS = PROCESS_METRICS.counter(
    "tidb_range_orphan_resolutions_total",
    "orphan percolator locks rolled forward or back via primary-status "
    "check after a coordinator crash")
RANGE_SPLITS = PROCESS_METRICS.counter(
    "tidb_range_splits_total",
    "online range splits completed, by trigger (manual = operator "
    "range_split RPC, auto = heat-advisory actuator)")

# wait-state attribution plane (typed per-statement wait ledger):
# process-wide like the breaker counters — Backoffer/RpcClient/SyncPolicy
# have no Storage in reach. The histogram carries the distribution per
# typed state; the counter twin is the metrics_schema tier's SQL view of
# accumulated wait seconds (histograms stay on /metrics)
WAIT_SECONDS = PROCESS_METRICS.histogram(
    "tidb_wait_seconds",
    "exclusive statement wait time by typed state (tso_wait, "
    "lease_wait, backoff.{kind}, rpc_net, prewrite, commit_primary, "
    "commit_secondary, resolve_lock, fsync_wait)")
WAIT_SECONDS_TOTAL = PROCESS_METRICS.counter(
    "tidb_wait_total_seconds",
    "accumulated exclusive wait seconds by typed state — the "
    "SQL-queryable twin of the tidb_wait_seconds histogram (named "
    "total_seconds, not seconds_total, so the counter family never "
    "prefix-collides with the histogram's sample names)")
BACKOFF_SECONDS = PROCESS_METRICS.histogram(
    "tidb_backoff_seconds",
    "Backoffer sleep time by backoff kind (txnLock, txnConflict, "
    "regionMiss, metaConflict, tsoWait, tikvRPC)")
BACKOFF_EVENTS = PROCESS_METRICS.counter(
    "tidb_backoff_events_total",
    "Backoffer sleeps taken, by backoff kind — each typed sleep "
    "reports here instead of silently time.sleep-ing")

# device telemetry gauges (ONE device per process, like the counters
# above): transfer bytes accumulate on the dispatch hot path; buffer
# bytes / cache entries / RSS are refreshed by the registered probes
# right before every scrape or history sample
DEVICE_TRANSFER_BYTES = PROCESS_METRICS.gauge(
    "tidb_device_transfer_bytes",
    "cumulative host->device bytes staged by the coprocessor client")
DEVICE_BUFFER_BYTES = PROCESS_METRICS.gauge(
    "tidb_device_buffer_bytes",
    "live device bytes pinned by the column/mask staging caches")
JIT_CACHE_ENTRIES = PROCESS_METRICS.gauge(
    "tidb_jit_cache_entries",
    "compiled kernels resident in the jit cache")
PROCESS_RSS_BYTES = PROCESS_METRICS.gauge(
    "tidb_process_rss_bytes", "resident set size of this process")

# mesh plane telemetry (copr/mesh.py): ONE device mesh per process. The
# devices gauge reports the active mesh width (1 = single-device path);
# per-device buffer bytes ride the existing tidb_device_buffer_bytes
# family with a {device} label (the unlabeled sample stays the
# process-wide total); reshard bytes count replication broadcasts,
# partitioned-build staging and exchange routing over the mesh axis
MESH_DEVICES = PROCESS_METRICS.gauge(
    "tidb_mesh_devices",
    "devices in the process-wide coprocessor mesh (1 = single-device)")
MESH_RESHARD_BYTES = PROCESS_METRICS.counter(
    "tidb_mesh_reshard_bytes_total",
    "bytes moved across mesh devices by build replication, partitioned "
    "build staging and exchange routing")
# mesh flight recorder (copr/mesh.py MeshFlightRecorder): per-dispatch
# per-shard balance, compile churn and HBM watermark telemetry. Label
# cardinality is bounded: `kind` is a small fixed set, `device` is the
# mesh width (lint_metrics enforces the device/shard cap)
MESH_SKEW_RATIO = PROCESS_METRICS.gauge(
    "tidb_mesh_skew_ratio",
    "last observed max/mean shard-row ratio of a sharded dispatch "
    "(1.0 = perfectly balanced)")
MESH_SKEW_WARNINGS = PROCESS_METRICS.counter(
    "tidb_mesh_skew_warnings_total",
    "sharded dispatches whose shard-row skew crossed "
    "mesh.skew-warn-ratio")
MESH_COMPILES = PROCESS_METRICS.counter(
    "tidb_mesh_compiles_total",
    "XLA kernel compiles observed by the mesh plane, by kernel kind")
MESH_COMPILE_SECONDS = PROCESS_METRICS.counter(
    "tidb_mesh_compile_seconds_total",
    "wall seconds spent in XLA kernel compiles observed by the mesh "
    "plane")
MESH_RECOMPILE_STORMS = PROCESS_METRICS.counter(
    "tidb_mesh_recompile_storms_total",
    "kernel signatures that re-entered compile repeatedly "
    "(bucket/placement-mode churn)")
MESH_HBM_WATERMARK = PROCESS_METRICS.counter(
    "tidb_mesh_hbm_watermark_total",
    "devices whose live buffer bytes crossed "
    "mesh.hbm-watermark-fraction of capacity, by device")

# probes recomputing the sampled gauges (device buffer bytes, jit cache
# entries, RSS) from live state; run by MetricsHistory.sample_now() and
# the /metrics scrape path so the gauges are current at read time
# without taxing the dispatch hot path
_GAUGE_PROBES: list = []


def register_gauge_probe(fn) -> None:
    _GAUGE_PROBES.append(fn)


def run_gauge_probes() -> None:
    for fn in list(_GAUGE_PROBES):
        try:
            fn()
        except Exception:  # noqa: BLE001 — a probe must never break reads
            pass


def _rss_probe() -> None:
    try:
        import os
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        PROCESS_RSS_BYTES.set(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        import resource
        import sys
        # best-effort fallback (peak, not live); ru_maxrss is KiB on
        # Linux but already bytes on macOS
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        PROCESS_RSS_BYTES.set(rss if sys.platform == "darwin"
                              else rss * 1024)


register_gauge_probe(_rss_probe)


# ---- metrics time-series ring (metrics_summary / history route) -------------

class MetricsHistory:
    """Background sampler keeping a bounded ring of counter/gauge
    snapshots (reference: the in-cluster metrics schema behind
    INFORMATION_SCHEMA.METRICS_SUMMARY — TiDB 4.0 reads Prometheus; the
    embedded analog samples its own registries). One per Storage,
    started at open and joined at close like the sampling profiler, so
    no thread outlives its store."""

    DEFAULT_INTERVAL_S = 15.0
    DEFAULT_CAP = 240  # one hour at the default cadence

    def __init__(self, registries, interval_s: Optional[float] = None,
                 cap: Optional[int] = None) -> None:
        self.registries = list(registries)
        self.interval_s = float(interval_s or self.DEFAULT_INTERVAL_S)
        self._ring: deque = deque(maxlen=int(cap or self.DEFAULT_CAP))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def configure(self, interval_s: Optional[float] = None,
                  cap: Optional[int] = None) -> None:
        """Apply the performance.metrics-history-* config knobs (the
        server calls this after loading config; safe while running)."""
        if interval_s:
            self.interval_s = max(float(interval_s), 0.1)
        if cap:
            with self._lock:
                self._ring = deque(self._ring, maxlen=max(int(cap), 1))

    def sample_now(self, record: bool = True) -> dict:
        """One sample of every counter/gauge. record=False computes the
        point without touching the ring — the metrics_summary read path
        uses it so reading the time-series never mutates it."""
        run_gauge_probes()
        values: dict[str, float] = {}
        for reg in self.registries:
            values.update(reg.flat_samples())
        ent = {"ts": time.time(), "values": values}
        if record:
            with self._lock:
                self._ring.append(ent)
        return ent

    def _run(self) -> None:
        self.sample_now()  # first point at start, not one interval in
        while not self._stop.wait(self.interval_s):
            self.sample_now()

    def start(self) -> "MetricsHistory":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="titpu-metrics-history")
            self._thread.start()
        return self

    def stop(self) -> None:
        t = self._thread
        if t is not None:
            self._stop.set()
            t.join(timeout=5.0)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def summary(self, extra: Optional[dict] = None) -> dict[str, dict]:
        """metric -> {samples, min, avg, max, last} over the ring (the
        information_schema.metrics_summary rows); `extra` folds in a
        transient point (e.g. sample_now(record=False)) for 'now'."""
        out: dict[str, dict] = {}
        points = self.snapshot()
        if extra is not None:
            points.append(extra)
        for ent in points:
            for name, v in ent["values"].items():
                st = out.get(name)
                if st is None:
                    out[name] = {"samples": 1, "min": v, "max": v,
                                 "sum": v, "last": v}
                else:
                    st["samples"] += 1
                    st["min"] = min(st["min"], v)
                    st["max"] = max(st["max"], v)
                    st["sum"] += v
                    st["last"] = v
        for st in out.values():
            st["avg"] = st.pop("sum") / st["samples"]
        return out


# ---- cross-layer span trees (TRACE) -----------------------------------------

class Span:
    """One timed span with children; durations in seconds."""

    __slots__ = ("name", "start", "end", "children", "note")

    def __init__(self, name: str, start: float) -> None:
        self.name = name
        self.start = start
        self.end = start
        self.children: list["Span"] = []
        self.note: Optional[str] = None


_span_tls = threading.local()

TRACE_SPAN_CAP = 4096  # default; sessions override via tidb_trace_span_cap


class SpanCollector:
    """Hierarchical span collection across layers (reference:
    sessionctx + tracing spans rendered by executor/trace.go; spans are
    opened by the layer doing the work — session, planner, executor,
    coprocessor client, storage — and nest via a thread-local stack).

    Activation is thread-local and scoped: when no collector is active,
    `span()` is a no-op `yield`, so the production path pays one TLS
    read per instrumented site.

    Bounded: once `cap` spans have been opened further spans are
    dropped (count only), so a pathological statement cannot OOM the
    tracer. The count is lock-guarded so worker threads that inherit
    the collector stay safe."""

    def __init__(self, name: str = "trace",
                 cap: Optional[int] = None) -> None:
        import uuid
        self.t0 = time.perf_counter()
        self.root = Span(name, 0.0)
        self._stack = [self.root]
        self.cap = cap if cap is not None else TRACE_SPAN_CAP
        self.count = 1
        self.dropped = 0
        self._lock = threading.Lock()
        # Dapper-style identity: every RPC issued under this collector
        # carries (trace_id, parent_span_id) so the remote side's spans
        # come back attributable to this tree (rpc/frame.py trace ctx)
        self.trace_id = uuid.uuid4().hex
        self._next_span_id = 1

    def alloc_span_id(self) -> int:
        with self._lock:
            self._next_span_id += 1
            return self._next_span_id

    def _admit(self) -> bool:
        with self._lock:
            if self.count >= self.cap:
                self.dropped += 1
                return False
            self.count += 1
            return True

    def __enter__(self) -> "SpanCollector":
        _span_tls.coll = self
        return self

    def __exit__(self, *exc) -> None:
        self.root.end = time.perf_counter() - self.t0
        if self.dropped:
            self.root.note = f"{self.dropped} span(s) dropped at cap"
        _span_tls.coll = None

    def rows(self) -> list[tuple]:
        """(indented name, start_ms, duration_ms) depth-first."""
        out: list[tuple] = []

        def walk(s: Span, depth: int) -> None:
            label = "  " * depth + s.name + (
                f" [{s.note}]" if s.note else "")
            out.append((label, round(s.start * 1e3, 3),
                        round((s.end - s.start) * 1e3, 3)))
            for c in s.children:
                walk(c, depth + 1)

        walk(self.root, 0)
        return out


class _SpanCtx:
    __slots__ = ("name", "coll", "sp")

    def __init__(self, name: str) -> None:
        self.name = name
        self.coll = getattr(_span_tls, "coll", None)
        self.sp: Optional[Span] = None

    def __enter__(self) -> Optional[Span]:
        c = self.coll
        if c is None or not c._admit():
            return None
        self.sp = Span(self.name, time.perf_counter() - c.t0)
        c._stack[-1].children.append(self.sp)
        c._stack.append(self.sp)
        return self.sp

    def __exit__(self, *exc) -> None:
        c = self.coll
        if c is not None and self.sp is not None:
            self.sp.end = time.perf_counter() - c.t0
            c._stack.pop()


def span(name: str) -> _SpanCtx:
    """`with obs.span("copr.execute"):` — nests under the active
    collector's current span; no-op without an active TRACE."""
    return _SpanCtx(name)


def active_collector() -> Optional[SpanCollector]:
    """The thread's live TRACE collector, if any (the RPC client reads
    this to decide whether to propagate trace context)."""
    return getattr(_span_tls, "coll", None)


def run_remote_traced(tc, name: str, fn):
    """Server side of cross-process trace propagation: when the request
    carried a trace context, run the handler under its own SpanCollector
    and hand the span rows back for the caller to stitch (reference:
    Dapper's span trees crossing process boundaries; TiDB ships remote
    trace spans back in the coprocessor response). Returns
    (result, rows-or-None)."""
    if not isinstance(tc, dict):
        return fn(), None
    with SpanCollector(name) as coll:
        coll.trace_id = str(tc.get("trace_id") or coll.trace_id)
        coll.root.note = (f"trace_id={coll.trace_id[:16]} "
                          f"parent_span_id={tc.get('parent_span_id')}")
        result = fn()
    return result, coll.rows()


def graft_collector(parent: SpanCollector, into: Span,
                    child: SpanCollector) -> None:
    """Merge a worker thread's child collector into the caller's tree.

    The span stack is thread-local, so parallel fan-out workers cannot
    open spans on the caller's collector directly; each worker runs
    under its own SpanCollector and the caller grafts the children here
    (re-based by the collectors' perf_counter origins), keeping the
    tree identical to what sequential execution would have produced."""
    offset = child.t0 - parent.t0

    def walk(src: Span, dst_children: list) -> bool:
        if not parent._admit():
            return False
        sp = Span(src.name, src.start + offset)
        sp.end = src.end + offset
        sp.note = src.note
        dst_children.append(sp)
        for c in src.children:
            if not walk(c, sp.children):
                return False
        return True

    for c in child.root.children:
        if not walk(c, into.children):
            break


def stitch_remote_rows(coll: SpanCollector, parent: Span, rows) -> None:
    """Client side: graft a peer's span rows (indented-label form, ms
    offsets relative to the remote handler start) under the local RPC
    span, re-based onto this collector's clock. Remote spans count
    against the collector's cap like local ones."""
    base = parent.start
    stack: list[tuple[int, Span]] = [(-1, parent)]
    for r in rows:
        try:
            label, start_ms, dur_ms = str(r[0]), float(r[1]), float(r[2])
        except (TypeError, ValueError, IndexError):
            continue  # a malformed peer row must not kill the trace
        name = label.lstrip(" ")
        depth = (len(label) - len(name)) // 2
        if not coll._admit():
            break
        sp = Span(name, base + start_ms / 1e3)
        sp.end = sp.start + dur_ms / 1e3
        while len(stack) > 1 and stack[-1][0] >= depth:
            stack.pop()
        stack[-1][1].children.append(sp)
        stack.append((depth, sp))


# ---- dispatch-stage accounting ----------------------------------------------

_stage_tls = threading.local()
_op_tls = threading.local()


class _OpCtx:
    """One plan-operator frame: tags the thread with the operator label
    (stages closed inside attribute their time to it; transfer-byte
    accounting does the same) and records the frame's EXCLUSIVE wall
    seconds on the active StageRecorder — a per-thread nesting stack
    subtracts inner operator frames, so summing op_wall never double
    counts a join's probe scan into the join. Without an active
    recorder it is label bookkeeping only (two TLS writes)."""

    __slots__ = ("label", "prev", "t0", "rec")

    def __init__(self, label: str) -> None:
        self.label = label
        self.prev = None
        self.t0 = 0.0
        self.rec = None

    def __enter__(self) -> "_OpCtx":
        self.prev = getattr(_op_tls, "label", None)
        _op_tls.label = self.label
        rec = getattr(_stage_tls, "rec", None)
        self.rec = rec
        if rec is not None:
            stack = getattr(_op_tls, "stack", None)
            if stack is None:
                stack = _op_tls.stack = []
            stack.append(0.0)  # accumulates nested-frame wall time
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        _op_tls.label = self.prev
        rec = self.rec
        if rec is not None:
            dt = time.perf_counter() - self.t0
            stack = _op_tls.stack
            child = stack.pop()
            if stack:
                stack[-1] += dt
            rec.add_op_wall(self.label,
                            dt - child if dt > child else 0.0)


def operator(label: str) -> _OpCtx:
    """`with obs.operator("join"):` — attribute the enclosed work (wall
    time, dispatch stages, transfer bytes) to one named plan operator
    on the statement's StageRecorder."""
    return _OpCtx(label)


def active_operator() -> Optional[str]:
    return getattr(_op_tls, "label", None)


def note_op_bytes(nbytes: int) -> None:
    """Attribute host->device transfer bytes to the active operator on
    the statement's recorder (no-op without one — e.g. background
    staging outside any statement)."""
    rec = getattr(_stage_tls, "rec", None)
    if rec is not None:
        rec.note_bytes(nbytes)


class StageRecorder:
    """Per-statement dispatch-stage durations, EXCLUSIVE of nested
    stages: a stage's recorded time is its wall time minus the wall
    time of stages opened inside it, so the per-stage numbers are
    additive — they sum to (at most) the instrumented wall time. This
    is what lets EXPLAIN ANALYZE / the slow log answer "where did the
    milliseconds go" without double counting (reference:
    util/execdetails ExecDetails stage durations).

    One recorder per statement, installed by the session; recording a
    stage is two perf_counter reads and a dict update — cheap enough
    to stay always-on.

    Besides the flat per-stage totals it carries the per-OPERATOR
    attribution the Top SQL plane aggregates: `op_wall` (exclusive
    wall seconds per plan operator, from obs.operator frames the
    executor/fragment paths open), `ops` (each operator's per-stage
    split; stages recorded outside any operator frame land under
    '(session)'), and `op_bytes` (host->device transfer bytes per
    operator, fed by the copr client's staging accounting)."""

    __slots__ = ("totals", "counts", "op_wall", "ops", "op_bytes",
                 "op_mesh", "engines")

    def __init__(self) -> None:
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.op_wall: dict[str, float] = {}
        self.ops: dict[str, dict[str, float]] = {}
        self.op_bytes: dict[str, int] = {}
        # per-operator mesh balance from the flight recorder:
        # op -> [max shard share (max_shard/total), max skew ratio]
        self.op_mesh: dict[str, list] = {}
        # engine tag per coprocessor read this statement issued
        # ("device", "device[fat]@mesh8", "host(fragment:key-span)", ...)
        # — the path-decision record bench.py persists per timed query
        self.engines: list[str] = []

    def add(self, name: str, seconds: float) -> None:
        self.totals[name] = self.totals.get(name, 0.0) + seconds
        self.counts[name] = self.counts.get(name, 0) + 1

    def add_op_wall(self, op: str, seconds: float) -> None:
        self.op_wall[op] = self.op_wall.get(op, 0.0) + seconds

    def note_mesh(self, op: str, share: float, skew: float) -> None:
        """Record one sharded dispatch's balance under the operator
        that issued it (fed by the mesh flight recorder at collect
        time): max-shard share of the rows and max/mean skew ratio."""
        m = self.op_mesh.get(op)
        if m is None:
            self.op_mesh[op] = [float(share), float(skew)]
        else:
            m[0] = max(m[0], float(share))
            m[1] = max(m[1], float(skew))

    def add_op_stage(self, op: str, stage: str, seconds: float) -> None:
        d = self.ops.get(op)
        if d is None:
            d = self.ops[op] = {}
        d[stage] = d.get(stage, 0.0) + seconds

    def note_bytes(self, nbytes: int) -> None:
        op = getattr(_op_tls, "label", None) or "(session)"
        self.op_bytes[op] = self.op_bytes.get(op, 0) + int(nbytes)

    def snapshot(self) -> dict[str, float]:
        return dict(self.totals)

    def delta_since(self, before: dict[str, float]) -> dict[str, float]:
        out = {}
        for k, v in self.totals.items():
            d = v - before.get(k, 0.0)
            if d > 0:
                out[k] = d
        return out


def note_engine(tag: Optional[str]) -> None:
    """Record which engine served a coprocessor read (device / host /
    ranged, with the fragment mode and gate reason embedded) on the
    statement's recorder — the always-on path-decision surface."""
    if not tag:
        return
    rec = getattr(_stage_tls, "rec", None)
    if rec is not None:
        rec.engines.append(tag)


def install_stage_recorder(rec: Optional[StageRecorder]) -> None:
    _stage_tls.rec = rec


def active_stage_recorder() -> Optional[StageRecorder]:
    return getattr(_stage_tls, "rec", None)


class _StageCtx:
    """Times one dispatch stage: always feeds the per-stage Prometheus
    histogram and the active StageRecorder — both with EXCLUSIVE time
    (a per-thread nesting stack subtracts inner stages, so summing the
    per-stage histograms never double-counts a nested compile into its
    enclosing kernel stage) — and opens a TRACE span when a collector
    is active. Allocates no Span when tracing is off (the hot-path
    guarantee test_trace pins)."""

    __slots__ = ("stage", "spanctx", "t0", "rec")

    def __init__(self, stage: str, span_name: Optional[str]) -> None:
        self.stage = stage
        self.spanctx = _SpanCtx(span_name or stage)
        self.rec = getattr(_stage_tls, "rec", None)
        self.t0 = 0.0

    def __enter__(self) -> Optional[Span]:
        stack = getattr(_stage_tls, "stack", None)
        if stack is None:
            stack = _stage_tls.stack = []
        stack.append(0.0)  # accumulates nested-stage wall time
        self.t0 = time.perf_counter()
        return self.spanctx.__enter__()

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self.t0
        self.spanctx.__exit__(*exc)
        stack = _stage_tls.stack
        child = stack.pop()
        if stack:
            stack[-1] += dt
        excl = dt - child if dt > child else 0.0
        DISPATCH_STAGE_SECONDS.observe(excl, stage=self.stage)
        if self.rec is not None:
            self.rec.add(self.stage, excl)
            # per-operator split of the same exclusive time: stages
            # closed outside any operator frame (plan_build at the
            # session layer) land under '(session)'
            self.rec.add_op_stage(
                getattr(_op_tls, "label", None) or "(session)",
                self.stage, excl)


def stage(name: str, span_name: Optional[str] = None) -> _StageCtx:
    """`with obs.stage("compile"):` — one named dispatch stage.
    Histogram + recorder always; a span only under an active TRACE."""
    return _StageCtx(name, span_name)


# ---- typed wait-state ledger (critical-path attribution) --------------------

_wait_tls = threading.local()


class WaitLedger:
    """Per-statement typed wait totals, EXCLUSIVE of nested wait frames
    (same additive guarantee as StageRecorder: summing the states never
    exceeds the instrumented wall). One ledger per statement, installed
    by the session ONLY while performance.wait-profile-enabled is on —
    disabled, nothing on the statement path allocates or touches one
    (the poison/zero-alloc contract test_trace pins). The states are
    the write path's blocking taxonomy: tso_wait, lease_wait,
    backoff.{kind}, rpc_net, prewrite, commit_primary,
    commit_secondary, resolve_lock, fsync_wait (reference: TiDB's
    execution-stage runtime stats feeding slow log and Top SQL)."""

    __slots__ = ("totals", "counts")

    def __init__(self) -> None:
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    def add(self, state: str, seconds: float) -> None:
        self.totals[state] = self.totals.get(state, 0.0) + seconds
        self.counts[state] = self.counts.get(state, 0) + 1

    def snapshot(self) -> dict[str, float]:
        return dict(self.totals)


def install_wait_ledger(led: Optional[WaitLedger]) -> None:
    _wait_tls.led = led


def active_wait_ledger() -> Optional[WaitLedger]:
    return getattr(_wait_tls, "led", None)


class _WaitCtx:
    """Times one typed wait frame: always feeds the tidb_wait_seconds
    histogram (+ its counter twin) with EXCLUSIVE time — a per-thread
    nesting stack subtracts inner wait frames and note_wait charges,
    so the per-state sums are additive — and feeds the active
    WaitLedger when one is installed. With `fallback=True` the frame
    is a full no-op when ANY wait frame is already open: the enclosed
    time stays attributed to the more specific enclosing state
    (rpc_net is the catch-all for network time not already typed as a
    2PC phase or tso_wait). Optionally opens a TRACE span (span_name),
    allocating no Span when tracing is off."""

    __slots__ = ("state", "spanctx", "t0", "skip")

    def __init__(self, state: str, span_name: Optional[str],
                 fallback: bool) -> None:
        self.state = state
        self.skip = bool(fallback and getattr(_wait_tls, "stack", None))
        self.spanctx = _SpanCtx(span_name) if (
            span_name and not self.skip) else None
        self.t0 = 0.0

    def __enter__(self) -> "_WaitCtx":
        if self.skip:
            return self
        stack = getattr(_wait_tls, "stack", None)
        if stack is None:
            stack = _wait_tls.stack = []
        stack.append(0.0)  # accumulates nested-frame wall time
        if self.spanctx is not None:
            self.spanctx.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self.skip:
            return
        dt = time.perf_counter() - self.t0
        if self.spanctx is not None:
            self.spanctx.__exit__(*exc)
        stack = _wait_tls.stack
        child = stack.pop()
        if stack:
            stack[-1] += dt
        excl = dt - child if dt > child else 0.0
        WAIT_SECONDS.observe(excl, state=self.state)
        WAIT_SECONDS_TOTAL.inc(excl, state=self.state)
        led = getattr(_wait_tls, "led", None)
        if led is not None:
            led.add(self.state, excl)


def wait(state: str, span_name: Optional[str] = None,
         fallback: bool = False) -> _WaitCtx:
    """`with obs.wait("prewrite"):` — one typed wait frame. Histogram +
    active ledger always (exclusive time); a span only when span_name
    is given AND a TRACE collector is active."""
    return _WaitCtx(state, span_name, fallback)


def note_wait(state: str, seconds: float) -> None:
    """Charge externally-timed wait seconds (a Backoffer sleep, a
    transport-timeout block) to the typed state: histogram + counter
    twin + the active ledger, and the enclosing wait frame's exclusive
    accounting (the charge is subtracted from the enclosing frame, so
    a backoff sleep inside a prewrite frame never double-counts)."""
    if seconds <= 0:
        return
    stack = getattr(_wait_tls, "stack", None)
    if stack:
        stack[-1] += seconds
    WAIT_SECONDS.observe(seconds, state=state)
    WAIT_SECONDS_TOTAL.inc(seconds, state=state)
    led = getattr(_wait_tls, "led", None)
    if led is not None:
        led.add(state, seconds)


def fmt_waits(waits: Optional[dict[str, float]]) -> str:
    """wait dict (seconds) -> 'prewrite:3.2ms rpc_net:1.1ms ...'
    heaviest first — the EXPLAIN ANALYZE / slow-log wait_profile cell."""
    if not waits:
        return ""
    return " ".join(f"{k}:{v * 1e3:.3g}ms" for k, v in
                    sorted(waits.items(), key=lambda kv: -kv[1]))


def fmt_waits_ms(waits_ms: Optional[dict[str, float]]) -> str:
    """fmt_waits for dicts already in milliseconds (the slow-log entry
    form written by record_slow)."""
    if not waits_ms:
        return ""
    return fmt_waits({k: v / 1e3 for k, v in waits_ms.items()})


def fmt_stages(stages: Optional[dict[str, float]]) -> str:
    """stage dict -> 'staging:0.12ms compile:5.3ms ...' (stable order)."""
    if not stages:
        return ""
    order = ("parse", "plan_build", "prepare", "staging", "transfer",
             "compile", "kernel", "device_get", "host_fallback", "ranged")
    keys = [k for k in order if k in stages] + \
        sorted(k for k in stages if k not in order)
    return " ".join(f"{k}:{stages[k] * 1e3:.3g}ms" for k in keys)


def fmt_stages_ms(stages_ms: Optional[dict[str, float]]) -> str:
    """fmt_stages for dicts already in milliseconds (the slow-log
    entry form written by record_slow)."""
    if not stages_ms:
        return ""
    return fmt_stages({k: v / 1e3 for k, v in stages_ms.items()})


def fmt_ops_ms(ops_ms: Optional[dict[str, float]]) -> str:
    """operator->ms dict -> 'join:5.2ms scan:1.1ms ...' heaviest first."""
    if not ops_ms:
        return ""
    return " ".join(f"{k}:{v:.3g}ms" for k, v in
                    sorted(ops_ms.items(), key=lambda kv: -kv[1]))


def fmt_mesh(note: Optional[dict]) -> str:
    """Mesh flight-recorder note -> the EXPLAIN ANALYZE `mesh` cell:
    'shards=8 skew=1.25 rows=[..per-shard rows..] [routed=NNN]'."""
    if not note:
        return ""
    rows = note.get("rows") or note.get("in") or []
    s = (f"shards={int(note.get('shards', 0))} "
         f"skew={float(note.get('skew', 0.0)):.2f} "
         f"rows=[{','.join(str(int(r)) for r in rows)}]")
    if note.get("routed"):
        s += f" routed={int(note['routed'])}"
    return s


# ---- per-statement runtime stats (EXPLAIN ANALYZE) --------------------------

class RuntimeStatsColl:
    """Per-plan-node runtime stats (reference:
    util/execdetails/execdetails.go RuntimeStatsColl): inclusive wall
    time, output rows, which engine served a leaf (device kernel vs
    host fallback, with the gate's reason), and the inclusive
    per-dispatch-stage second breakdown (staging / compile / transfer /
    kernel / device_get / host_fallback)."""

    def __init__(self) -> None:
        self.nodes: dict[int, dict] = {}

    def record(self, plan, seconds: float, rows: int,
               engine: Optional[str] = None,
               stages: Optional[dict[str, float]] = None,
               mesh: Optional[dict] = None) -> None:
        ent = self.nodes.setdefault(id(plan), {
            "time": 0.0, "rows": 0, "loops": 0, "engine": None,
            "stages": {}, "mesh": None})
        ent["time"] += seconds
        ent["rows"] += rows
        ent["loops"] += 1
        if engine:
            ent["engine"] = engine
        if stages:
            st = ent["stages"]
            for k, v in stages.items():
                st[k] = st.get(k, 0.0) + v
        if mesh:
            # mesh flight-recorder note: keep the latest per-shard rows
            # and the worst skew across loops; routed bytes accumulate
            m = ent["mesh"]
            if m is None:
                ent["mesh"] = dict(mesh)
            else:
                m["skew"] = max(m.get("skew", 0.0),
                                mesh.get("skew", 0.0))
                m["rows"] = mesh.get("rows") or m.get("rows")
                m["in"] = mesh.get("in") or m.get("in")
                m["routed"] = m.get("routed", 0) + mesh.get("routed", 0)

    def for_plan(self, plan) -> Optional[dict]:
        return self.nodes.get(id(plan))


# ---- sampling host-CPU profiler ---------------------------------------------

class Profile:
    """Aggregated stack samples: {stack tuple -> count}. A stack is a
    tuple of 'func (file:line)' strings, outermost first."""

    __slots__ = ("stacks", "hz", "duration_s")

    def __init__(self, stacks: dict[tuple, int], hz: float,
                 duration_s: float) -> None:
        self.stacks = stacks
        self.hz = hz
        self.duration_s = duration_s

    @property
    def total_samples(self) -> int:
        return sum(self.stacks.values())

    def hot_frames(self, limit: int = 20) -> list[tuple[str, int]]:
        """Frames ranked by SELF samples (innermost frame of a stack)."""
        own: dict[str, int] = {}
        for stack, n in self.stacks.items():
            if stack:
                own[stack[-1]] = own.get(stack[-1], 0) + n
        return sorted(own.items(), key=lambda kv: -kv[1])[:limit]

    def tree_rows(self, max_rows: int = 512) -> list[tuple[str, float, int]]:
        """Flamegraph-style rows: (indented frame, est. seconds,
        samples), depth-first, heaviest subtree first."""
        root: dict = {}
        counts: dict[int, int] = {}

        for stack, n in self.stacks.items():
            node = root
            for frame in stack:
                node = node.setdefault(frame, {})
                counts[id(node)] = counts.get(id(node), 0) + n

        per_sample = 1.0 / self.hz if self.hz > 0 else 0.0
        rows: list[tuple[str, float, int]] = []

        def walk(node: dict, depth: int) -> None:
            for frame, child in sorted(
                    node.items(), key=lambda kv: -counts[id(kv[1])]):
                if len(rows) >= max_rows:
                    return
                n = counts[id(child)]
                rows.append(("  " * depth + frame,
                             round(n * per_sample, 6), n))
                walk(child, depth + 1)

        walk(root, 0)
        return rows

    def to_dict(self) -> dict:
        return {
            "hz": self.hz,
            "duration_s": round(self.duration_s, 6),
            "total_samples": self.total_samples,
            "hot_frames": self.hot_frames(),
            "tree": [{"frame": f, "seconds": s, "samples": n}
                     for f, s, n in self.tree_rows()],
        }


def _format_frame(frame) -> str:
    co = frame.f_code
    return f"{co.co_name} ({co.co_filename.rsplit('/', 1)[-1]}" \
        f":{frame.f_lineno})"


class SamplingProfiler:
    """Wall-clock stack sampler over sys._current_frames() (reference:
    util/profile serving pprof CPU profiles through SQL and the status
    port). `thread_ids=None` samples every thread (the /debug/profile
    whole-process view); a set restricts to those threads (the
    per-statement SHOW PROFILE view). start()/stop() own the sampler
    thread's lifecycle — stop() joins it, so no sampler leaks past the
    statement that started it."""

    MAX_DEPTH = 48
    MAX_STACKS = 4096

    def __init__(self, hz: float = 97.0,
                 thread_ids: Optional[set] = None) -> None:
        self.hz = max(float(hz), 1.0)
        self.thread_ids = thread_ids
        self._stacks: dict[tuple, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0
        self._elapsed = 0.0

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            raise RuntimeError("profiler already running")
        self._t0 = time.perf_counter()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="titpu-profiler")
        self._thread.start()
        return self

    def _run(self) -> None:
        import sys

        me = threading.get_ident()
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            frames = sys._current_frames()
            for tid, frame in frames.items():
                if tid == me:
                    continue
                if self.thread_ids is not None and \
                        tid not in self.thread_ids:
                    continue
                stack: list[str] = []
                f = frame
                while f is not None and len(stack) < self.MAX_DEPTH:
                    stack.append(_format_frame(f))
                    f = f.f_back
                stack.reverse()
                key = tuple(stack)
                if key in self._stacks or \
                        len(self._stacks) < self.MAX_STACKS:
                    self._stacks[key] = self._stacks.get(key, 0) + 1
                PROFILER_SAMPLES.inc()
            del frames

    def stop(self) -> Profile:
        t = self._thread
        if t is not None:
            self._stop.set()
            t.join(timeout=5.0)
            self._thread = None
        self._elapsed = time.perf_counter() - self._t0
        return Profile(dict(self._stacks), self.hz, self._elapsed)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()


def profile_process(seconds: float = 0.5, hz: float = 97.0) -> Profile:
    """Block for `seconds` sampling every thread — the /debug/profile
    handler's one-shot whole-process view."""
    p = SamplingProfiler(hz=hz).start()
    time.sleep(max(min(seconds, 10.0), 0.01))
    return p.stop()


# ---- metric-hygiene lint -----------------------------------------------------

_METRIC_NAME_RE = None  # compiled lazily (re import stays off hot paths)


def lint_metrics(registries, device_label_cap: Optional[int] = None
                 ) -> list[str]:
    """Walk registries + their rendered exposition and return hygiene
    findings (empty list = clean). Checks: every metric carries help
    text; names are tidb_-prefixed snake_case; no family is registered
    in more than one of the given registries (their /metrics outputs
    concatenate); `device`/`shard` label families stay bounded by the
    mesh size (`device_label_cap`; default = the live mesh width, floor
    8) so per-device telemetry cannot turn into unbounded cardinality;
    and the rendered Prometheus text exposition is well-formed
    (HELP/TYPE precede samples, label syntax and values parse,
    histogram buckets are cumulative and _count-consistent). Run by
    tier-1 so a metric added by a later PR cannot silently break the
    scrape."""
    import re
    global _METRIC_NAME_RE
    if _METRIC_NAME_RE is None:
        _METRIC_NAME_RE = re.compile(r"^tidb_[a-z0-9_]+$")
    if device_label_cap is None:
        device_label_cap = max(int(MESH_DEVICES.get()), 8)
    findings: list[str] = []
    seen: dict[str, int] = {}
    label_vals: dict[tuple[str, str], set] = {}
    for ri, reg in enumerate(registries):
        with reg._lock:
            metrics = list(reg._metrics.values())
        for m in metrics:
            if not getattr(m, "help", ""):
                findings.append(f"metric {m.name}: missing help text")
            if not _METRIC_NAME_RE.match(m.name):
                findings.append(
                    f"metric {m.name}: name must match tidb_[a-z0-9_]+")
            if m.name in seen and seen[m.name] != ri:
                findings.append(
                    f"metric {m.name}: registered in more than one "
                    "concatenated registry (duplicate family on "
                    "/metrics)")
            seen[m.name] = ri
            if isinstance(m, (Counter, Gauge)):
                keys = [k for k, _ in m.samples()]
            else:
                keys = [k for k, _, _, _ in m.series()]
            for key in keys:
                for lk, lv in key:
                    if lk in ("device", "shard"):
                        label_vals.setdefault((m.name, lk),
                                              set()).add(lv)
        findings.extend(_lint_exposition(reg.render()))
    for (mname, lk), vals in sorted(label_vals.items()):
        if len(vals) > device_label_cap:
            findings.append(
                f"metric {mname}: label {lk!r} has {len(vals)} values, "
                f"over the mesh-size cap {device_label_cap} (unbounded "
                "per-device/per-shard cardinality)")
    return findings


def _lint_exposition(text: str) -> list[str]:
    """Validate one registry's Prometheus text exposition."""
    import re
    findings: list[str] = []
    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
        r'(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*")'
        r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*")*)?\})? (\S+)$')
    helped: set[str] = set()
    typed: dict[str, str] = {}
    bucket_acc: dict[str, int] = {}  # series label-part -> last cum count
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            parts = ln.split(" ", 3)
            if len(parts) < 4 or not parts[3].strip():
                findings.append(f"exposition: HELP without text: {ln!r}")
            helped.add(parts[2])
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split(" ")
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary"):
                findings.append(f"exposition: malformed TYPE: {ln!r}")
                continue
            if parts[2] in typed:
                findings.append(
                    f"exposition: duplicate TYPE for {parts[2]}")
            typed[parts[2]] = parts[3]
            continue
        if ln.startswith("#"):
            continue
        m = sample_re.match(ln)
        if m is None:
            findings.append(f"exposition: malformed sample line: {ln!r}")
            continue
        name, labels, value = m.group(1), m.group(2), m.group(3)
        family = name
        for sfx in ("_bucket", "_sum", "_count"):
            if name.endswith(sfx) and name[:-len(sfx)] in typed:
                family = name[:-len(sfx)]
                break
        if family not in typed:
            findings.append(
                f"exposition: sample {name} precedes (or lacks) its "
                "TYPE line")
        elif family not in helped:
            findings.append(f"exposition: {family} lacks a HELP line")
        try:
            float(value)
        except ValueError:
            findings.append(
                f"exposition: non-numeric value {value!r} on {name}")
            continue
        if name.endswith("_bucket") and labels:
            series = re.sub(r'le="[^"]*",?', "", labels)
            key = family + "{" + series + "}"
            cum = int(float(value))
            if cum < bucket_acc.get(key, 0):
                findings.append(
                    f"exposition: non-cumulative buckets on {key}")
            if 'le="+Inf"' in labels:
                bucket_acc.pop(key, None)  # series complete; reset
            else:
                bucket_acc[key] = cum
    return findings


# ---- module-level delegates (default instance) ------------------------------

def record_slow(sql: str, db: str, duration_s: float,
                plan_digest: str = "",
                stages: Optional[dict[str, float]] = None,
                mem_peak: int = 0, spill_count: int = 0,
                op_wall: Optional[dict[str, float]] = None,
                mesh_skew: float = 0.0) -> None:
    DEFAULT.record_slow(sql, db, duration_s, plan_digest, stages,
                        mem_peak, spill_count, op_wall, mesh_skew)


def slow_queries() -> list[dict]:
    return DEFAULT.slow_queries()
