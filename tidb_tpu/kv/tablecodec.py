"""Table/index KV key layout.

Counterpart of the reference's tablecodec (reference:
tablecodec/tablecodec.go:46-48 — `t{tableID}_r{handle}` row keys,
`t{tableID}_i{indexID}{encodedVals}` index keys, :89 EncodeRowKeyWithHandle).
Table IDs and handles use the memcomparable int format so ranges scan in
order; the 't' prefix keeps table data clustered and separable from the
meta prefix 'm'.
"""

from __future__ import annotations

import struct
from typing import Any, Optional

from .codec import encode_key

TABLE_PREFIX = b"t"
ROW_SEP = b"_r"
INDEX_SEP = b"_i"
META_PREFIX = b"m"


def _eint(v: int) -> bytes:
    return struct.pack(">Q", (v + 0x8000000000000000) & 0xFFFFFFFFFFFFFFFF)


def _dint(b: bytes) -> int:
    return struct.unpack(">Q", b)[0] - 0x8000000000000000


def table_prefix(table_id: int) -> bytes:
    return TABLE_PREFIX + _eint(table_id)


def record_prefix(table_id: int) -> bytes:
    return table_prefix(table_id) + ROW_SEP


def record_key(table_id: int, handle: int) -> bytes:
    return record_prefix(table_id) + _eint(handle)


def decode_record_key(key: bytes) -> tuple[int, int]:
    if not key.startswith(TABLE_PREFIX) or key[9:11] != ROW_SEP:
        raise ValueError(f"not a record key: {key!r}")
    return _dint(key[1:9]), _dint(key[11:19])


def index_prefix(table_id: int, index_id: int) -> bytes:
    return table_prefix(table_id) + INDEX_SEP + _eint(index_id)


def index_key(table_id: int, index_id: int, values: list[Any],
              handle: Optional[int] = None) -> bytes:
    """Non-unique indexes append the handle (making keys unique); unique
    indexes omit it and store the handle as the value (reference:
    tablecodec EncodeIndexSeekKey + tables/index.go Create)."""
    k = index_prefix(table_id, index_id) + encode_key(values)
    if handle is not None:
        k += _eint(handle)
    return k


def table_range(table_id: int) -> tuple[bytes, bytes]:
    """[start, end) covering every key of one table."""
    p = table_prefix(table_id)
    return p, p + b"\xff"


def record_range(table_id: int) -> tuple[bytes, bytes]:
    p = record_prefix(table_id)
    return p, p + b"\xff"


def meta_key(name: bytes) -> bytes:
    return META_PREFIX + name
