"""Transaction staging buffer with statement-level staging/rollback.

Counterpart of the reference's red-black-tree arena memdb (reference:
kv/memdb.go — `Staging()`, `Release()`, `Cleanup()` checkpoints used by
session/txn.go:52-87 for per-statement rollback). TPU-first difference:
keys are logical `(table_id, handle)` pairs and values are row tuples, not
byte-encoded KV — the columnar store consumes mutations directly; the
byte-level codec lives only at the (later) persistence boundary.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, Optional


class _Tombstone:
    __slots__ = ()

    def __repr__(self) -> str:
        return "TOMBSTONE"


TOMBSTONE = _Tombstone()

Key = tuple[int, int]  # (table_id, handle)


@dataclass
class Mutation:
    key: Key
    # row values tuple (physical encoding per column), or TOMBSTONE
    value: Any


class MemDB:
    """Ordered-by-insertion mutation buffer with nested staging points.

    Supports: Set/Delete/Get, snapshot-merged iteration (union with the
    store happens in the union reader, not here), staging handles for
    statement rollback, and flush-to-commit draining.
    """

    def __init__(self) -> None:
        # full history of (key, value) writes, append-only; staging rollback
        # truncates the log and rebuilds the index
        self._log: list[Mutation] = []
        self._index: dict[Key, Any] = {}
        self._stages: list[int] = []

    def __len__(self) -> int:
        return len(self._index)

    @property
    def is_empty(self) -> bool:
        return not self._index

    # ---- writes ------------------------------------------------------------
    def set(self, key: Key, value: Any) -> None:
        self._log.append(Mutation(key, value))
        self._index[key] = value

    def delete(self, key: Key) -> None:
        self.set(key, TOMBSTONE)

    # ---- reads -------------------------------------------------------------
    def get(self, key: Key) -> Optional[Any]:
        """Latest staged value: row tuple, TOMBSTONE, or None (not buffered)."""
        return self._index.get(key)

    def iter_table(self, table_id: int) -> Iterator[tuple[int, Any]]:
        """(handle, value) for all buffered mutations of one table."""
        for (tid, handle), value in self._index.items():
            if tid == table_id:
                yield handle, value

    # ---- staging (statement rollback) --------------------------------------
    def staging(self) -> int:
        """Open a staging point; returns a handle for release/cleanup.
        Mirrors kv/memdb.go Staging()."""
        self._stages.append(len(self._log))
        return len(self._stages)

    def release(self, handle: int) -> None:
        """Commit the staging buffer into the parent (keep writes)."""
        assert handle == len(self._stages), "staging handles must nest"
        self._stages.pop()

    def cleanup(self, handle: int) -> None:
        """Discard all writes since the staging point (statement rollback)."""
        assert handle == len(self._stages), "staging handles must nest"
        mark = self._stages.pop()
        if mark >= len(self._log):
            return
        del self._log[mark:]
        self._index = {}
        for m in self._log:
            self._index[m.key] = m.value

    # ---- commit drain ------------------------------------------------------
    def mutations(self) -> dict[Key, Any]:
        """Final state of every touched key (last write wins)."""
        return dict(self._index)
