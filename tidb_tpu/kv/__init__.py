from .memdb import MemDB, Mutation, TOMBSTONE
from .tso import TimestampOracle

__all__ = ["MemDB", "Mutation", "TOMBSTONE", "TimestampOracle"]
