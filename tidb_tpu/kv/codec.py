"""Memcomparable byte encodings for all SQL types.

Counterpart of the reference's util/codec (reference: util/codec/codec.go,
number.go, bytes.go, decimal.go): every encoding preserves SQL ordering
under plain bytewise comparison, so the KV engine (Python or C++) can stay
type-blind. Formats match the reference's scheme conceptually:

* ints: flag byte + big-endian uint64 biased by 2^63
* bytes: 8-byte groups, each followed by a pad-count marker (0xF7+n used,
  0xFF = full group continues) — preserves prefix ordering with escapes
* floats: IEEE bits with sign-flip trick
* decimals: encoded via scaled int64 (precision <= 18 in this build)
* dates/datetimes: their int encodings ride the int format
* NULL sorts before everything
"""

from __future__ import annotations

import struct
from typing import Any, Optional

NIL_FLAG = 0x00
BYTES_FLAG = 0x01
INT_FLAG = 0x03
FLOAT_FLAG = 0x05
MAX_FLAG = 0xFA

_SIGN_MASK = 0x8000000000000000


# ---- ints -------------------------------------------------------------------

def encode_int(buf: bytearray, v: int) -> None:
    buf.append(INT_FLAG)
    buf += struct.pack(">Q", (v + _SIGN_MASK) & 0xFFFFFFFFFFFFFFFF)


def decode_int(buf: bytes, pos: int) -> tuple[int, int]:
    if buf[pos] != INT_FLAG:
        raise ValueError(f"int flag expected at {pos}, got {buf[pos]:#x}")
    (u,) = struct.unpack_from(">Q", buf, pos + 1)
    return u - _SIGN_MASK, pos + 9


def encode_uint_desc(v: int) -> bytes:
    """Descending-order uint64 (used for reverse-ts MVCC keys)."""
    return struct.pack(">Q", 0xFFFFFFFFFFFFFFFF - v)


def decode_uint_desc(b: bytes) -> int:
    return 0xFFFFFFFFFFFFFFFF - struct.unpack(">Q", b)[0]


# ---- floats -----------------------------------------------------------------

def encode_float(buf: bytearray, v: float) -> None:
    buf.append(FLOAT_FLAG)
    u = struct.unpack(">Q", struct.pack(">d", v))[0]
    if u & _SIGN_MASK:
        u = ~u & 0xFFFFFFFFFFFFFFFF  # negative: flip all
    else:
        u |= _SIGN_MASK  # positive: flip sign bit
    buf += struct.pack(">Q", u)


def decode_float(buf: bytes, pos: int) -> tuple[float, int]:
    if buf[pos] != FLOAT_FLAG:
        raise ValueError(f"float flag expected at {pos}")
    (u,) = struct.unpack_from(">Q", buf, pos + 1)
    if u & _SIGN_MASK:
        u &= ~_SIGN_MASK & 0xFFFFFFFFFFFFFFFF
    else:
        u = ~u & 0xFFFFFFFFFFFFFFFF
    return struct.unpack(">d", struct.pack(">Q", u))[0], pos + 9


# ---- bytes (8-byte-group escape encoding) ----------------------------------

_GROUP = 8
_PAD = 0x00
_MARKER_FULL = 0xFF


def encode_bytes(buf: bytearray, b: bytes) -> None:
    buf.append(BYTES_FLAG)
    for i in range(0, len(b) + 1, _GROUP):
        group = b[i:i + _GROUP]
        pad = _GROUP - len(group)
        buf += group + bytes([_PAD]) * pad
        buf.append(_MARKER_FULL - pad)


def decode_bytes(buf: bytes, pos: int) -> tuple[bytes, int]:
    if buf[pos] != BYTES_FLAG:
        raise ValueError(f"bytes flag expected at {pos}")
    pos += 1
    out = bytearray()
    while True:
        group = buf[pos:pos + _GROUP]
        marker = buf[pos + _GROUP]
        pos += _GROUP + 1
        pad = _MARKER_FULL - marker
        if pad == 0:
            out += group
        else:
            out += group[:_GROUP - pad]
            break
    return bytes(out), pos


# ---- null + dispatch --------------------------------------------------------

def encode_null(buf: bytearray) -> None:
    buf.append(NIL_FLAG)


def encode_value(buf: bytearray, v: Any) -> None:
    """Encode a physical value (int-encoded temporals/decimals, str, float,
    bytes, None) memcomparably."""
    if v is None:
        encode_null(buf)
    elif isinstance(v, bool):
        encode_int(buf, int(v))
    elif isinstance(v, int):
        encode_int(buf, v)
    elif isinstance(v, float):
        encode_float(buf, v)
    elif isinstance(v, str):
        encode_bytes(buf, v.encode("utf-8"))
    elif isinstance(v, bytes):
        encode_bytes(buf, v)
    else:
        raise TypeError(f"cannot encode {type(v).__name__}")


def encode_key(values: list[Any]) -> bytes:
    buf = bytearray()
    for v in values:
        encode_value(buf, v)
    return bytes(buf)


def decode_one(buf: bytes, pos: int) -> tuple[Any, int]:
    flag = buf[pos]
    if flag == NIL_FLAG:
        return None, pos + 1
    if flag == INT_FLAG:
        return decode_int(buf, pos)
    if flag == FLOAT_FLAG:
        return decode_float(buf, pos)
    if flag == BYTES_FLAG:
        v, pos = decode_bytes(buf, pos)
        return v, pos
    raise ValueError(f"unknown flag {flag:#x} at {pos}")


def decode_key(buf: bytes) -> list[Any]:
    out = []
    pos = 0
    while pos < len(buf):
        v, pos = decode_one(buf, pos)
        out.append(v)
    return out
