"""ctypes bindings for the C++ ordered-KV engine (native/kvstore.cpp).

Builds the shared library on first use (g++ is part of the toolchain; no
pybind11 in this environment, hence the plain C ABI). `NativeOrderedKV`
is interface-identical to mvcc.PyOrderedKV, so `MVCCStore(NativeOrderedKV())`
swaps the substrate without touching percolator logic.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path
from typing import Iterator, Optional

from ..analysis import lockcheck

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_SO = _NATIVE_DIR / "libtidbkv.so"
# TIDB_TPU_NATIVE_SANITIZE=1: load the ASan/UBSan instrumented build
# instead (native/Makefile `sanitize` target). The process must have
# libasan preloaded (LD_PRELOAD) — dlopen'ing an ASan object into a
# clean interpreter fails with "runtime does not come first"; the
# slow-marked torture test in tests/test_analysis.py spawns a child
# with the right environment.
SANITIZE_ENV = "TIDB_TPU_NATIVE_SANITIZE"
_SO_ASAN = _NATIVE_DIR / "libtidbkv_asan.so"

_lib = None
_lib_lock = threading.Lock()


class NativeUnavailable(RuntimeError):
    pass


def _sanitize_requested() -> bool:
    import os
    # same falsy spellings as lockcheck's env parsing
    return os.environ.get(SANITIZE_ENV, "") not in ("", "0", "false",
                                                    "off")


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        so, target = (_SO_ASAN, "sanitize") if _sanitize_requested() \
            else (_SO, "all")
        if not so.exists():
            try:
                subprocess.run(["make", "-C", str(_NATIVE_DIR), target],
                               check=True, capture_output=True, timeout=120)
            except (subprocess.CalledProcessError, OSError) as e:
                raise NativeUnavailable(f"cannot build {so}: {e}") from e
        try:
            lib = ctypes.CDLL(str(so))
        except OSError as e:
            if so is _SO_ASAN:
                raise NativeUnavailable(
                    f"cannot load {so.name}: {e} — the ASan runtime "
                    "must be preloaded (LD_PRELOAD=$(gcc "
                    "-print-file-name=libasan.so))") from e
            raise
        c = ctypes.c_char_p
        vp = ctypes.c_void_p
        sz = ctypes.c_size_t
        lib.kv_open.restype = vp
        lib.kv_open_at.argtypes = [c]
        lib.kv_open_at.restype = vp
        lib.kv_checkpoint.argtypes = [vp]
        lib.kv_checkpoint.restype = ctypes.c_int
        lib.kv_sync.argtypes = [vp]
        lib.kv_sync.restype = ctypes.c_int
        lib.kv_close.argtypes = [vp]
        lib.kv_put.argtypes = [vp, ctypes.c_int, c, sz, c, sz]
        lib.kv_delete.argtypes = [vp, ctypes.c_int, c, sz]
        lib.kv_get.argtypes = [vp, ctypes.c_int, c, sz,
                               ctypes.POINTER(ctypes.c_char_p)]
        lib.kv_get.restype = ctypes.c_long
        lib.kv_count.argtypes = [vp, ctypes.c_int]
        lib.kv_count.restype = sz
        lib.kv_scan.argtypes = [vp, ctypes.c_int, c, sz, c, sz,
                                ctypes.c_long]
        lib.kv_scan.restype = vp
        lib.kv_iter_next.argtypes = [
            vp, ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(sz),
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(sz)]
        lib.kv_iter_next.restype = ctypes.c_int
        lib.kv_iter_close.argtypes = [vp]
        lib.kv_seek_prev.argtypes = [
            vp, ctypes.c_int, c, sz, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(sz), ctypes.POINTER(ctypes.c_char_p)]
        lib.kv_seek_prev.restype = ctypes.c_long
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except NativeUnavailable:
        return False


class NativeOrderedKV:
    """C++-backed ordered KV; drop-in for mvcc.PyOrderedKV.

    With `path` the engine is durable: every mutation is WAL-appended
    before the in-memory map changes, and `checkpoint()` folds the state
    into a snapshot file (truncating the WAL). The file format is shared
    with the Python twin, so either engine reopens the other's directory."""

    def __init__(self, path: Optional[str] = None,
                 sync_log: str = "off",
                 sync_interval_ms: int = 100) -> None:
        self._lib = _load()
        if path is not None:
            Path(path).mkdir(parents=True, exist_ok=True)
            self._h = self._lib.kv_open_at(str(path).encode())
            if not self._h:
                raise NativeUnavailable(f"cannot open WAL dir {path}")
        else:
            self._h = self._lib.kv_open()
        self._mu = lockcheck.lock("NativeOrderedKV._mu", hot=True)
        # fsync-vs-close fence (see _fsync_native); writers never take
        # it. NOT a hot lock: holding it across the fsync IS its job
        self._sync_mu = lockcheck.lock("NativeOrderedKV._sync_mu")
        self._durable = path is not None
        # same storage.sync-log policy the Python twin honors, via the
        # SAME shared evaluator (mvcc.SyncPolicy — commit/interval
        # semantics, deferred tail flush); the C++ engine exposes one
        # kv_sync entry point, so dirtiness is tracked here (every
        # put/delete under a durable dir dirties)
        from .mvcc import SyncPolicy
        self.sync_log = sync_log
        self.sync_interval_ms = sync_interval_ms
        self._syncer = SyncPolicy(sync_log, sync_interval_ms,
                                  self._fsync_native)
        # cross-commit group fsync: like the Python twin in
        # single-process mode, the commit-boundary fsync moves out of
        # the mutation section into the commit path's rendezvous
        self._syncer.defer_commit = True

    def _fsync_native(self) -> None:
        # fsync OUTSIDE _mu: holding the write lock for the disk
        # barrier would serialize concurrent writers behind every fsync
        # and reduce the group-commit rendezvous to batches of one
        # (kv_sync itself flushes under the C++ lock and fsyncs
        # lock-free, same reasoning). _sync_mu serializes ONLY against
        # close(): kv_close frees the C++ Store, and an in-flight
        # kv_sync on the freed handle is a use-after-free.
        with self._sync_mu:
            with self._mu:
                h = self._h
            if h:
                # dynamic blocking probe: fires only if a caller holds
                # a HOT lock (the store mutex) into this fsync — the
                # deliberately-held _sync_mu close fence is not hot
                lockcheck.note_blocking("fsync", "native kv_sync")
                self._lib.kv_sync(h)

    def checkpoint(self) -> None:
        # _sync_mu: kv_checkpoint rotates the C++ WAL FILE*, and the
        # group fsync runs lock-free on that handle's fd — same fence
        # as close() so the rotation never recycles an fd mid-fsync
        with self._sync_mu, self._mu:
            if not self._h:
                return  # closed (crash-simulation checkpoint-after-close)
            self._lib.kv_checkpoint(self._h)
        self._syncer.clean()

    def sync(self) -> None:
        self._syncer.flush()

    def maybe_sync(self) -> None:
        """Commit-boundary fsync per the sync-log policy (the same
        contract as mvcc.PyOrderedKV.maybe_sync)."""
        if self._durable:
            self._syncer.boundary()

    def commit_sync(self) -> None:
        """Commit-ack group-fsync rendezvous (PyOrderedKV contract)."""
        if self._durable:
            self._syncer.commit_sync()

    def close(self) -> None:
        self._syncer.close()
        # _sync_mu first (same order as _fsync_native): an in-flight
        # group fsync finishes before the C++ Store is freed
        with self._sync_mu, self._mu:
            if self._h:
                self._lib.kv_close(self._h)
                self._h = None

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h:
            self._lib.kv_close(h)
            self._h = None

    def put(self, cf: int, key: bytes, value: bytes) -> None:
        with self._mu:
            self._lib.kv_put(self._h, cf, key, len(key), value, len(value))
        if self._durable:
            self._syncer.mark_dirty()

    def delete(self, cf: int, key: bytes) -> None:
        with self._mu:
            self._lib.kv_delete(self._h, cf, key, len(key))
        if self._durable:
            self._syncer.mark_dirty()

    def get(self, cf: int, key: bytes) -> Optional[bytes]:
        out = ctypes.c_char_p()
        with self._mu:
            n = self._lib.kv_get(self._h, cf, key, len(key),
                                 ctypes.byref(out))
            if n < 0:
                return None
            return ctypes.string_at(out, n)

    def scan(self, cf: int, start: bytes, end: bytes,
             limit: int = -1) -> Iterator[tuple[bytes, bytes]]:
        with self._mu:
            it = self._lib.kv_scan(self._h, cf, start, len(start),
                                   end, len(end), limit)
        k = ctypes.c_char_p()
        v = ctypes.c_char_p()
        kl = ctypes.c_size_t()
        vl = ctypes.c_size_t()
        try:
            while self._lib.kv_iter_next(it, ctypes.byref(k),
                                         ctypes.byref(kl), ctypes.byref(v),
                                         ctypes.byref(vl)):
                yield (ctypes.string_at(k, kl.value),
                       ctypes.string_at(v, vl.value))
        finally:
            self._lib.kv_iter_close(it)

    def seek_prev(self, cf: int, key: bytes) -> Optional[tuple[bytes, bytes]]:
        outk = ctypes.c_char_p()
        outkl = ctypes.c_size_t()
        outv = ctypes.c_char_p()
        with self._mu:
            n = self._lib.kv_seek_prev(self._h, cf, key, len(key),
                                       ctypes.byref(outk),
                                       ctypes.byref(outkl),
                                       ctypes.byref(outv))
            if n < 0:
                return None
            return (ctypes.string_at(outk, outkl.value),
                    ctypes.string_at(outv, n))

    def count(self, cf: int) -> int:
        with self._mu:
            return int(self._lib.kv_count(self._h, cf))
