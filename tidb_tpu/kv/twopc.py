"""Two-phase percolator commit + lock resolver + TSO-driven snapshots.

Counterpart of the reference's twoPhaseCommitter (reference:
store/tikv/2pc.go:78 — execute :1050, region-grouped batches :616,670,
primary-first commit :730-761) and LockResolver (reference:
store/tikv/lock_resolver.go — check primary txn status, roll
forward/backward). In-process regions replace gRPC; the retry loop against
RegionError and KeyIsLocked is the same control flow the reference runs
against real TiKV.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Optional

from .. import obs
from ..util import failpoint
from .mvcc import KeyIsLockedError, KVError, Mutation
from ..rpc.errors import RPCError
from .region import Region, RegionError, RegionManager


class TSO:
    """Monotonic timestamp oracle (reference: oracle/oracles/pd.go —
    physical<<18 | logical layout; local twin oracle/oracles/local.go)."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._last_physical = 0
        self._logical = 0

    def ts(self) -> int:
        with self._mu:
            physical = int(time.time() * 1000)
            if physical <= self._last_physical:
                physical = self._last_physical
                self._logical += 1
            else:
                self._last_physical = physical
                self._logical = 0
            return (physical << 18) | self._logical


class CommitError(Exception):
    errno = 9007  # ER_WRITE_CONFLICT (tidb_tpu/errno.py)
    sqlstate = "HY000"


class LockResolver:
    """Resolves locks left by crashed/slow transactions (reference:
    store/tikv/lock_resolver.go ResolveLocks)."""

    def __init__(self, rm: RegionManager, tso: TSO,
                 events=None) -> None:
        self.rm = rm
        self.tso = tso
        # optional structured EventLog sink: every orphan actually
        # rolled forward/back is auditable in /debug/events
        self.events = events

    def resolve(self, lock) -> bool:
        """True if the lock was cleared (caller may retry immediately).

        Goes through the rm-level resolver surface, not rm.store: over
        the range tier (kv/rangeclient.py) the primary's status lives on
        ANOTHER range's leader, so the status check and the resolve are
        two routed calls — exactly how a peer rolls a crashed
        coordinator's orphans forward/backward."""
        with obs.wait("resolve_lock"):
            commit_ts, done = self.rm.check_txn_status(
                lock.primary, lock.start_ts, self.tso.ts())
            if not done:
                return False  # lock holder still alive; caller backs off
            self.rm.resolve_lock(lock.key, lock.start_ts, commit_ts)
        if self.events is not None:
            coll = obs.active_collector()
            action = "roll-forward" if commit_ts else "roll-back"
            self.events.record(
                "orphan_resolved",
                detail=f"{action} key={lock.key!r} "
                       f"primary={lock.primary!r} "
                       f"start_ts={lock.start_ts} commit_ts={commit_ts} "
                       f"trace_id={coll.trace_id if coll else ''}")
        return True


@dataclass
class TwoPhaseCommitter:
    rm: RegionManager
    tso: TSO
    lock_ttl: int = 3000
    max_retries: int = 12
    # how long a commit waits on someone else's (live) lock before giving
    # up — pessimistic txns hold locks for arbitrary user-paced durations,
    # so this is time-based, unlike the count-based region retries
    # (reference: backoff.go txnLockFastBackoff with a total budget)
    lock_wait_timeout_s: float = 50.0
    # structured EventLog sink for orphan resolutions (the storage
    # passes its obs.events; bare committers audit nothing)
    events: Optional[object] = None
    # keyspace heat recorder (obs_heat.RangeHeatRecorder). ONLY the
    # storage's committer over LOCAL regions carries it — the range
    # tier's per-worker committers leave it None so a routed write is
    # counted once, by the range leader's apply (rpc/ranged.py)
    heat: Optional[object] = None

    def commit(self, mutations: list[Mutation], start_ts: int) -> int:
        """Run 2PC; returns commit_ts (reference: 2pc.go execute :1050)."""
        if not mutations:
            return start_ts
        state = self.prewrite_phase(mutations, start_ts)
        return self.commit_phase(state, start_ts)

    def prewrite_phase(self, mutations: list[Mutation], start_ts: int):
        """Phase 1 only. This is where commit blocks on other txns' locks
        (possibly for the whole lock-wait timeout), so callers must NOT
        hold serializing locks across it — the storage runs it outside
        its commit lock (the reference has no such global lock; its fold
        equivalent is TiFlash's async raft apply)."""
        with obs.wait("prewrite"), obs.span("twopc.prewrite") as sp:
            if sp:
                sp.note = f"{len(mutations)} keys"
            return self._prewrite_phase(mutations, start_ts)

    def _prewrite_phase(self, mutations: list[Mutation], start_ts: int):
        resolver = LockResolver(self.rm, self.tso, events=self.events)
        mutations = sorted(mutations, key=lambda m: m.key)
        # the primary must leave a write record: a lock-only (OP_LOCK)
        # primary would give crash recovery nothing to roll forward from
        # (reference: 2pc.go primary selection skips lock-only keys)
        from .mvcc import OP_LOCK
        primary = next((m.key for m in mutations if m.op != OP_LOCK),
                       mutations[0].key)

        # prewrite grouped by region, primary's batch first
        # (reference: 2pc.go:730 prewrite primary first for async recovery)
        failpoint.inject("twopc/before-prewrite")
        self._run_batches(
            mutations, primary, resolver,
            lambda region, batch: self.rm.prewrite(
                region, batch, primary, start_ts, self.lock_ttl))
        # crash here = fully-prewritten, uncommitted txn: every lock is
        # orphaned and must roll BACK once its TTL expires (reference
        # failpoint site: 2pc.go:704 prewrite fail injection)
        failpoint.inject("twopc/after-prewrite")
        return mutations, primary, resolver

    def commit_phase(self, state, start_ts: int) -> int:
        """Phase 2: never waits on foreign locks (we hold every key),
        so it is safe inside the storage commit lock."""
        with obs.span("twopc.commit"):
            return self._commit_phase(state, start_ts)

    def _commit_phase(self, state, start_ts: int) -> int:
        mutations, primary, resolver = state
        # commit timestamps go through the oracle's COMMIT interface
        # when it has one (RemoteTSO.commit_ts): the leader's pending-
        # commit ledger must know this ts may stamp records that are
        # not published yet, or the follower read tier could close a
        # timestamp past an in-flight remote commit. Local oracles
        # have no ledger — their commits run under the storage commit
        # lock the closed-ts computation also takes.
        alloc = getattr(self.tso, "commit_ts", None) or self.tso.ts
        with obs.wait("tso_wait"):
            commit_ts = alloc()

        # over the RANGE tier (RangeRouter exposes txn_done), a
        # cross-range transaction must hold the pending-commit ledger
        # open on EVERY participant range until its secondaries are
        # durable — commits carry done=False and the fan-out below
        # releases the holds. Single-range traffic (and the in-process
        # region tier) keeps the retire-on-commit fast path.
        fanout = getattr(self.rm, "txn_done", None)
        cross = False
        if fanout is not None:
            try:
                cross = len({self.rm.locate(m.key).id
                             for m in mutations}) > 1
            except (RegionError, RPCError):
                cross = True  # routing unsettled: hold conservatively

        def commit_call(region, keys):
            if fanout is not None:
                return self.rm.commit(region, keys, start_ts,
                                      commit_ts, done=not cross)
            return self.rm.commit(region, keys, start_ts, commit_ts)

        # commit the primary synchronously — the txn is durable
        # once this lands (reference: 2pc.go:741)
        failpoint.inject("twopc/before-commit-primary")
        with obs.wait("commit_primary",
                      span_name="twopc.commit_primary"):
            self._retry_region(
                primary, resolver,
                lambda region: commit_call(region, [primary]))
        # crash here = committed txn with secondary locks left behind:
        # the resolver must roll them FORWARD from the primary's write
        # record (reference failpoint site: 2pc.go:1027)
        failpoint.inject("twopc/after-primary-commit")
        # the txn is durable: account it on the keyspace heatmap (keys
        # route to range cells; OP_LOCK values are empty — 0 bytes)
        if self.heat is not None and self.heat.enabled:
            self.heat.note_write(
                [(m.key, len(m.value or b"")) for m in mutations])
        # secondaries may commit lazily; do them inline (the reference
        # fires a goroutine — same semantics, resolver covers crashes).
        # IMPORTANT: the txn is already durable — a secondary failure must
        # NOT surface as a commit failure (the lock resolver rolls the
        # stragglers forward from the committed primary)
        rest = [m.key for m in mutations if m.key != primary]
        if rest:
            with obs.wait("commit_secondary",
                          span_name="twopc.commit_secondary"):
                for key in rest:
                    try:
                        self._retry_region(
                            key, resolver,
                            lambda region, k=key: commit_call(
                                region, [k]))
                    except (CommitError, KVError):
                        # resolver recovers from the primary's record
                        pass
        if cross:
            # every participant's secondaries were driven durable
            # above: release the ledger holds so each range's
            # closed_ts may pass commit_ts. Best-effort — a lost
            # txn_done costs hold-TTL latency, never correctness.
            done_rids: set = set()
            for m in mutations:
                try:
                    region = self.rm.locate(m.key)
                except (RegionError, RPCError):
                    continue
                if region.id in done_rids:
                    continue
                done_rids.add(region.id)
                fanout(region, start_ts)
        return commit_ts

    def rollback(self, mutations: list[Mutation], start_ts: int) -> None:
        resolver = LockResolver(self.rm, self.tso, events=self.events)
        for m in mutations:
            self._retry_region(
                m.key, resolver,
                lambda region, k=m.key: self.rm.rollback(
                    region, [k], start_ts))

    # ---- helpers -----------------------------------------------------------
    def _run_batches(self, mutations, primary, resolver, fn) -> None:
        """Group by region, primary's batch first — re-locating and
        re-grouping on EVERY attempt: an online split moves keys to a
        fresh region/epoch mid-flight, and retrying with the handle
        that just answered EpochNotMatch would exhaust the budget
        without ever seeing the reloaded table. Re-sending an already-
        applied batch is safe — prewrite/commit/rollback are all
        idempotent per (key, start_ts) (see mvcc._prewrite_check)."""
        def attempt():
            groups: dict[int, tuple[Region, list[Mutation]]] = {}
            for m in mutations:
                r = self.rm.locate(m.key)
                groups.setdefault(r.id, (r, []))[1].append(m)
            ordered = sorted(
                groups.values(),
                key=lambda g: 0 if any(m.key == primary
                                       for m in g[1]) else 1)
            for region, batch in ordered:
                fn(region, batch)

        self._retry(attempt, [m.key for m in mutations], resolver)

    def _retry_region(self, key: bytes, resolver, fn) -> None:
        self._retry(lambda: fn(self.rm.locate(key)), [key], resolver)

    def _retry(self, fn, keys, resolver) -> None:
        backoff = 0.001
        region_errs = 0
        deadline = time.monotonic() + self.lock_wait_timeout_s
        while True:
            try:
                fn()
                return
            except RegionError:
                region_errs += 1  # refreshed routing on next call
                if region_errs >= self.max_retries:
                    raise CommitError(
                        f"region retries exhausted for keys {keys[:2]}...")
            except KeyIsLockedError as e:
                if resolver.resolve(e.lock):
                    continue
                if time.monotonic() >= deadline:
                    err = CommitError(
                        "Lock wait timeout exceeded; try restarting "
                        "transaction")
                    err.errno = 1205  # ER_LOCK_WAIT_TIMEOUT
                    raise err from None
                time.sleep(backoff)
                _note_lock_backoff(backoff)
                backoff = min(backoff * 2, 0.05)


def _note_lock_backoff(seconds: float) -> None:
    """Type a foreign-lock wait sleep: the backoff families plus the
    active statement's wait ledger — no silent time.sleep on the
    commit/read retry paths."""
    obs.BACKOFF_SECONDS.observe(seconds, kind="txnLock")
    obs.BACKOFF_EVENTS.inc(kind="txnLock")
    obs.note_wait("backoff.txnLock", seconds)


class Snapshot:
    """Read view at one ts over the region tier (reference:
    store/tikv/snapshot.go — Get :122, BatchGet :223, with lock
    resolution on read)."""

    def __init__(self, rm: RegionManager, tso: TSO, read_ts: int) -> None:
        self.rm = rm
        self.read_ts = read_ts
        self._resolver = LockResolver(rm, tso)

    def get(self, key: bytes) -> Optional[bytes]:
        backoff = 0.001
        for _ in range(12):
            try:
                return self.rm.get(self.rm.locate(key), key, self.read_ts)
            except RegionError:
                continue
            except KeyIsLockedError as e:
                if not self._resolver.resolve(e.lock):
                    time.sleep(backoff)
                    _note_lock_backoff(backoff)
                    backoff = min(backoff * 2, 0.1)
        raise CommitError(f"read of {key!r} kept hitting locks")

    def scan(self, start: bytes, end: bytes,
             limit: int = -1) -> list[tuple[bytes, bytes]]:
        backoff = 0.001
        for _ in range(12):
            try:
                return self.rm.scan(start, end, self.read_ts, limit)
            except RegionError:
                continue  # split/reload mid-scan: routing refreshed
            except KeyIsLockedError as e:
                if not self._resolver.resolve(e.lock):
                    time.sleep(backoff)
                    _note_lock_backoff(backoff)
                    backoff = min(backoff * 2, 0.1)
        raise CommitError("scan kept hitting locks")
