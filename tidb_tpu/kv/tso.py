"""Timestamp oracle: hybrid physical/logical timestamps.

Single-process equivalent of PD's TSO service (reference:
store/tikv/oracle/oracles/pd.go:77 for the PD-backed oracle,
oracle/oracles/local.go for the single-node one). Timestamps use PD's
layout — physical milliseconds << 18 | logical counter — because the MVCC
tier derives lock TTL expiry from `now_ts - lock_ts > ttl << 18`
(reference: oracle.ExtractPhysical); a plain counter would make abandoned
prewrite locks effectively immortal. start_ts/commit_ts ordering is the
basis of snapshot-isolation visibility in the MVCC store.
"""

from __future__ import annotations

import threading
import time

_LOGICAL_BITS = 18


class TimestampOracle:
    def __init__(self, floor: int = 0, node_id: int = 0,
                 n_nodes: int = 1) -> None:
        """`floor`: restart lower bound — every issued ts is > floor
        (recovery passes the persisted lease so timestamps never repeat
        across restarts even under clock skew; reference analog: PD's
        persisted TSO window, oracle/oracles/pd.go).

        `node_id`/`n_nodes`: multi-process deployments slice the logical
        bits per node so timestamps are unique across processes sharing
        one store directory with no hot-path coordination (the PD role
        without a PD; store/coordinator.py)."""
        self._lock = threading.Lock()
        self._slice = (1 << _LOGICAL_BITS) // max(n_nodes, 1)
        self._base = node_id * self._slice
        self._physical = floor >> _LOGICAL_BITS
        logical = floor & ((1 << _LOGICAL_BITS) - 1)
        self._logical = max(logical - self._base, 0) \
            if n_nodes > 1 else logical

    def next_ts(self) -> int:
        with self._lock:
            physical = int(time.time() * 1000)
            if physical <= self._physical:
                self._logical += 1
                if self._logical >= self._slice:
                    # logical slice exhausted within one millisecond:
                    # borrow the next physical tick
                    self._physical += 1
                    self._logical = 0
            else:
                self._physical = physical
                self._logical = 0
            return (self._physical << _LOGICAL_BITS) | \
                (self._base + self._logical)

    def observe(self, ts: int) -> None:
        """Advance past an externally observed timestamp (a sibling
        process's commit seen during WAL refresh) so every timestamp we
        issue afterwards is strictly greater — required for the sibling's
        commits to be VISIBLE to our snapshots (commit_ts <= read_ts)."""
        with self._lock:
            phys = ts >> _LOGICAL_BITS
            logi = ts & ((1 << _LOGICAL_BITS) - 1)
            if phys < self._physical:
                return
            if phys > self._physical:
                self._physical = phys
                self._logical = 0
            if logi >= self._base + self._logical:
                need = logi - self._base
                if need + 1 >= self._slice:
                    # observed logical beyond our slice in this tick:
                    # borrow the next physical tick
                    self._physical = phys + 1
                    self._logical = 0
                else:
                    self._logical = need

    # the 2PC committer's oracle interface (kv/twopc.py TSO protocol)
    def ts(self) -> int:
        return self.next_ts()

    def current(self) -> int:
        with self._lock:
            return (self._physical << _LOGICAL_BITS) | self._logical
