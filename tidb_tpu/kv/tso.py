"""Timestamp oracle: hybrid physical/logical timestamps.

Single-process equivalent of PD's TSO service (reference:
store/tikv/oracle/oracles/pd.go:77 for the PD-backed oracle,
oracle/oracles/local.go for the single-node one). Timestamps use PD's
layout — physical milliseconds << 18 | logical counter — because the MVCC
tier derives lock TTL expiry from `now_ts - lock_ts > ttl << 18`
(reference: oracle.ExtractPhysical); a plain counter would make abandoned
prewrite locks effectively immortal. start_ts/commit_ts ordering is the
basis of snapshot-isolation visibility in the MVCC store.
"""

from __future__ import annotations

import threading
import time

_LOGICAL_BITS = 18


class TimestampOracle:
    def __init__(self, floor: int = 0) -> None:
        """`floor`: restart lower bound — every issued ts is > floor
        (recovery passes the persisted lease so timestamps never repeat
        across restarts even under clock skew; reference analog: PD's
        persisted TSO window, oracle/oracles/pd.go)."""
        self._lock = threading.Lock()
        self._physical = floor >> _LOGICAL_BITS
        self._logical = floor & ((1 << _LOGICAL_BITS) - 1)

    def next_ts(self) -> int:
        with self._lock:
            physical = int(time.time() * 1000)
            if physical <= self._physical:
                self._logical += 1
            else:
                self._physical = physical
                self._logical = 0
            return (self._physical << _LOGICAL_BITS) | self._logical

    # the 2PC committer's oracle interface (kv/twopc.py TSO protocol)
    def ts(self) -> int:
        return self.next_ts()

    def current(self) -> int:
        with self._lock:
            return (self._physical << _LOGICAL_BITS) | self._logical
