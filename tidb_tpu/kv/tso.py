"""Timestamp oracle: monotonically increasing logical timestamps.

Single-process equivalent of PD's TSO service (reference:
store/tikv/oracle/oracles/pd.go:77 for the PD-backed oracle,
oracle/oracles/local.go for the single-node one). start_ts/commit_ts
ordering is the basis of snapshot-isolation visibility in the MVCC store.
"""

from __future__ import annotations

import threading


class TimestampOracle:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ts = 0

    def next_ts(self) -> int:
        with self._lock:
            self._ts += 1
            return self._ts

    def current(self) -> int:
        with self._lock:
            return self._ts
