"""Timestamp oracles: hybrid physical/logical timestamps.

Equivalents of PD's TSO service (reference:
store/tikv/oracle/oracles/pd.go:77 for the PD-backed oracle,
oracle/oracles/local.go for the single-node one). Timestamps use PD's
layout — physical milliseconds << 18 | logical counter — because the MVCC
tier derives lock TTL expiry from `now_ts - lock_ts > ttl << 18`
(reference: oracle.ExtractPhysical); a plain counter would make abandoned
prewrite locks effectively immortal. start_ts/commit_ts ordering is the
basis of snapshot-isolation visibility in the MVCC store.

Three implementations:

* `TimestampOracle` — in-process allocator (single-server stores).
* `RemoteTSO` — RPC proxy to the store leader's allocator (socket
  followers; the PD-client role, reference: oracle/oracles/pd.go
  GetTimestamp over the PD RPC pool). Strictness is inherited: every
  timestamp is issued by the ONE leader allocator. When the leader is
  unreachable past the backoff budget the oracle can degrade to
  re-issuing the last replicated timestamp for READS (bounded-staleness
  follower reads); such timestamps sit at or below `stale_watermark`,
  and the storage layer refuses to let a transaction whose start_ts is
  under the watermark write — degraded followers are read-only.
* `SharedTSO` — ONE allocator for all processes sharing a durable store
  directory: an mmap'd shared counter advanced under a dedicated flock,
  with a persisted allocation window (fsync'd every `_WINDOW_MS` of
  timestamp space, PD's TSO-window pattern) so a full-cluster crash can
  never re-issue a timestamp. This is what makes cross-process snapshot
  isolation STRICT: any commit_ts a sibling obtained is <= the counter,
  so every later snapshot ts is strictly greater and the WAL refresh can
  never surface a commit inside an already-open snapshot (the round-4
  node-sliced TSO admitted exactly that same-millisecond anomaly).
"""

from __future__ import annotations

import fcntl
import mmap
import os
import struct
import threading
import time

from .. import obs

_LOGICAL_BITS = 18


class TimestampOracle:
    def __init__(self, floor: int = 0) -> None:
        """`floor`: restart lower bound — every issued ts is > floor
        (recovery passes the persisted lease so timestamps never repeat
        across restarts even under clock skew; reference analog: PD's
        persisted TSO window, oracle/oracles/pd.go). Multi-process
        deployments use `SharedTSO` instead (one allocator, strict SI)."""
        self._lock = threading.Lock()
        self._physical = floor >> _LOGICAL_BITS
        self._logical = floor & ((1 << _LOGICAL_BITS) - 1)

    def next_ts(self) -> int:
        with self._lock:
            physical = int(time.time() * 1000)
            if physical <= self._physical:
                self._logical += 1
                if self._logical >= (1 << _LOGICAL_BITS):
                    # logical space exhausted within one millisecond:
                    # borrow the next physical tick
                    self._physical += 1
                    self._logical = 0
            else:
                self._physical = physical
                self._logical = 0
            return (self._physical << _LOGICAL_BITS) | self._logical

    def observe(self, ts: int) -> None:
        """Advance past an externally observed timestamp so every
        timestamp we issue afterwards is strictly greater — required for
        observed commits to be VISIBLE to our snapshots
        (commit_ts <= read_ts)."""
        with self._lock:
            phys = ts >> _LOGICAL_BITS
            logi = ts & ((1 << _LOGICAL_BITS) - 1)
            if phys < self._physical:
                return
            if phys > self._physical:
                self._physical = phys
                self._logical = 0
            if logi > self._logical:
                self._logical = logi

    # the 2PC committer's oracle interface (kv/twopc.py TSO protocol)
    def ts(self) -> int:
        return self.next_ts()

    def current(self) -> int:
        with self._lock:
            return (self._physical << _LOGICAL_BITS) | self._logical


class RemoteTSO:
    """Leader-allocated timestamps over RPC (PD-client role).

    `next_ts` (snapshot acquisition) may fall back to a stale re-issue
    when degraded; `ts` (the 2PC committer's interface) NEVER does — a
    commit timestamp must come from the live allocator or the commit
    must fail typed."""

    def __init__(self, client, allow_stale: bool = True) -> None:
        self._client = client
        self._allow_stale = allow_stale
        self._lock = threading.Lock()
        self._seen = 0            # highest leader-issued ts witnessed
        self.stale_watermark = 0  # every stale re-issue is <= this
        # the commit ts this THREAD holds open in the leader's ledger
        # (commit_ts/commit_done pair up per committing thread; a
        # process-wide flag would let one thread's late done retire a
        # sibling's in-flight entry)
        self._commit_tl = threading.local()

    def _remote_next(self) -> int:
        # typed wait: time blocked on the leader's allocator is
        # tso_wait unless an enclosing frame (a 2PC phase) already
        # owns it
        with obs.wait("tso_wait", fallback=True):
            ts = int(self._client.call("tso_next")["ts"])
        with self._lock:
            if ts > self._seen:
                self._seen = ts
        return ts

    def commit_ts(self) -> int:
        """A COMMIT timestamp: allocated through the leader's
        pending-commit ledger (rpc/server.py tso_commit) so the
        closed-timestamp protocol of the follower read tier never
        closes past a commit whose records are still unpublished.
        Strict like ts(): never degrades to a stale re-issue."""
        with obs.wait("tso_wait", fallback=True):
            ts = int(self._client.call("tso_commit")["ts"])
        with self._lock:
            if ts > self._seen:
                self._seen = ts
        self._commit_tl.ts = ts
        return ts

    def commit_done(self) -> None:
        """Retire the pending-commit ledger entry once the commit phase
        finished (its records are published, or definitively never will
        be). Carries the exact ts so a done that arrives late — after
        the same client's NEXT commit replaced the ledger slot — is a
        no-op server-side. Best effort: the leader also retires the
        entry by replacement on the next tso_commit and on client
        reap."""
        ts = getattr(self._commit_tl, "ts", 0)
        if not ts:
            return
        self._commit_tl.ts = 0
        from ..rpc.errors import RPCError
        try:
            self._client.call("tso_commit_done", ts=ts, _budget_ms=500)
        except RPCError:
            pass

    def next_ts(self) -> int:
        from ..rpc.errors import RPCError
        if not (self._client.degraded and self._allow_stale):
            try:
                return self._remote_next()
            except RPCError:
                if not self._allow_stale:
                    raise
        # degraded read-only mode: re-issue the last replicated ts.
        # Re-issuing (rather than bumping) keeps every fallback value
        # strictly below anything the live allocator will ever hand
        # out, so the watermark check cleanly fences writes.
        with self._lock:
            if self.stale_watermark < self._seen:
                self.stale_watermark = self._seen
            return self._seen

    def ts(self) -> int:
        return self._remote_next()

    def observe(self, ts: int) -> None:
        """Track replicated commit timestamps locally (they were issued
        by the leader allocator, so no RPC is needed to stay ordered)."""
        with self._lock:
            if ts > self._seen:
                self._seen = ts

    def current(self) -> int:
        with self._lock:
            return self._seen

    def close(self) -> None:
        pass


# window persisted ahead of issued timestamps: every issued ts is < the
# on-disk window, so restart-after-crash floors above everything issued
_WINDOW_MS = 3000


class SharedTSO:
    """Strict cross-process TSO over a shared store directory.

    Files (all under `path`):
      tso.mem    — 8-byte mmap'd counter: the last issued timestamp.
                   MAP_SHARED, so every process sees each allocation
                   immediately; durability is NOT required of this file.
      tso.alloc  — flock'd for the read-bump-write critical section.
      tso.window — decimal upper bound W with invariant issued < W;
                   extended (+ fsync) whenever an allocation approaches
                   it. The PD TSO-window pattern (oracle/oracles/pd.go):
                   pay an fsync per ~3s of timestamp space, not per ts.
      tso.live   — held LOCK_SH by every live process; a LOCK_EX probe
                   succeeding means no process is live, so the prober
                   re-seeds tso.mem from max(mem, window, floor) —
                   recovery after a full-cluster crash where the mmap
                   page was never written back.
    """

    def __init__(self, path: str, floor: int = 0) -> None:
        self._lock = threading.Lock()
        self._alloc_f = open(os.path.join(path, "tso.alloc"), "a+b")
        self._window_path = os.path.join(path, "tso.window")
        mem_path = os.path.join(path, "tso.mem")
        self._live_f = open(os.path.join(path, "tso.live"), "a+b")
        with self._alloc_locked():  # serialize the 8-byte init vs peers
            with open(mem_path, "a+b") as f:
                f.seek(0, 2)
                if f.tell() < 8:
                    f.write(b"\0" * (8 - f.tell()))
                    f.flush()
        self._mem_f = open(mem_path, "r+b")
        self._mem = mmap.mmap(self._mem_f.fileno(), 8)
        # first-process re-seed: EX probe on tso.live (everyone else
        # holds SH); downgrade to SH afterwards and hold it for life
        try:
            fcntl.flock(self._live_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            first = True
        except OSError:
            first = False
        if first:
            with self._alloc_locked():
                last = max(self._read_mem(), self._read_window(), floor)
                self._write_mem(last)
        fcntl.flock(self._live_f, fcntl.LOCK_SH)  # downgrade (or join)
        self._window = self._read_window()

    # ---- low-level shared state -------------------------------------------
    def _read_mem(self) -> int:
        return struct.unpack("<q", self._mem[:8])[0]

    def _write_mem(self, ts: int) -> None:
        self._mem[:8] = struct.pack("<q", ts)

    def _read_window(self) -> int:
        try:
            with open(self._window_path) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return 0

    def _extend_window(self, need: int) -> None:
        w = need + (_WINDOW_MS << _LOGICAL_BITS)
        tmp = self._window_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(w))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._window_path)
        # the rename itself must be durable: without fsync'ing the
        # directory a power loss can revert to the OLD window and re-issue
        # timestamps — the one invariant this file exists to keep
        dfd = os.open(os.path.dirname(self._window_path) or ".",
                      os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._window = w

    class _AllocLock:
        def __init__(self, f):
            self._f = f

        def __enter__(self):
            fcntl.flock(self._f, fcntl.LOCK_EX)

        def __exit__(self, *exc):
            fcntl.flock(self._f, fcntl.LOCK_UN)

    def _alloc_locked(self):
        return self._AllocLock(self._alloc_f)

    # ---- oracle interface --------------------------------------------------
    def next_ts(self) -> int:
        # the cross-process flock IS a wait: type it so a contended
        # shared allocator shows up as tso_wait, not untyped wall
        with obs.wait("tso_wait", fallback=True), \
                self._lock, self._alloc_locked():
            last = self._read_mem()
            # +1 carries logical overflow into physical: the borrow-next-
            # tick behavior of the in-process oracle, for free
            cand = max(last + 1, int(time.time() * 1000) << _LOGICAL_BITS)
            # cached window keeps file I/O off the per-ts path; a sibling
            # may have extended it further on disk, so a cache miss
            # re-reads before paying the fsync (stale-low cache is safe:
            # it only ever triggers this re-read under the same flock)
            if cand >= self._window:
                self._window = self._read_window()
                if cand >= self._window:
                    self._extend_window(cand)
            self._write_mem(cand)
            return cand

    def observe(self, ts: int) -> None:
        """With one shared allocator every sibling commit_ts is already
        <= the counter; this remains as a cheap invariant net for
        timestamps from OUTSIDE the allocator (none today)."""
        if ts <= self._read_mem():
            return
        with self._lock, self._alloc_locked():
            if ts > self._read_mem():
                if ts >= self._window:
                    self._window = self._read_window()
                    if ts >= self._window:
                        self._extend_window(ts)
                self._write_mem(ts)

    def ts(self) -> int:
        return self.next_ts()

    def current(self) -> int:
        return self._read_mem()

    def close(self) -> None:
        for h in (self._mem, self._mem_f, self._alloc_f, self._live_f):
            try:
                h.close()
            except OSError:
                pass
