"""Percolator MVCC store: lock/write/data columns over an ordered KV.

Counterpart of the reference's in-process TiKV MVCC engines (reference:
store/mockstore/mocktikv/mvcc_leveldb.go — Prewrite :commitOneKey paths,
Commit, Rollback, ResolveLock, Get/Scan with lock checks) and the
percolator model TiKV itself implements. The ordered-KV substrate is
pluggable: `PyOrderedKV` here, the C++ engine in kv/native.py — both expose
put/delete/get/scan over (cf, key) -> bytes.

Column families:
  lock:  key -> (start_ts, primary, op, ttl)
  write: key + rev(commit_ts) -> (start_ts, kind)   kind: P/D/R
  data:  key + rev(start_ts)  -> value bytes
"""

from __future__ import annotations

import bisect
import struct
import threading
from dataclasses import dataclass
from typing import Iterator, Optional

from .. import obs
from ..analysis import lockcheck
from .codec import encode_uint_desc

CF_LOCK = 0
CF_WRITE = 1
CF_DATA = 2

OP_PUT = b"P"
OP_DEL = b"D"
OP_ROLLBACK = b"R"
OP_LOCK = b"L"  # lock-only mutation (SELECT FOR UPDATE)


class KVError(Exception):
    pass


def fsync_dir(path: str) -> None:
    """Durable-rename helper: fsync the DIRECTORY so a tmp+rename
    sequence survives power loss (the rename itself lives in the
    directory's metadata; fsyncing only the file leaves the old name
    recoverable)."""
    import os
    fd = os.open(path or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class SyncPolicy:
    """THE storage.sync-log policy evaluator, shared by every WAL-ish
    sink (engine WAL, native engine, follower mirror, leader-side
    remote appends) so the policy lives in one place:

      off      — never fsync (flushing to the OS is the caller's job)
      commit   — fsync at every boundary() call; an fsync failure
                 PROPAGATES so the commit is never acked undurable
      interval — group commit: at most one fsync per interval_ms. The
                 tail burst before an idle period is covered by a
                 deferred one-shot flush timer, so the loss window is
                 genuinely bounded by interval_ms, not by when the
                 next commit happens to arrive.

    Cross-commit group fsync (`commit` mode): with `defer_commit` set
    by the owning engine, boundary() leaves the commit's bytes flushed
    to the OS and the COMMIT PATH calls commit_sync() after releasing
    its locks. Concurrent committers rendezvous there on one in-flight
    fsync — an fsync covers every byte written before it started, so N
    waiters whose writes predate the leader's fsync all become durable
    for the price of one disk barrier (reference: raft-store write
    batching / MySQL binlog group commit). The durability contract is
    UNCHANGED: nobody returns from commit_sync() until an fsync that
    started after their last write completed, and a failed fsync
    propagates to (or is retried by) every waiter it stranded.

    `fsync` is the sink's own durability callable; it must tolerate
    being invoked after close (the deferred timer may race teardown).
    """

    __slots__ = ("policy", "interval_ms", "_fsync", "_lock", "_last",
                 "_dirty", "_timer", "_closed", "on_stall", "stall_ms",
                 "defer_commit", "group_max_batch", "group_max_wait_us",
                 "on_batch", "_cv", "_wgen", "_sgen", "_sync_active",
                 "_waiters")

    # an fsync slower than this reports a stall (a healthy fsync is
    # single-digit ms; ~17ms is this box's measured commit fsync — the
    # threshold flags the pathological tail, not the normal case)
    STALL_MS_DEFAULT = 100.0

    def __init__(self, policy: str, interval_ms: int, fsync) -> None:
        self.policy = policy
        self.interval_ms = interval_ms
        self._fsync = fsync
        self._lock = lockcheck.lock("SyncPolicy._lock")
        self._last = 0.0
        self._dirty = False
        self._timer = None
        self._closed = False
        # stall reporting hook (seconds -> None), wired by the Storage
        # to its event ring; exceptions are swallowed — telemetry must
        # never fail a commit whose fsync succeeded
        self.on_stall = None
        self.stall_ms = self.STALL_MS_DEFAULT
        # ---- cross-commit group fsync (commit mode) ----
        # defer_commit: the owning engine routes commit-boundary
        # durability through commit_sync() instead of the in-section
        # boundary() (False here so a bare SyncPolicy keeps the exact
        # fsync-per-boundary behavior)
        self.defer_commit = False
        # leader gather window: once elected, wait up to max-wait-µs
        # for more committers to join (0 = fsync immediately; the
        # natural rendezvous during a slow fsync already batches) —
        # skipped once max-batch committers are aboard
        self.group_max_batch = 64
        self.group_max_wait_us = 0
        # batch telemetry hook (batch_size -> None), wired by the
        # Storage to tidb_group_commit_batch_size; never fails a commit
        self.on_batch = None
        self._cv = threading.Condition(self._lock)
        # write generation vs the generation covered by the last
        # completed fsync: a committer whose writes are <= _sgen is
        # durable without touching the disk itself
        self._wgen = 0
        self._sgen = 0
        self._sync_active = False
        self._waiters = 0

    def mark_dirty(self) -> None:
        # plain flag store — called once per WAL record on the write
        # hot path; the group-commit write GENERATION advances at
        # mutation-section granularity in boundary() instead, so bulk
        # loads don't pay a lock round-trip per row
        self._dirty = True

    def boundary(self) -> None:
        """Commit-boundary hook. OSError from the sink propagates (the
        caller must not ack a commit whose durability failed)."""
        if not self._dirty or self.policy == "off":
            return
        if self.policy == "commit":
            if not self.defer_commit:
                self.flush()
                return
            # deferred: every record of this mutation section is
            # already written; CONSUME the dirty mark into one
            # generation bump that fences them all for the commit
            # path's commit_sync() rendezvous (which runs AFTER the
            # caller's locks release, so concurrent committers share
            # the fsync instead of serializing). A sibling section's
            # mark consumed here is safe: its records were written
            # before this bump, so this generation covers them; records
            # it writes later re-mark and re-fence at its own exit.
            with self._lock:
                self._dirty = False
                self._wgen += 1
            return
        import time as _time
        now = _time.monotonic()
        with self._lock:
            due = now - self._last >= self.interval_ms / 1000.0
            if not due:
                if self._timer is None and not self._closed:
                    # cover the tail burst: without this, commits that
                    # land inside the window and are followed by idle
                    # time would stay un-fsynced indefinitely
                    delay = self.interval_ms / 1000.0 - (now - self._last)
                    t = threading.Timer(max(delay, 0.001),
                                        self._deferred_flush)
                    t.daemon = True
                    t.name = "titpu-sync-flush"
                    self._timer = t
                    t.start()
                return
        self.flush()

    def _deferred_flush(self) -> None:
        with self._lock:
            self._timer = None
            if self._closed:
                return
        if self._dirty:
            try:
                self.flush()
            except OSError:
                pass  # still dirty: the next boundary retries loudly

    def flush(self) -> None:
        """Unconditional sync-now (checkpoint/close path too)."""
        import time as _time
        with self._lock:
            start = self._wgen
        self._timed_fsync()
        with self._lock:
            self._dirty = False
            if start > self._sgen:
                self._sgen = start
            self._last = _time.monotonic()
            self._cv.notify_all()

    def _timed_fsync(self) -> None:
        import time as _time
        # dynamic twin of the blocking-call-under-hot-lock rule: a
        # disk barrier with a hot lock held is a typed finding (one
        # module-global bool probe when the checker is off)
        lockcheck.note_blocking("fsync", "SyncPolicy WAL fsync")
        t0 = _time.perf_counter()
        # typed wait + server span: a range leader's commit fsync rides
        # back to the coordinator's trace as wal.fsync
        with obs.wait("fsync_wait", span_name="wal.fsync"):
            self._fsync()
        dt = _time.perf_counter() - t0
        if self.on_stall is not None and dt * 1e3 >= self.stall_ms:
            try:
                self.on_stall(dt)
            except Exception:  # noqa: BLE001 — telemetry only
                pass

    def _finish_sync(self, covered_gen: int) -> None:
        """Advance the covered generation after a group fsync. `_dirty`
        is deliberately NOT touched: a writer may have marked it
        between fsync start and here, and clearing it would let that
        writer's boundary() skip its generation fence (an undurable
        ack). Coverage decisions in commit mode ride the generations;
        `_dirty` only ever clears on flush()/clean(), whose callers
        hold the write path quiescent."""
        import time as _time
        with self._lock:
            if covered_gen > self._sgen:
                self._sgen = covered_gen
            self._last = _time.monotonic()
            self._cv.notify_all()

    def commit_sync(self) -> None:
        """Group-commit rendezvous: return once an fsync that STARTED
        after this caller's last write has completed. One caller (the
        leader) runs the fsync; everyone whose bytes were already in
        the OS buffers when it started is covered for free. An fsync
        failure propagates from the leader; stranded waiters retry as
        the next leader, so nobody returns undurable."""
        if self.policy != "commit":
            return
        with obs.wait("fsync_wait", span_name="wal.group_commit"):
            self._commit_sync()

    def _commit_sync(self) -> None:
        with self._lock:
            if self._dirty:
                # writes not yet fenced by a boundary() (direct
                # SyncPolicy users, or a sibling section's records
                # marked after the last fence): consume + fence them —
                # conservative, but only when unfenced writes exist
                self._dirty = False
                self._wgen += 1
            my = self._wgen
            if self._sgen >= my:
                return  # already covered by a completed fsync
            self._waiters += 1
            try:
                while self._sgen < my and self._sync_active:
                    self._cv.wait()
                if self._sgen >= my:
                    return
                self._sync_active = True
            finally:
                self._waiters -= 1
        # ---- leader path (no locks held) ----
        try:
            wait_s = self.group_max_wait_us / 1e6
            if wait_s > 0:
                with self._lock:
                    gather = self._waiters + 1 < self.group_max_batch
                if gather:
                    import time as _time
                    _time.sleep(wait_s)
            with self._lock:
                start = self._wgen
                batch = self._waiters + 1  # every waiter wrote <= start
            # kill-9 torture site: the batch's bytes are flushed to the
            # OS but NOT fsynced, and none of its commits is acked yet
            from ..util import failpoint
            failpoint.inject("kv/group-fsync")
            self._timed_fsync()
        except BaseException:
            with self._lock:
                self._sync_active = False
                self._cv.notify_all()  # a waiter takes over as leader
            raise
        self._finish_sync(start)
        with self._lock:
            self._sync_active = False
            self._cv.notify_all()
        if self.on_batch is not None:
            try:
                self.on_batch(batch)
            except Exception:  # noqa: BLE001 — telemetry only
                pass

    def clean(self) -> None:
        """The sink was made durable by other means (checkpoint wrote
        and fsynced a snapshot; the WAL restarted empty)."""
        with self._lock:
            self._dirty = False
            self._sgen = self._wgen
            self._cv.notify_all()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            t, self._timer = self._timer, None
        if t is not None:
            t.cancel()


@dataclass
class LockInfo:
    key: bytes
    primary: bytes
    start_ts: int
    op: bytes
    ttl: int


class KeyIsLockedError(KVError):
    def __init__(self, lock: LockInfo) -> None:
        super().__init__(
            f"key {lock.key!r} locked by txn {lock.start_ts}")
        self.lock = lock


class WriteConflictError(KVError):
    def __init__(self, key: bytes, start_ts: int, conflict_ts: int) -> None:
        super().__init__(
            f"write conflict on {key!r}: txn {start_ts} vs commit "
            f"{conflict_ts}")
        self.key = key
        self.start_ts = start_ts
        self.conflict_ts = conflict_ts


class TxnNotFoundError(KVError):
    pass


# ---------------------------------------------------------------------------
# ordered KV substrate (Python reference implementation)
# ---------------------------------------------------------------------------

class PyOrderedKV:
    """Sorted-key in-memory KV with 3 column families. The pure-Python
    twin of the C++ engine (native/kvstore.cpp); identical interface,
    including the WAL + snapshot file format when `path` is given (the
    record layout in kvstore.cpp write_rec), so either engine can reopen
    a directory the other wrote."""

    def __init__(self, path=None, shared: bool = False,
                 sync_log: str = "off",
                 sync_interval_ms: int = 100) -> None:
        self._maps: list[dict[bytes, bytes]] = [{}, {}, {}]
        self._keys: list[list[bytes]] = [[], [], []]
        self._dir = None
        self._wal = None
        self._shared = shared
        self._applied_off = 0
        # bumped whenever checkpoint() rotates (truncates) the WAL —
        # the closed-ts protocol brackets its lock-free size stats on
        # it (shared-mode engines never rotate, so a socket leader's
        # generation is constant; the counter future-proofs any
        # rotation path)
        self.wal_generation = 0
        # durability policy (storage.sync-log): 'off' flushes to the OS
        # only (a machine crash can lose acked commits), 'commit' fsyncs
        # at every commit boundary, 'interval' group-commits — at most
        # one fsync per sync_interval_ms, amortized over the commits
        # that landed inside the window (reference: TiKV raftstore
        # sync-log / raft-store.store-io-pool batching)
        self.sync_log = sync_log
        self.sync_interval_ms = sync_interval_ms
        self._syncer = SyncPolicy(sync_log, sync_interval_ms,
                                  self._fsync_wal)
        # cross-commit group fsync: single-process stores defer the
        # commit-boundary fsync out of the mutation section (the commit
        # path rendezvous in commit_sync after dropping its locks).
        # Shared-dir stores keep the in-section fsync: the flock
        # contract is durability BEFORE visibility to sibling processes,
        # and the flock serializes committers anyway.
        self._syncer.defer_commit = not shared
        # records applied by refresh() that the Storage layer has not yet
        # folded into columnar epochs / catalog (shared mode only)
        self.pending_refresh: list[tuple[int, int, bytes, bytes]] = []
        if path is not None:
            import os

            os.makedirs(path, exist_ok=True)
            self._dir = str(path)
            self._replay(os.path.join(self._dir, "snapshot.kv"))
            wal_path = os.path.join(self._dir, "wal.log")
            valid = self._replay(wal_path)
            if valid >= 0 and not shared:
                # drop a torn tail (crash mid-append): appending after the
                # garbage would hide every later record from the next replay
                with open(wal_path, "ab") as f:
                    f.truncate(valid)
            self._applied_off = max(valid, 0)
            self._wal = open(wal_path, "ab")

    # ---- durability --------------------------------------------------------
    def _replay(self, path: str) -> int:
        """Apply valid records; returns the valid-prefix byte length
        (-1 when the file is absent)."""
        try:
            f = open(path, "rb")
        except OSError:
            return -1
        valid = 0
        with f:
            while True:
                hdr = f.read(10)
                if len(hdr) < 10:
                    return valid
                op, cf = hdr[0], hdr[1]
                klen, vlen = struct.unpack_from("<II", hdr, 2)
                if cf >= 3 or op not in (1, 2):
                    return valid  # torn/corrupt tail
                key = f.read(klen)
                val = f.read(vlen)
                if len(key) < klen or len(val) < vlen:
                    return valid
                if op == 1:
                    self._apply_put(cf, key, val)
                else:
                    self._apply_delete(cf, key)
                valid = f.tell()

    def _log(self, op: int, cf: int, key: bytes, value: bytes) -> None:
        if self._wal is not None:
            rec = struct.pack("<BBII", op, cf, len(key),
                              len(value)) + key + value
            from ..util import failpoint
            if failpoint.is_enabled("kv/wal-torn-append"):
                # crash-injection site: half the record reaches the file,
                # then the armed action fires (the torture harness arms
                # exit(N)@K here — a kill-9 mid-append). An inert hit
                # falls through and writes the remainder, keeping the
                # stream whole.
                half = rec[:max(1, len(rec) // 2)]
                self._wal.write(half)
                self._wal.flush()
                failpoint.inject("kv/wal-torn-append")
                self._wal.write(rec[len(half):])
            else:
                self._wal.write(rec)
            self._wal.flush()
            self._syncer.mark_dirty()
            # shared mode: our own appends are already in memory — advance
            # the tail cursor so refresh() skips them. Writes happen only
            # inside the coordinator section after refresh(), so the
            # cursor was at EOF when this append started.
            self._applied_off += len(rec)

    def refresh(self) -> int:
        """Apply records other processes appended past our cursor
        (shared mode); applied records are also queued on
        `pending_refresh` for the storage layer's columnar fold. Returns
        the number of records applied."""
        if self._dir is None or not self._shared:
            return 0
        import os

        path = os.path.join(self._dir, "wal.log")
        try:
            size = os.path.getsize(path)
        except OSError:
            return 0
        if size <= self._applied_off:
            return 0
        n = 0
        with open(path, "rb") as f:
            f.seek(self._applied_off)
            while True:
                hdr = f.read(10)
                if len(hdr) < 10:
                    break
                op, cf = hdr[0], hdr[1]
                klen, vlen = struct.unpack_from("<II", hdr, 2)
                if cf >= 3 or op not in (1, 2):
                    break  # torn tail; tail_clean truncates under flock
                key = f.read(klen)
                val = f.read(vlen)
                if len(key) < klen or len(val) < vlen:
                    break
                if op == 1:
                    self._apply_put(cf, key, val)
                else:
                    self._apply_delete(cf, key)
                self.pending_refresh.append((op, cf, key, val))
                self._applied_off = f.tell()
                n += 1
        return n

    def tail_clean(self) -> None:
        """Truncate a torn tail left by a writer that crashed mid-append.
        Callers must hold the coordinator flock (nobody else can be
        appending) and have refresh()ed to the valid prefix."""
        if self._dir is None or not self._shared:
            return
        import os

        path = os.path.join(self._dir, "wal.log")
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        if size > self._applied_off:
            with open(path, "r+b") as f:
                f.truncate(self._applied_off)

    def checkpoint(self) -> None:
        if self._dir is None or self._wal is None:
            return
        if self._shared:
            # snapshot+truncate would destroy sibling processes' WAL
            # cursors and any records we have not refreshed yet; shared
            # dirs compact only via a dedicated offline pass
            return
        import os

        tmp = os.path.join(self._dir, "snapshot.tmp")
        with open(tmp, "wb") as f:
            for cf in range(3):
                for k in self._keys[cf]:
                    v = self._maps[cf][k]
                    f.write(struct.pack("<BBII", 1, cf, len(k), len(v))
                            + k + v)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, "snapshot.kv"))
        # the rename must be durable BEFORE the WAL truncates: a crash
        # between the two otherwise leaves the old snapshot + an empty
        # WAL — every record folded into the new snapshot gone
        fsync_dir(self._dir)
        self._wal.close()
        self._wal = open(os.path.join(self._dir, "wal.log"), "wb")
        # rotation epoch: readers pairing (wal size, other state) —
        # rpc/server closed_info — bracket on this to detect a
        # truncate+regrow race that a size comparison alone cannot
        # (same inode, size may already exceed the pre-rotation stat)
        self.wal_generation += 1
        self._syncer.clean()  # the fsync'd snapshot covers everything

    def _fsync_wal(self) -> None:
        import os
        wal = self._wal
        if wal is None:
            return
        try:
            wal.flush()
            os.fsync(wal.fileno())
        except ValueError:
            # the group fsync runs outside the engine locks, so a
            # concurrent checkpoint can rotate (close+reopen) the WAL
            # under us: its snapshot was written AND fsynced before the
            # rotation, so every record this fsync meant to cover is
            # already durable — closed-file here is success, not error
            return

    def sync(self) -> None:
        if self._wal is not None:
            self._syncer.flush()

    def maybe_sync(self) -> None:
        """Commit-boundary durability hook (called at every mutation
        section exit): fsync per the sync-log policy. 'interval' mode is
        the group commit — commits inside the window share one fsync,
        and the tail burst is covered by SyncPolicy's deferred flush.
        'commit' mode with defer_commit leaves durability to the commit
        path's commit_sync() rendezvous (cross-commit group fsync)."""
        if self._wal is not None:
            self._syncer.boundary()

    def commit_sync(self) -> None:
        """Commit-ack durability: group-fsync rendezvous covering every
        byte this committer wrote (no-op unless sync-log=commit)."""
        if self._wal is not None:
            self._syncer.commit_sync()

    def close(self) -> None:
        self._syncer.close()
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    # ---- mutations ---------------------------------------------------------
    def _apply_put(self, cf: int, key: bytes, value: bytes) -> None:
        m = self._maps[cf]
        if key not in m:
            bisect.insort(self._keys[cf], key)
        m[key] = value

    def _apply_delete(self, cf: int, key: bytes) -> None:
        m = self._maps[cf]
        if key in m:
            del m[key]
            ks = self._keys[cf]
            i = bisect.bisect_left(ks, key)
            if i < len(ks) and ks[i] == key:
                ks.pop(i)

    def put(self, cf: int, key: bytes, value: bytes) -> None:
        self._log(1, cf, key, value)
        self._apply_put(cf, key, value)

    def delete(self, cf: int, key: bytes) -> None:
        self._log(2, cf, key, b"")
        self._apply_delete(cf, key)

    def get(self, cf: int, key: bytes) -> Optional[bytes]:
        return self._maps[cf].get(key)

    def scan(self, cf: int, start: bytes, end: bytes,
             limit: int = -1) -> Iterator[tuple[bytes, bytes]]:
        ks = self._keys[cf]
        m = self._maps[cf]
        i = bisect.bisect_left(ks, start)
        n = 0
        while i < len(ks) and (not end or ks[i] < end):
            if limit >= 0 and n >= limit:
                return
            yield ks[i], m[ks[i]]
            n += 1
            i += 1

    def seek_prev(self, cf: int, key: bytes) -> Optional[tuple[bytes, bytes]]:
        """Greatest entry with k <= key (for newest-version lookups)."""
        ks = self._keys[cf]
        i = bisect.bisect_right(ks, key)
        if i == 0:
            return None
        k = ks[i - 1]
        return k, self._maps[cf][k]


# ---------------------------------------------------------------------------
# record encodings
# ---------------------------------------------------------------------------

def _lock_enc(l: LockInfo) -> bytes:
    return (struct.pack("<QQ", l.start_ts, l.ttl) + l.op
            + struct.pack("<I", len(l.primary)) + l.primary)


def _lock_dec(key: bytes, b: bytes) -> LockInfo:
    start_ts, ttl = struct.unpack_from("<QQ", b, 0)
    op = b[16:17]
    (plen,) = struct.unpack_from("<I", b, 17)
    return LockInfo(key, b[21:21 + plen], start_ts, op, ttl)


def _write_enc(start_ts: int, kind: bytes) -> bytes:
    return struct.pack("<Q", start_ts) + kind


def _write_dec(b: bytes) -> tuple[int, bytes]:
    return struct.unpack_from("<Q", b, 0)[0], b[8:9]


def _wkey(key: bytes, commit_ts: int) -> bytes:
    return key + b"\x00" + encode_uint_desc(commit_ts)


def _dkey(key: bytes, start_ts: int) -> bytes:
    return key + b"\x00" + encode_uint_desc(start_ts)


def _split_vkey(vkey: bytes) -> tuple[bytes, int]:
    from .codec import decode_uint_desc
    return vkey[:-9], decode_uint_desc(vkey[-8:])


# ---------------------------------------------------------------------------
# MVCC store
# ---------------------------------------------------------------------------

@dataclass
class Mutation:
    op: bytes  # OP_PUT / OP_DEL / OP_LOCK
    key: bytes
    value: bytes = b""


class MVCCStore:
    def __init__(self, engine=None, coord=None) -> None:
        self.kv = engine if engine is not None else PyOrderedKV()
        self._mu = lockcheck.rlock("MVCCStore._mu", hot=True)
        # shared-directory coordinator (multi-process deployments): every
        # mutation runs inside its flock with the WAL tail caught up, so
        # percolator lock/write records from sibling processes are always
        # visible to conflict checks (store/coordinator.py)
        self.coord = coord

    def _mutate(self):
        return _MutationSection(self)

    def refresh(self) -> int:
        """Locked WAL catch-up (shared mode): serializes with in-process
        mutators so the tail cursor never moves backwards under a
        concurrent append."""
        with self._mu:
            return self.kv.refresh()

    def drain_pending(self) -> list:
        with self._mu:
            out = self.kv.pending_refresh
            self.kv.pending_refresh = []
            return out

    def commit_sync(self) -> None:
        """Commit-ack durability rendezvous (see SyncPolicy.commit_sync).
        Called by the storage commit path AFTER releasing the commit
        lock, so concurrent committers amortize one fsync. Engines
        without deferred group commit answer trivially."""
        cs = getattr(self.kv, "commit_sync", None)
        if cs is not None:
            cs()

    # ---- reads -------------------------------------------------------------
    def get(self, key: bytes, read_ts: int) -> Optional[bytes]:
        with self._mu:
            self._check_lock(key, read_ts)
            return self._read_committed(key, read_ts)

    def batch_get(self, keys: list[bytes],
                  read_ts: int) -> dict[bytes, bytes]:
        out = {}
        for k in keys:
            v = self.get(k, read_ts)
            if v is not None:
                out[k] = v
        return out

    def scan(self, start: bytes, end: bytes, read_ts: int,
             limit: int = -1) -> list[tuple[bytes, bytes]]:
        """Committed (key, value) pairs visible at read_ts, ordered."""
        with self._mu:
            # lock check over the range
            for k, lv in self.kv.scan(CF_LOCK, start, end):
                lock = _lock_dec(k, lv)
                if lock.start_ts <= read_ts and lock.op != OP_LOCK:
                    raise KeyIsLockedError(lock)
            out: list[tuple[bytes, bytes]] = []
            last_key: Optional[bytes] = None
            it_start = _wkey(start, 0xFFFFFFFFFFFFFFFF) if start else b""
            for wk, wv in self.kv.scan(CF_WRITE, it_start,
                                       end if end else b""):
                key, commit_ts = _split_vkey(wk)
                if end and key >= end:
                    break
                if key == last_key or commit_ts > read_ts:
                    continue
                start_ts, kind = _write_dec(wv)
                if kind in (OP_ROLLBACK, OP_LOCK):
                    continue  # markers never settle a key
                last_key = key
                if kind == OP_PUT:
                    data = self.kv.get(CF_DATA, _dkey(key, start_ts))
                    if data is not None:
                        out.append((key, data))
                        if limit >= 0 and len(out) >= limit:
                            break
            return out

    def _check_lock(self, key: bytes, read_ts: int) -> None:
        lv = self.kv.get(CF_LOCK, key)
        if lv is not None:
            lock = _lock_dec(key, lv)
            if lock.start_ts <= read_ts and lock.op != OP_LOCK:
                raise KeyIsLockedError(lock)

    def _read_committed(self, key: bytes, read_ts: int) -> Optional[bytes]:
        probe = _wkey(key, read_ts)
        ent = None
        for wk, wv in self.kv.scan(CF_WRITE, probe, key + b"\x01"):
            k, commit_ts = _split_vkey(wk)
            if k != key:
                return None
            start_ts, kind = _write_dec(wv)
            if kind == OP_ROLLBACK or kind == OP_LOCK:
                continue
            if kind == OP_DEL:
                return None
            return self.kv.get(CF_DATA, _dkey(key, start_ts))
        return None

    # ---- percolator writes -------------------------------------------------
    def prewrite(self, mutations: list[Mutation], primary: bytes,
                 start_ts: int, ttl: int = 3000) -> None:
        """First phase (reference: mvcc_leveldb.go Prewrite; tikv
        prewrite.rs). All-or-nothing per call under the store mutex."""
        with self._mutate():
            errs: list[KVError] = []
            for m in mutations:
                e = self._prewrite_check(m.key, start_ts)
                if e is not None:
                    errs.append(e)
            if errs:
                raise errs[0]
            # wal.append: lock/data records hitting the engine (+WAL)
            # — a child span under a traced range_prewrite
            with obs.span("wal.append"):
                for m in mutations:
                    self.kv.put(CF_LOCK, m.key, _lock_enc(
                        LockInfo(m.key, primary, start_ts, m.op, ttl)))
                    if m.op == OP_PUT:
                        self.kv.put(CF_DATA, _dkey(m.key, start_ts),
                                    m.value)

    def _prewrite_check(self, key: bytes, start_ts: int) -> Optional[KVError]:
        lv = self.kv.get(CF_LOCK, key)
        if lv is not None:
            lock = _lock_dec(key, lv)
            if lock.start_ts != start_ts:
                return KeyIsLockedError(lock)
            return None  # idempotent re-prewrite
        latest = self._latest_commit(key)
        if latest is not None and latest[0] >= start_ts:
            return WriteConflictError(key, start_ts, latest[0])
        return None

    def _latest_commit(self, key: bytes) -> Optional[tuple[int, int, bytes]]:
        """(commit_ts, start_ts, kind) of the newest write record."""
        for wk, wv in self.kv.scan(CF_WRITE,
                                   _wkey(key, 0xFFFFFFFFFFFFFFFF),
                                   key + b"\x01", limit=1):
            k, commit_ts = _split_vkey(wk)
            if k != key:
                return None
            start_ts, kind = _write_dec(wv)
            return commit_ts, start_ts, kind
        return None

    def commit(self, keys: list[bytes], start_ts: int,
               commit_ts: int) -> None:
        """Second phase (reference: mvcc_leveldb.go Commit)."""
        with self._mutate(), obs.span("wal.append"):
            for key in keys:
                lv = self.kv.get(CF_LOCK, key)
                if lv is None:
                    # lock gone: committed already (idempotent) or rolled back
                    st = self._find_txn_write(key, start_ts)
                    if st is not None and st != OP_ROLLBACK:
                        continue
                    raise TxnNotFoundError(
                        f"txn {start_ts} lock not found on {key!r}")
                lock = _lock_dec(key, lv)
                if lock.start_ts != start_ts:
                    raise TxnNotFoundError(
                        f"txn {start_ts} lock not found on {key!r} "
                        f"(held by {lock.start_ts})")
                self.kv.delete(CF_LOCK, key)
                # lock-only mutations leave a LOCK-kind write record too
                # (reference: TiKV WriteType::Lock): readers skip it, but
                # the prewrite conflict check MUST see it — it is how a
                # second optimistic claim of the same unique-index guard
                # key loses instead of silently double-committing
                self.kv.put(CF_WRITE, _wkey(key, commit_ts),
                            _write_enc(start_ts, lock.op))

    def rollback(self, keys: list[bytes], start_ts: int) -> None:
        """Abort a txn's keys (reference: mvcc_leveldb.go Rollback);
        writes a rollback marker so late prewrites cannot resurrect it."""
        with self._mutate():
            for key in keys:
                lv = self.kv.get(CF_LOCK, key)
                if lv is not None:
                    lock = _lock_dec(key, lv)
                    if lock.start_ts == start_ts:
                        self.kv.delete(CF_LOCK, key)
                        self.kv.delete(CF_DATA, _dkey(key, start_ts))
                st = self._find_txn_write(key, start_ts)
                if st is None:
                    self.kv.put(CF_WRITE, _wkey(key, start_ts),
                                _write_enc(start_ts, OP_ROLLBACK))
                elif st != OP_ROLLBACK:
                    raise KVError(
                        f"cannot rollback committed txn {start_ts}")

    def _find_txn_write(self, key: bytes, start_ts: int) -> Optional[bytes]:
        """kind of the write record this txn left on key, if any."""
        for wk, wv in self.kv.scan(CF_WRITE,
                                   _wkey(key, 0xFFFFFFFFFFFFFFFF),
                                   key + b"\x01"):
            k, _commit_ts = _split_vkey(wk)
            if k != key:
                return None
            st, kind = _write_dec(wv)
            if st == start_ts:
                return kind
        return None

    # ---- pessimistic locks -------------------------------------------------
    def pessimistic_lock(self, keys: list[bytes], primary: bytes,
                         start_ts: int, for_update_ts: int,
                         ttl: int = 20000) -> None:
        """Acquire lock-only (OP_LOCK) locks for a pessimistic txn
        (reference: store/tikv/pessimistic.go actionPessimisticLock;
        TiKV acquire_pessimistic_lock). Readers pass over OP_LOCK locks
        (see _check_lock); writers block on them. All-or-nothing: checks
        every key before writing any lock.

        Raises KeyIsLockedError when another txn holds any key and
        WriteConflictError when a commit newer than for_update_ts exists
        (the caller retries with a fresh for_update_ts)."""
        with self._mutate():
            for key in keys:
                lv = self.kv.get(CF_LOCK, key)
                if lv is not None:
                    lock = _lock_dec(key, lv)
                    if lock.start_ts != start_ts:
                        raise KeyIsLockedError(lock)
                    continue  # ours already (idempotent re-lock)
                latest = self._latest_commit(key)
                if latest is not None and latest[0] > for_update_ts:
                    raise WriteConflictError(key, start_ts, latest[0])
            for key in keys:
                if self.kv.get(CF_LOCK, key) is None:
                    self.kv.put(CF_LOCK, key, _lock_enc(
                        LockInfo(key, primary, start_ts, OP_LOCK, ttl)))

    def txn_heart_beat(self, primary: bytes, start_ts: int,
                       ttl: int) -> bool:
        """Extend the primary lock's TTL (reference: TiKV TxnHeartBeat —
        the ttlManager keepalive for long pessimistic txns). TTL only
        grows; returns False when the lock is gone (resolved/expired)."""
        with self._mutate():
            lv = self.kv.get(CF_LOCK, primary)
            if lv is None:
                return False
            lock = _lock_dec(primary, lv)
            if lock.start_ts != start_ts:
                return False
            if ttl > lock.ttl:
                lock.ttl = ttl
                self.kv.put(CF_LOCK, primary, _lock_enc(lock))
            return True

    def pessimistic_rollback(self, keys: list[bytes],
                             start_ts: int) -> None:
        """Release this txn's lock-only locks without leaving a rollback
        marker (reference: TiKV PessimisticRollback — the txn may still
        commit later; only the guards are dropped)."""
        with self._mutate():
            for key in keys:
                lv = self.kv.get(CF_LOCK, key)
                if lv is not None:
                    lock = _lock_dec(key, lv)
                    if lock.start_ts == start_ts and lock.op == OP_LOCK:
                        self.kv.delete(CF_LOCK, key)

    # ---- lock resolution ---------------------------------------------------
    def check_txn_status(self, primary: bytes, lock_ts: int,
                         current_ts: int) -> tuple[int, bool]:
        """(commit_ts, lock_expired): commit_ts>0 means committed;
        0 + expired means safe to roll back (reference:
        lock_resolver.go getTxnStatus)."""
        with self._mutate():
            lv = self.kv.get(CF_LOCK, primary)
            if lv is not None:
                lock = _lock_dec(primary, lv)
                if lock.start_ts == lock_ts:
                    expired = current_ts - lock_ts > (lock.ttl << 18)
                    if expired:
                        self.rollback([primary], lock_ts)
                        return 0, True
                    return 0, False
            kind = self._find_txn_write(primary, lock_ts)
            if kind == OP_ROLLBACK or kind is None:
                # already rolled back, or vanished: mark rollback
                self.rollback([primary], lock_ts)
                return 0, True
            # committed: find its commit_ts
            for wk, wv in self.kv.scan(CF_WRITE,
                                       _wkey(primary, 0xFFFFFFFFFFFFFFFF),
                                       primary + b"\x01"):
                k, commit_ts = _split_vkey(wk)
                if k != primary:
                    break
                st, kd = _write_dec(wv)
                if st == lock_ts and kd != OP_ROLLBACK:
                    return commit_ts, True
            raise TxnNotFoundError(f"txn {lock_ts} status unknown")

    def resolve_lock(self, key: bytes, start_ts: int,
                     commit_ts: int) -> None:
        """Roll a secondary forward (commit_ts>0) or back (reference:
        lock_resolver.go resolveLock)."""
        if commit_ts > 0:
            self.commit([key], start_ts, commit_ts)
        else:
            self.rollback([key], start_ts)

    # ---- recovery ----------------------------------------------------------
    def scan_latest(
        self, start: bytes, end: bytes
    ) -> list[tuple[bytes, int, bytes, Optional[bytes]]]:
        """Newest settled version per key in [start, end):
        (key, commit_ts, kind, value|None). Rollback/lock markers are
        skipped. Restart recovery uses this to re-fold committed rows into
        column epochs (reference analog: bootstrap reads schema + rows
        straight from the KV truth, session/session.go:2090)."""
        with self._mu:
            out: list[tuple[bytes, int, bytes, Optional[bytes]]] = []
            last_key: Optional[bytes] = None
            it_start = _wkey(start, 0xFFFFFFFFFFFFFFFF) if start else b""
            for wk, wv in self.kv.scan(CF_WRITE, it_start,
                                       end if end else b""):
                key, commit_ts = _split_vkey(wk)
                if end and key >= end:
                    break
                if key == last_key:
                    continue
                start_ts, kind = _write_dec(wv)
                if kind in (OP_ROLLBACK, OP_LOCK):
                    continue
                last_key = key
                val = self.kv.get(CF_DATA, _dkey(key, start_ts)) \
                    if kind == OP_PUT else None
                out.append((key, commit_ts, kind, val))
            return out

    def max_commit_ts(self) -> int:
        """Largest commit_ts in the write column (recovery TSO floor)."""
        with self._mu:
            best = 0
            for wk, _ in self.kv.scan(CF_WRITE, b"", b""):
                _, commit_ts = _split_vkey(wk)
                if commit_ts > best:
                    best = commit_ts
            return best

    def all_locks(self) -> list[LockInfo]:
        with self._mu:
            return [_lock_dec(k, v)
                    for k, v in self.kv.scan(CF_LOCK, b"", b"")]

    def checkpoint(self) -> None:
        cp = getattr(self.kv, "checkpoint", None)
        if cp is not None:
            with self._mu:
                cp()

    def unsafe_destroy_range(self, start: bytes, end: bytes) -> None:
        """Physically remove every version, lock and value in [start, end)
        bypassing MVCC (reference: TiKV UnsafeDestroyRange — the DROP/
        TRUNCATE TABLE data reclaim path). Callers guarantee no reader
        needs the range again."""
        with self._mutate():
            for cf in (CF_LOCK, CF_WRITE, CF_DATA):
                doomed = [k for k, _ in self.kv.scan(cf, start, end)]
                # versioned CFs suffix keys with \x00+ts — the plain range
                # end bound still covers them (suffix sorts below end)
                for k in doomed:
                    self.kv.delete(cf, k)

    # ---- range splits (rpc/ranged.py split protocol) ------------------------
    @staticmethod
    def _user_key(cf: int, raw: bytes) -> bytes:
        """The USER key a raw CF key encodes: lock CF keys are plain,
        data/write CF keys carry the \\x00+ts version suffix. Range
        bounds compare user keys — a raw-bound scan at a split point
        K would misfile versions of any user key that is a strict
        prefix of K (u < K but u+\\x00+ts can sort above K)."""
        return raw if cf == CF_LOCK else _split_vkey(raw)[0]

    def export_range(self, start: bytes,
                     end: bytes) -> list[tuple[int, bytes, bytes]]:
        """Every raw (cf, key, value) whose decoded USER key falls in
        [start, end) — the read half of a range split's WAL partition
        (the child's store is rebuilt from these verbatim: locks,
        write records and values keep their exact encoding, so the
        child replays and resolves orphans identically)."""
        with self._mu:
            out: list[tuple[int, bytes, bytes]] = []
            for cf in (CF_LOCK, CF_WRITE, CF_DATA):
                for k, v in self.kv.scan(cf, b"", b""):
                    u = self._user_key(cf, k)
                    if u >= start and (not end or u < end):
                        out.append((cf, k, v))
            return out

    def discard_range(self, start: bytes, end: bytes) -> int:
        """Physically drop every version, lock and value whose decoded
        USER key falls in [start, end) — the parent-retire half of a
        range split (the child now owns those keys). Differs from
        unsafe_destroy_range by bounding on DECODED keys, which is
        the correct comparison at a split point that some user key
        prefixes. Returns the raw record count removed; idempotent."""
        with self._mutate():
            removed = 0
            for cf in (CF_LOCK, CF_WRITE, CF_DATA):
                doomed = [k for k, _ in self.kv.scan(cf, b"", b"")
                          if (u := self._user_key(cf, k)) >= start
                          and (not end or u < end)]
                for k in doomed:
                    self.kv.delete(cf, k)
                removed += len(doomed)
            return removed

    # ---- GC ----------------------------------------------------------------
    def gc(self, safepoint: int) -> int:
        """Drop versions not visible at/after safepoint (reference:
        gcworker/gc_worker.go DoGC). Returns removed version count."""
        with self._mutate():
            removed = 0
            drop_w: list[bytes] = []
            drop_d: list[bytes] = []
            last_key: Optional[bytes] = None
            kept_newest = False
            for wk, wv in self.kv.scan(CF_WRITE, b"", b""):
                key, commit_ts = _split_vkey(wk)
                if key != last_key:
                    last_key = key
                    kept_newest = False
                start_ts, kind = _write_dec(wv)
                if commit_ts >= safepoint:
                    continue
                if kind in (OP_LOCK, OP_ROLLBACK):
                    # markers never settle a key: collect the marker but
                    # keep looking for the newest REAL version — treating
                    # a marker as the kept version would delete the live
                    # PUT beneath it
                    drop_w.append(wk)
                    continue
                if not kept_newest:
                    kept_newest = True
                    if kind == OP_PUT:
                        continue  # newest visible version stays
                    # newest real record below safepoint is DEL: drop it
                drop_w.append(wk)
                if kind == OP_PUT:
                    drop_d.append(_dkey(key, start_ts))
            for wk in drop_w:
                self.kv.delete(CF_WRITE, wk)
                removed += 1
            for dk in drop_d:
                self.kv.delete(CF_DATA, dk)
            return removed


class _MutationSection:
    """Mutation critical section: the coordinator flock (when present)
    plus the in-process mutex, entered with the shared WAL caught up so
    conflict checks see every sibling process's records."""

    __slots__ = ("store", "_coord")

    def __init__(self, store: MVCCStore) -> None:
        self.store = store
        self._coord = None

    def __enter__(self):
        # capture the coordinator ONCE: a leader promotion swaps
        # store.coord mid-flight, and releasing a coordinator this
        # section never acquired would corrupt both coordinators' state
        c = self._coord = self.store.coord
        if c is not None:
            c.acquire()
            self.store.kv.refresh()
            self.store.kv.tail_clean()
        self.store._mu.acquire()
        return self

    def __exit__(self, *exc) -> None:
        # durability BEFORE visibility to siblings: the section's
        # records fsync per the sync-log policy while the flock is
        # still held, so no other process can act on a commit this
        # process could still lose to a crash. (Single-process stores
        # defer the commit-mode fsync to the commit path's group
        # rendezvous instead — maybe_sync no-ops there; the ack still
        # waits on commit_sync.) A FAILED fsync must not
        # strand the locks below — but it must still FAIL the section
        # (re-raised after teardown): acking a commit whose durability
        # call errored would quietly void the sync-log=commit contract.
        c = self._coord
        sync_err: Optional[OSError] = None
        try:
            sync = getattr(self.store.kv, "maybe_sync", None)
            if sync is not None:
                sync()
        except OSError as e:
            sync_err = e
        # coordinator release NEXT, while the engine mutex is still
        # held: a remote coordinator publishes (or reverts) the
        # section's buffered records in release(), and doing that
        # outside the mutex would let a concurrent local reader observe
        # a commit that a fenced flush then reverts
        try:
            if c is not None:
                c.release()
        finally:
            self.store._mu.release()
        if sync_err is not None and exc == (None, None, None):
            # surface only on the success path (never mask the original
            # exception already unwinding through this section)
            raise KVError(
                f"WAL fsync failed at commit boundary: {sync_err}"
            ) from sync_err
