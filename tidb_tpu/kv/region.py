"""Region model: key-range shards with epochs, splits, and a region cache.

Counterpart of the reference's region plumbing (reference:
store/tikv/region_cache.go:274 — LocateKey :538, epoch invalidation;
store/mockstore/mocktikv/cluster.go — Split, the in-process region
topology used by every multi-region test). Regions shard one shared MVCC
store in-process; RegionError surfaces stale routing exactly like TiKV's
epoch-not-match so client retry paths are exercised for real.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from .mvcc import MVCCStore, Mutation


class RegionError(Exception):
    """Stale region routing (epoch mismatch / key out of range) — the
    client must refresh its cache and retry (reference:
    region_request.go:599 onRegionError)."""


@dataclass
class Region:
    id: int
    start_key: bytes
    end_key: bytes  # b"" = +inf
    epoch: int = 1

    def contains(self, key: bytes) -> bool:
        return self.start_key <= key and (not self.end_key
                                          or key < self.end_key)


class RegionManager:
    """Authoritative region table (PD analog) + the per-region request
    gate. All regions serve the same underlying MVCCStore; the gate checks
    routing freshness, which is what distributes correctness."""

    def __init__(self, store: Optional[MVCCStore] = None) -> None:
        self.store = store if store is not None else MVCCStore()
        self._mu = threading.RLock()
        self._next_id = 2
        self._regions: dict[int, Region] = {1: Region(1, b"", b"")}
        # parallel sorted arrays: region start keys + their ids
        self._starts: list[bytes] = [b""]
        self._ids: list[int] = [1]

    # ---- PD-side API -------------------------------------------------------
    def locate(self, key: bytes) -> Region:
        with self._mu:
            i = bisect.bisect_right(self._starts, key) - 1
            r = self._regions[self._ids[i]]
            assert r.contains(key), (key, r)
            return Region(r.id, r.start_key, r.end_key, r.epoch)

    def split(self, split_key: bytes) -> tuple[Region, Region]:
        """Split the region containing split_key (reference:
        cluster.go Split; tikv split_region.go)."""
        with self._mu:
            old = self._region_for(split_key)
            if old.start_key == split_key:
                right = self._regions[old.id]
                return Region(right.id, right.start_key, right.end_key,
                              right.epoch), \
                    Region(right.id, right.start_key, right.end_key,
                           right.epoch)
            new_id = self._next_id
            self._next_id += 1
            right = Region(new_id, split_key, old.end_key)
            old.end_key = split_key
            old.epoch += 1
            self._regions[new_id] = right
            i = bisect.bisect_left(self._starts, split_key)
            self._starts.insert(i, split_key)
            self._ids.insert(i, new_id)
            return (Region(old.id, old.start_key, old.end_key, old.epoch),
                    Region(right.id, right.start_key, right.end_key,
                           right.epoch))

    def regions(self) -> list[Region]:
        with self._mu:
            return [Region(r.id, r.start_key, r.end_key, r.epoch)
                    for rid in self._ids
                    for r in (self._regions[rid],)]

    def _region_for(self, key: bytes) -> Region:
        i = bisect.bisect_right(self._starts, key) - 1
        return self._regions[self._ids[i]]

    # ---- store-side request gate ------------------------------------------
    def check_context(self, region_id: int, epoch: int,
                      keys: list[bytes]) -> None:
        with self._mu:
            r = self._regions.get(region_id)
            if r is None or r.epoch != epoch:
                raise RegionError(f"epoch not match for region {region_id}")
            for k in keys:
                if not r.contains(k):
                    raise RegionError(
                        f"key {k!r} not in region {region_id}")

    # ---- region-checked MVCC ops (the kv.Client surface) ------------------
    def prewrite(self, region: Region, mutations: list[Mutation],
                 primary: bytes, start_ts: int, ttl: int = 3000) -> None:
        self.check_context(region.id, region.epoch,
                           [m.key for m in mutations])
        self.store.prewrite(mutations, primary, start_ts, ttl)

    def commit(self, region: Region, keys: list[bytes], start_ts: int,
               commit_ts: int) -> None:
        self.check_context(region.id, region.epoch, keys)
        self.store.commit(keys, start_ts, commit_ts)

    def rollback(self, region: Region, keys: list[bytes],
                 start_ts: int) -> None:
        self.check_context(region.id, region.epoch, keys)
        self.store.rollback(keys, start_ts)

    def get(self, region: Region, key: bytes, read_ts: int):
        self.check_context(region.id, region.epoch, [key])
        return self.store.get(key, read_ts)

    # ---- resolver/read surface (no region gate: these route BY key) -------
    # The committer and LockResolver call these on whatever rm they were
    # built over; kv/rangeclient.py's RangeRouter implements the same
    # three names over cross-process RPC, which is what lets ONE
    # committer run against either tier.
    def check_txn_status(self, primary: bytes, lock_ts: int,
                         current_ts: int) -> tuple[int, bool]:
        return self.store.check_txn_status(primary, lock_ts, current_ts)

    def resolve_lock(self, key: bytes, start_ts: int,
                     commit_ts: int) -> None:
        self.store.resolve_lock(key, start_ts, commit_ts)

    def scan(self, start: bytes, end: bytes, read_ts: int,
             limit: int = -1) -> list[tuple[bytes, bytes]]:
        return self.store.scan(start, end, read_ts, limit)


def group_by_region(rm: RegionManager,
                    keys: list[bytes]) -> dict[int, tuple[Region, list]]:
    """Split keys into per-region groups (reference: 2pc.go:616
    groupMutations / coprocessor.go:248 buildCopTasks)."""
    groups: dict[int, tuple[Region, list]] = {}
    for k in keys:
        r = rm.locate(k)
        if r.id not in groups:
            groups[r.id] = (r, [])
        groups[r.id][1].append(k)
    return groups
