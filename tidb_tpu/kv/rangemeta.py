"""Range descriptors: the keyspace split into leader-sharded ranges.

A RangeSpec is the unit of write leadership: a half-open key interval
[start_key, end_key) with a routing-table epoch. The range TABLE (the
ordered list of specs) is the cluster's authoritative metadata — the
PD region-table analog (reference: store/tikv/region_cache.go:274
keeps the client copy; pd owns the truth). rpc/ranged.py persists it
as `ranges/meta.json` under the shared durable root and bumps a
range's epoch whenever its metadata or leadership generation changes;
clients carrying an older epoch are answered with EpochNotMatchError
and reload.

Key routing is plain byte comparison on the encoded KV keys — the same
keys kv/region.py routes in-process — so one committer can run against
either tier.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass


@dataclass
class RangeSpec:
    id: int
    start_key: bytes
    end_key: bytes  # b"" = +inf
    epoch: int = 1

    def contains(self, key: bytes) -> bool:
        return self.start_key <= key and (not self.end_key
                                          or key < self.end_key)

    def to_wire(self) -> dict:
        return {"id": int(self.id), "start": self.start_key,
                "end": self.end_key, "epoch": int(self.epoch)}

    @staticmethod
    def from_wire(d: dict) -> "RangeSpec":
        return RangeSpec(int(d["id"]), bytes(d["start"]),
                         bytes(d["end"]), int(d.get("epoch", 1)))


def split_keyspace(count: int = 1,
                   split_points: list = ()) -> list[RangeSpec]:
    """The initial range table from the [ranges] knobs. Explicit split
    points (strings, encoded utf-8, or bytes) win; otherwise `count`
    ranges split the single-byte prefix space evenly — coarse on
    purpose: table-prefixed keys (catalog/codec) hash across prefixes,
    and real split points come from the knob when a workload needs
    them. Always covers the whole keyspace ([b'', +inf))."""
    points: list[bytes] = []
    for p in split_points:
        b = p.encode("utf-8") if isinstance(p, str) else bytes(p)
        if b:
            points.append(b)
    if not points and count > 1:
        count = min(int(count), 256)
        step = 256 // count
        points = [bytes([min(i * step, 255)])
                  for i in range(1, count)]
    points = sorted(set(points))
    bounds = [b""] + points + [b""]
    return [RangeSpec(i + 1, bounds[i], bounds[i + 1])
            for i in range(len(bounds) - 1)]


def locate_spec(specs: list[RangeSpec], key: bytes) -> RangeSpec:
    """The spec containing key — specs must be the full sorted table
    (split_keyspace output order)."""
    starts = [s.start_key for s in specs]
    i = bisect.bisect_right(starts, key) - 1
    s = specs[i]
    assert s.contains(key), (key, s)
    return s


def split_spec(parent: RangeSpec, split_key: bytes,
               child_id: int) -> tuple[RangeSpec, RangeSpec]:
    """One online split's table delta: the parent keeps its id as the
    LEFT child [start, split_key) (so its metric/heat series never
    turns into a phantom id), a fresh id takes [split_key, end), and
    BOTH carry epoch parent+1 — any request stamped with the parent's
    pre-split epoch is answered EpochNotMatchError and re-routes
    (reference: the region-split epoch bump, region_cache.go:274)."""
    if not (parent.start_key < split_key
            and (not parent.end_key or split_key < parent.end_key)):
        raise ValueError(
            f"split key {split_key!r} not strictly inside "
            f"[{parent.start_key!r}, {parent.end_key!r})")
    if int(child_id) == int(parent.id):
        raise ValueError("child id must differ from the parent's")
    epoch = int(parent.epoch) + 1
    left = RangeSpec(parent.id, parent.start_key, split_key, epoch)
    right = RangeSpec(int(child_id), split_key, parent.end_key, epoch)
    return left, right


def table_gaps(specs: list[RangeSpec]) -> list[str]:
    """Coverage defects in a (sorted) range table: gaps, overlaps, a
    missing -inf/+inf edge, duplicate ids. Empty list = the table
    covers the whole keyspace exactly once — the invariant every
    split must preserve and the chaos suite asserts after a kill."""
    out: list[str] = []
    if not specs:
        return ["empty table"]
    specs = sorted(specs, key=lambda s: s.start_key)
    ids = [s.id for s in specs]
    if len(set(ids)) != len(ids):
        out.append(f"duplicate range ids: {sorted(ids)}")
    if specs[0].start_key != b"":
        out.append(f"keyspace starts at "
                   f"{specs[0].start_key!r}, not -inf")
    if specs[-1].end_key != b"":
        out.append(f"keyspace ends at {specs[-1].end_key!r}, not +inf")
    for a, b in zip(specs, specs[1:]):
        if not a.end_key or a.end_key > b.start_key:
            out.append(f"r{a.id}/r{b.id} overlap at "
                       f"{b.start_key!r}")
        elif a.end_key < b.start_key:
            out.append(f"gap between r{a.id} and r{b.id}: "
                       f"[{a.end_key!r}, {b.start_key!r})")
    return out


__all__ = ["RangeSpec", "split_keyspace", "locate_spec", "split_spec",
           "table_gaps"]
