"""Range router: the client half of range-sharded write leadership.

Duck-types the RegionManager surface kv/twopc.py's committer and
LockResolver run against — locate / prewrite / commit / rollback / get
/ check_txn_status / resolve_lock / scan — but routes every call to the
addressed range's CURRENT leader over the frame RPC tier (reference:
store/tikv/region_cache.go:274 + region_request.go — the client-side
region cache with epoch/leader invalidation in front of every kv RPC).

Routing state is two caches with different lifetimes:

* the range TABLE (bounds + epochs) — reloaded when a server answers
  EpochNotMatchError;
* per-range leader GRANTS (owner address + fencing term) — refreshed
  when a server answers NotLeaderError/StaleTermError or stops
  answering at all.

Both refresh paths run under one typed kv/backoff.py Backoffer, so a
leader kill burns a bounded, observable budget (BO_REGION_MISS for
routing staleness, BO_RPC for dead transports) instead of either
hanging or failing the statement on the first stale read of the world.
Typed KV outcomes (KeyIsLockedError and friends) come back in-band and
re-raise locally — they are the COMMITTER's control flow, not routing
failures, and never consume this budget.

Routing truth comes from the shared durable root when this process can
see it (`root=`), or from any live range server's `range_table` RPC
(`seeds=`) when it cannot.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..rpc.client import RpcClient, RpcOptions
from ..rpc.errors import (EpochNotMatchError, LeaderUnavailable,
                          NotLeaderError, RPCError, StaleLeaseError,
                          StaleTermError)
from ..rpc.frame import RANGE_KEY, make_range_ctx
from ..rpc.ranged import RangeDirectory
from .backoff import BO_REGION_MISS, BO_RPC, Backoffer
from .mvcc import (KeyIsLockedError, KVError, LockInfo, Mutation,
                   TxnNotFoundError, WriteConflictError)
from .rangemeta import RangeSpec, locate_spec
from .region import RegionError


class RangeHandle:
    """What locate() hands the committer: enough to group mutations by
    range and to stamp the request's routing context. Leader identity
    is NOT here on purpose — it is resolved per attempt from the grant
    cache, so a handle never pins a request to a dead owner."""

    __slots__ = ("id", "start_key", "end_key", "epoch")

    def __init__(self, spec: RangeSpec) -> None:
        self.id = spec.id
        self.start_key = spec.start_key
        self.end_key = spec.end_key
        self.epoch = spec.epoch

    def contains(self, key: bytes) -> bool:
        return self.start_key <= key and (not self.end_key
                                          or key < self.end_key)


def _raise_kv(err: dict) -> None:
    kind = err.get("kind")
    if kind == "locked":
        raise KeyIsLockedError(LockInfo(
            bytes(err["key"]), bytes(err["primary"]),
            int(err["start_ts"]), bytes(err["op"]), int(err["ttl"])))
    if kind == "conflict":
        raise WriteConflictError(bytes(err["key"]),
                                 int(err["start_ts"]),
                                 int(err["conflict_ts"]))
    if kind == "txn_not_found":
        raise TxnNotFoundError(err.get("msg", "txn not found"))
    raise KVError(err.get("msg", "kv error"))


class RangeRouter:
    def __init__(self, root: Optional[str] = None, seeds=(),
                 options: Optional[RpcOptions] = None,
                 budget_ms: int = 8000,
                 attempt_budget_ms: int = 400) -> None:
        if root is None and not seeds:
            raise ValueError("RangeRouter needs a shared root or seeds")
        self.directory = RangeDirectory(root) if root else None
        self.seeds = [str(s) for s in seeds]
        self.options = options or RpcOptions()
        # total routing budget per logical call; each ATTEMPT gets a
        # small transport budget so a dead leader is detected in one
        # refused connect, not a full per-call retry budget
        self.budget_ms = int(budget_ms)
        self.attempt_budget_ms = int(attempt_budget_ms)
        self._mu = threading.Lock()
        self._specs: list[RangeSpec] = []
        self._grants: dict[int, dict] = {}
        self._clients: dict[str, RpcClient] = {}
        self._load_table()
        if not self._specs:
            raise RPCError("range table unavailable from "
                           f"root={root!r} seeds={self.seeds}")

    # ---- routing state -----------------------------------------------------
    def _load_table(self) -> None:
        if self.directory is not None:
            specs = self.directory.load_specs()
            if specs:
                with self._mu:
                    self._specs = specs
            return
        for seed in list(self.seeds):
            try:
                r = self._client(seed).call(
                    "range_table", _budget_ms=self.attempt_budget_ms)
            except RPCError:
                continue
            # sorted defensively: locate_spec bisects, and a split
            # inserts the new child mid-table
            specs = sorted((RangeSpec.from_wire(d)
                            for d in r.get("specs", [])),
                           key=lambda s: s.start_key)
            if not specs:
                continue
            grants = {int(k): dict(v)
                      for k, v in (r.get("grants") or {}).items()}
            with self._mu:
                self._specs = specs
                self._grants.update(grants)
            return

    def _grant(self, rid: int) -> Optional[dict]:
        now_ms = time.time() * 1000.0
        with self._mu:
            g = self._grants.get(rid)
        if g and float(g.get("expires_ms", 0)) > now_ms:
            return g
        if self.directory is not None:
            g = self.directory.read_grant(rid)
        else:
            g = None
            self._load_table()
            with self._mu:
                g = self._grants.get(rid)
        if g and float(g.get("expires_ms", 0)) > now_ms:
            with self._mu:
                self._grants[rid] = g
                owner = str(g.get("owner", ""))
                # learned leaders become table sources too — the seed
                # list stays useful after every original seed died
                if owner and not self.directory \
                        and owner not in self.seeds:
                    self.seeds.append(owner)
            return g
        return None

    def _invalidate_grant(self, rid: int) -> None:
        with self._mu:
            self._grants.pop(rid, None)

    def _client(self, addr: str) -> RpcClient:
        with self._mu:
            c = self._clients.get(addr)
            if c is None:
                c = RpcClient(addr, self.options, _heartbeat=False)
                self._clients[addr] = c
        return c

    # ---- the routed call ----------------------------------------------------
    def _call(self, rid: int, epoch: int, method: str, **params):
        bo = Backoffer(budget_ms=self.budget_ms)
        while True:
            g = self._grant(rid)
            if g is None:
                # nobody holds the range yet (mid-failover): wait for
                # the lease race to settle. BackoffExhausted escapes
                # typed when it never does. The ledger types this as
                # lease_wait — blocked on leadership, not on routing.
                bo.sleep(BO_REGION_MISS, wait_state="lease_wait")
                continue
            params[RANGE_KEY] = make_range_ctx(rid, epoch,
                                               int(g.get("term", 0)))
            client = self._client(str(g["owner"]))
            try:
                r = client.call(method,
                                _budget_ms=self.attempt_budget_ms,
                                **params)
            except EpochNotMatchError as e:
                # the range TABLE moved under us: reload it and force
                # the caller to re-locate/re-group (region-retry path)
                self._load_table()
                raise RegionError(str(e)) from e
            except (NotLeaderError, StaleTermError,
                    StaleLeaseError) as e:
                self._invalidate_grant(rid)
                bo.sleep(BO_REGION_MISS, wait_state="lease_wait")
                continue
            except LeaderUnavailable as e:
                self._invalidate_grant(rid)
                bo.sleep(BO_RPC)
                continue
            if not r.get("ok", True):
                _raise_kv(r.get("err_kv") or {})
            return r.get("v")

    # ---- the RegionManager surface ------------------------------------------
    def locate(self, key: bytes) -> RangeHandle:
        with self._mu:
            specs = self._specs
        return RangeHandle(locate_spec(specs, key))

    def regions(self) -> list[RangeHandle]:
        with self._mu:
            return [RangeHandle(s) for s in self._specs]

    def prewrite(self, region: RangeHandle, mutations: list[Mutation],
                 primary: bytes, start_ts: int, ttl: int = 3000) -> None:
        self._call(region.id, region.epoch, "range_prewrite",
                   mutations=[[m.op, m.key, m.value] for m in mutations],
                   primary=primary, start_ts=start_ts, ttl=ttl)

    def commit(self, region: RangeHandle, keys: list[bytes],
               start_ts: int, commit_ts: int,
               done: bool = True) -> None:
        # done=False marks a cross-range participant: the range keeps
        # its pending-commit ledger entry (closed_ts held below
        # commit_ts) until txn_done reports every secondary durable
        self._call(region.id, region.epoch, "range_commit", keys=keys,
                   start_ts=start_ts, commit_ts=commit_ts, done=done)

    def txn_done(self, region: RangeHandle, start_ts: int) -> None:
        """Release one participant range's ledger hold. Best-effort:
        a lost call costs closed-ts latency (the hold TTL), never
        correctness, so routing trouble is swallowed."""
        try:
            self._call(region.id, region.epoch, "range_txn_done",
                       start_ts=start_ts)
        except (RPCError, RegionError, KVError):
            pass

    def rollback(self, region: RangeHandle, keys: list[bytes],
                 start_ts: int) -> None:
        self._call(region.id, region.epoch, "range_rollback", keys=keys,
                   start_ts=start_ts)

    def get(self, region: RangeHandle, key: bytes, read_ts: int):
        return self._call(region.id, region.epoch, "range_get", key=key,
                          read_ts=read_ts)

    def check_txn_status(self, primary: bytes, lock_ts: int,
                         current_ts: int) -> tuple[int, bool]:
        h = self.locate(primary)
        v = self._call(h.id, h.epoch, "range_check_txn_status",
                       primary=primary, lock_ts=lock_ts,
                       current_ts=current_ts)
        return int(v["commit_ts"]), bool(v["expired"])

    def resolve_lock(self, key: bytes, start_ts: int,
                     commit_ts: int) -> None:
        h = self.locate(key)
        self._call(h.id, h.epoch, "range_resolve_lock", key=key,
                   start_ts=start_ts, commit_ts=commit_ts)

    def closed_over(self, start: bytes, end: bytes,
                    refresh: bool = False) -> list[tuple[int, int]]:
        """Per-range published closed timestamps over the key span
        [start, end): [(range_id, closed_ts), ...] in key order. The
        span's COVERED timestamp is the min — a snapshot read at or
        below it is settled on every range it touches. closed_ts 0 =
        no grant/publication visible (counts as uncovered). refresh
        reloads the range table and bypasses the grant cache, so a
        waiting reader observes heartbeat progress AND mid-wait
        splits (a child range it has never routed to still gates)."""
        if refresh:
            self._load_table()
        out: list[tuple[int, int]] = []
        for h in self.regions():
            if end and h.start_key and h.start_key >= end:
                break
            if h.end_key and h.end_key <= start:
                continue
            if self.directory is not None:
                # raw grant read: a published closed_ts is a floor
                # FOREVER (monotonic across transfers), so even an
                # expired grant's value safely covers reads at/below it
                g = self.directory.read_grant(h.id)
            else:
                if refresh:
                    self._invalidate_grant(h.id)
                g = self._grant(h.id)
            out.append((int(h.id),
                        int(g.get("closed_ts", 0)) if g else 0))
        return out

    def scan(self, start: bytes, end: bytes, read_ts: int,
             limit: int = -1) -> list[tuple[bytes, bytes]]:
        out: list[tuple[bytes, bytes]] = []
        for h in self.regions():
            if end and h.start_key and h.start_key >= end:
                break
            if h.end_key and h.end_key <= start:
                continue
            rows = self._call(h.id, h.epoch, "range_scan", start=start,
                              end=end, read_ts=read_ts, limit=limit)
            out.extend((bytes(k), bytes(v)) for k, v in rows)
            if limit >= 0 and len(out) >= limit:
                return out[:limit]
        return out

    def close(self) -> None:
        with self._mu:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()


__all__ = ["RangeRouter", "RangeHandle"]
