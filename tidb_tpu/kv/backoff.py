"""Typed retry backoff (reference: store/tikv/backoff.go).

The reference classifies every retryable condition (BoTxnLock,
BoRegionMiss, boTiKVRPC, ...) with its own base/cap growth and a total
budget per request, and surfaces exhaustion with the accumulated retry
types. The engine's retry sites (pessimistic lock waits, write-conflict
rescans, meta-key retries) use the same structure: a Backoffer carries a
millisecond budget, each sleep is typed, grows exponentially with
equal-jitter, and exhaustion raises with the full retry history so an
operator sees WHY a statement burned its budget instead of a bare
"retries exhausted".
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from .. import obs
from ..errno import ER_TIKV_SERVER_TIMEOUT, CodedError


@dataclass(frozen=True)
class BackoffKind:
    name: str
    base_ms: int
    cap_ms: int


# the taxonomy (reference: backoff.go NewBackoffFn call sites)
BO_TXN_LOCK = BackoffKind("txnLock", 2, 200)          # foreign lock wait
BO_TXN_CONFLICT = BackoffKind("txnConflict", 2, 100)  # write conflict rescan
BO_REGION_MISS = BackoffKind("regionMiss", 2, 40)     # region map stale
BO_META = BackoffKind("metaConflict", 2, 100)         # meta-key CAS retry
BO_MAX_TS = BackoffKind("tsoWait", 1, 20)             # TSO window refill
BO_RPC = BackoffKind("tikvRPC", 10, 400)              # transport retry



class BackoffExhausted(CodedError):
    errno = ER_TIKV_SERVER_TIMEOUT
    sqlstate = "HY000"


@dataclass
class Backoffer:
    """Per-request retry budget (reference: backoff.go Backoffer).

    sleep(kind) blocks for the kind's current backoff (exponential with
    equal-jitter, capped) and charges the shared budget; once spent,
    BackoffExhausted carries the typed history. Every sleep reports
    (kind, ms) to the tidb_backoff_seconds histogram and the active
    statement's wait ledger — never a silent time.sleep. A caller that
    knows the wait's higher-level meaning passes wait_state (the range
    router types its grant-settle sleeps as lease_wait)."""

    budget_ms: int
    total_ms: float = 0.0
    attempts: dict = field(default_factory=dict)

    def sleep(self, kind: BackoffKind, wait_state: str = "") -> None:
        n = self.attempts.get(kind.name, 0)
        self.attempts[kind.name] = n + 1
        raw = min(kind.base_ms * (2 ** n), kind.cap_ms)
        ms = raw / 2 + random.uniform(0, raw / 2)  # equal jitter
        if self.total_ms + ms > self.budget_ms:
            hist = ", ".join(f"{k}x{v}"
                             for k, v in sorted(self.attempts.items()))
            raise BackoffExhausted(
                f"backoff budget exhausted after {self.total_ms:.0f}ms "
                f"(budget {self.budget_ms}ms): {hist}")
        self.total_ms += ms
        time.sleep(ms / 1000.0)
        s = ms / 1000.0
        obs.BACKOFF_SECONDS.observe(s, kind=kind.name)
        obs.BACKOFF_EVENTS.inc(kind=kind.name)
        obs.note_wait(wait_state or f"backoff.{kind.name}", s)

    def charge(self, kind: BackoffKind, waited_s: float) -> None:
        """Account an externally-performed wait (e.g. a condition-var
        lock wait) against the budget without sleeping again."""
        self.attempts[kind.name] = self.attempts.get(kind.name, 0) + 1
        self.total_ms += waited_s * 1000.0
        if self.total_ms > self.budget_ms:
            hist = ", ".join(f"{k}x{v}"
                             for k, v in sorted(self.attempts.items()))
            raise BackoffExhausted(
                f"backoff budget exhausted after {self.total_ms:.0f}ms "
                f"(budget {self.budget_ms}ms): {hist}")
