"""Range-sharded write leadership: per-range leases over a shared
durable root, serving percolator RPCs with fencing checks.

The tier splits the keyspace into ranges (kv/rangemeta.py) whose write
leadership is held by INDEPENDENTLY-leased leaders — possibly different
processes per range — so durable writes scale past one commit lock /
one WAL and a single crash only stalls the ranges that process led
(reference: the region model — raftstore leaders per region, not per
store; PAPER.md L7). Three pieces:

* RangeDirectory — the filesystem directory service under
  `<root>/ranges/`: the range table (`meta.json`, first writer wins),
  and per range a grant file + fencing-term file + WAL directory.
  Lease acquisition takes an flock only for the read-modify-write of
  the grant; TENURE is the grant's wall-clock expiry, never the flock
  (a SIGKILLed holder's flock vanishes with the process — the grant
  must keep fencing until it times out). Terms bump exactly when
  ownership changes hands, and persist crash-atomically (the
  rpc/server.py write_term idiom), so a deposed leader — or a client
  that last spoke to it — presents a provably stale term forever after.

* RangeLeader — one hosted range: an MVCCStore over the range's own
  WAL directory (sync_log='commit' by default: acked means fsynced),
  replayed on open, plus the range's closed timestamp (min pending
  lock start_ts - 1, else max committed ts — the per-range analog of
  the PR 11 pending-commit ledger).

* RangeServer — a FrameListener answering `range_*` percolator RPCs.
  Every data request carries the client's (range_id, epoch, term)
  context and is gated BEFORE any data access: wrong host answers
  NotLeaderError, an older routing table answers EpochNotMatchError,
  a superseded term answers StaleTermError, and a grant past its
  expiry refuses to serve at all — stale routing can produce a typed
  retry, never a silently wrong result. A lease loop acquires unheld
  ranges (election = the deterministic lease race over the shared
  directory; the WAL replay makes takeover lossless for acked commits)
  and renews held ones.

Loss window (document over deny): leadership fencing is checked at
request entry, not per WAL byte. A leader paused (SIGSTOP) MID-handler
past its lease expiry can still append after a successor opened the
same WAL — the same bounded window the pull-replication tier documents.
Kill-9 (the failure mode the chaos suite drives) has no such window:
a dead process appends nothing.
"""

from __future__ import annotations

import fcntl
import json
import os
import shutil
import threading
import time
from contextlib import contextmanager
from typing import Optional

from .. import obs
from ..analysis import lockcheck
from ..kv.mvcc import (KVError, KeyIsLockedError, MVCCStore, Mutation,
                       PyOrderedKV, TxnNotFoundError, WriteConflictError,
                       fsync_dir)
from ..kv.rangemeta import RangeSpec, split_keyspace, split_spec
from ..util import failpoint
from .errors import (EpochNotMatchError, NotLeaderError, RPCError,
                     StaleLeaseError, StaleTermError, traced_response,
                     wire_error)
from .frame import get_range_ctx, get_trace_ctx
from .server import FrameListener, read_term, write_term


def _now_ms() -> float:
    # wall clock on purpose: grant expiries must compare across
    # processes, which monotonic clocks never do
    return time.time() * 1000.0


def _write_json_atomic(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---- the directory service ---------------------------------------------------
class RangeDirectory:
    """Range table + per-range lease grants under `<root>/ranges/`.

    Layout:
        ranges/meta.json          the range table (id, bounds, epoch)
        ranges/meta.lock          flock serializing table writes
        ranges/r<id>/lease.lock   flock serializing grant writes
        ranges/r<id>/grant.json   {owner, token, term, expires_ms, ...}
        ranges/r<id>/term         persisted fencing term (write_term)
        ranges/r<id>/data/        the range's own WAL directory
        ranges/r<id>/split.json   in-flight split journal (parent side)
    """

    def __init__(self, root: str) -> None:
        self.root = root
        self.dir = os.path.join(root, "ranges")
        os.makedirs(self.dir, exist_ok=True)

    # ---- paths ----
    def _meta_path(self) -> str:
        return os.path.join(self.dir, "meta.json")

    def _range_dir(self, rid: int) -> str:
        return os.path.join(self.dir, f"r{int(rid)}")

    def data_dir(self, rid: int) -> str:
        return os.path.join(self._range_dir(rid), "data")

    def _grant_path(self, rid: int) -> str:
        return os.path.join(self._range_dir(rid), "grant.json")

    def _term_path(self, rid: int) -> str:
        return os.path.join(self._range_dir(rid), "term")

    def split_path(self, rid: int) -> str:
        return os.path.join(self._range_dir(rid), "split.json")

    @contextmanager
    def _flock(self, path: str):
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            os.close(fd)  # releases the flock with the fd

    # ---- the range table ----
    def bootstrap(self, specs: Optional[list] = None) -> list[RangeSpec]:
        """Write the range table if absent (first writer wins — every
        later bootstrapper adopts the existing table regardless of its
        own knobs, so concurrently started servers can never disagree
        about range bounds). Returns the authoritative table."""
        with self._flock(os.path.join(self.dir, "meta.lock")):
            existing = self.load_specs()
            if existing is not None:
                return existing
            specs = list(specs) if specs else split_keyspace(1)
            _write_json_atomic(self._meta_path(), {
                "ranges": [{"id": s.id, "start": s.start_key.hex(),
                            "end": s.end_key.hex(), "epoch": s.epoch}
                           for s in specs]})
            for s in specs:
                os.makedirs(self.data_dir(s.id), exist_ok=True)
            return specs

    def load_specs(self) -> Optional[list[RangeSpec]]:
        doc = _read_json(self._meta_path())
        if not doc:
            return None
        # sorted by start on every read: locate_spec bisects and the
        # router scans in table order — a split inserts mid-table
        return sorted(
            [RangeSpec(int(r["id"]), bytes.fromhex(r["start"]),
                       bytes.fromhex(r["end"]), int(r.get("epoch", 1)))
             for r in doc["ranges"]],
            key=lambda s: s.start_key)

    def bump_epoch(self, rid: int) -> int:
        """Advance one range's routing epoch (the metadata-changed
        signal: clients carrying the old epoch get EpochNotMatchError
        and reload the table). Bounds stay put — live reshapes go
        through begin_split."""
        with self._flock(os.path.join(self.dir, "meta.lock")):
            doc = _read_json(self._meta_path())
            if not doc:
                raise RPCError("range table missing")
            new = 0
            for r in doc["ranges"]:
                if int(r["id"]) == int(rid):
                    r["epoch"] = new = int(r.get("epoch", 1)) + 1
            if not new:
                raise RPCError(f"unknown range {rid}")
            _write_json_atomic(self._meta_path(), doc)
            return new

    # ---- the split journal ----
    def read_split(self, rid: int) -> Optional[dict]:
        """The parent-side split journal, if a split is in flight:
        {parent, child, split (hex), state: pending|ready}."""
        return _read_json(self.split_path(rid))

    def begin_split(self, parent_id: int, split_key: bytes,
                    trigger: str = "manual"
                    ) -> tuple[RangeSpec, RangeSpec]:
        """Crash-atomically commit one split's table delta. Protocol,
        all under the meta flock: (1) journal the intent next to the
        parent's grant (state=pending), (2) rewrite meta.json with the
        two children — the tmp+fsync+rename+dirfsync discipline makes
        that rename THE commit point. A crash between the two leaves a
        pending journal whose child id is absent from the meta: the
        successor's recovery rolls the split BACK deterministically. A
        crash after leaves both, and recovery rolls FORWARD. Returns
        (left, right) — the parent keeps its id as the left child,
        both at epoch parent+1 (in-flight requests stamped with the
        old epoch get EpochNotMatchError and re-route)."""
        split_key = bytes(split_key)
        with self._flock(os.path.join(self.dir, "meta.lock")):
            specs = self.load_specs()
            if not specs:
                raise RPCError("range table missing")
            parent = next((s for s in specs
                           if s.id == int(parent_id)), None)
            if parent is None:
                raise RPCError(f"unknown range {parent_id}")
            if self.read_split(parent.id) is not None:
                raise RPCError(f"range {parent.id} already splitting")
            child_id = max(s.id for s in specs) + 1
            try:
                left, right = split_spec(parent, split_key, child_id)
            except ValueError as e:
                raise RPCError(str(e)) from e
            _write_json_atomic(self.split_path(parent.id), {
                "parent": int(parent.id), "child": int(child_id),
                "split": split_key.hex(), "state": "pending",
                "trigger": str(trigger)})
            try:
                failpoint.inject("range/split-before-meta-commit")
                table = sorted(
                    [s for s in specs if s.id != parent.id]
                    + [left, right], key=lambda s: s.start_key)
                _write_json_atomic(self._meta_path(), {
                    "ranges": [{"id": s.id, "start": s.start_key.hex(),
                                "end": s.end_key.hex(),
                                "epoch": s.epoch} for s in table]})
            except BaseException:
                # the meta never committed: withdraw the intent (the
                # in-process twin of the successor's roll-back)
                try:
                    os.unlink(self.split_path(parent.id))
                except OSError:
                    pass
                raise
            os.makedirs(self.data_dir(child_id), exist_ok=True)
            return left, right

    def mark_split_ready(self, rid: int) -> None:
        """The child's store is complete and durable: from here the
        split only rolls FORWARD (recovery must never rebuild a ready
        child — it may already hold post-split writes)."""
        j = self.read_split(rid)
        if j is not None:
            j["state"] = "ready"
            _write_json_atomic(self.split_path(rid), j)

    def clear_split(self, rid: int) -> None:
        try:
            os.unlink(self.split_path(rid))
            fsync_dir(self._range_dir(rid))
        except OSError:
            pass

    def pending_children(self) -> set[int]:
        """Child range ids whose split journal is still pending — their
        data dirs may be partial, so NOBODY may acquire their lease
        until the parent-side recovery marks them ready."""
        out: set[int] = set()
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            if not n.startswith("r"):
                continue
            j = _read_json(os.path.join(self.dir, n, "split.json"))
            if j and j.get("state") == "pending":
                out.add(int(j.get("child", -1)))
        return out

    # ---- grants ----
    def read_grant(self, rid: int) -> Optional[dict]:
        """Lock-free grant read (atomic rename makes it torn-proof) —
        what routers use to find a range's current leader."""
        return _read_json(self._grant_path(rid))

    def acquire(self, rid: int, owner: str,
                lease_ms: int) -> Optional[dict]:
        """Take the range's lease if it is free, expired, or already
        ours. The token bumps on EVERY grant write (per-tenure fencing
        for renewal); the TERM bumps only when ownership changes hands
        (the cross-process fencing epoch a deposed leader can never
        re-present). Returns the grant, or None while another owner's
        grant is still live."""
        os.makedirs(self._range_dir(rid), exist_ok=True)
        with self._flock(os.path.join(self._range_dir(rid),
                                      "lease.lock")):
            g = _read_json(self._grant_path(rid))
            now = _now_ms()
            if g and g.get("owner") != owner \
                    and float(g.get("expires_ms", 0)) > now:
                return None  # live grant held elsewhere
            prev_owner = g.get("owner", "") if g else ""
            # the term floor survives a torn/corrupt grant file: the
            # dedicated term file is the durable fencing record
            term = max(int(g.get("term", 0)) if g else 0,
                       read_term(self._term_path(rid)))
            if prev_owner != owner:
                term += 1
                write_term(self._term_path(rid), term)
            grant = {"range_id": int(rid), "owner": owner,
                     "token": (int(g.get("token", 0)) if g else 0) + 1,
                     "term": term, "expires_ms": now + int(lease_ms),
                     "prev_owner": prev_owner,
                     # the closed-ts FLOOR a successor inherits: the
                     # predecessor published this value and routed
                     # reads may already have trusted it, so the new
                     # leader's closed_ts must never start below it
                     "closed_ts": int(g.get("closed_ts", 0)) if g
                     else 0}
            _write_json_atomic(self._grant_path(rid), grant)
            return grant

    def renew(self, rid: int, owner: str, token: int,
              lease_ms: int, closed_ts: Optional[int] = None) -> dict:
        """Extend our own grant; StaleLeaseError when the grant is no
        longer ours (another process acquired while our lease was
        expired — the holder must fence itself immediately). The lease
        heartbeat doubles as the closed-ts publication: routers read
        the grant's closed_ts lock-free to compute read coverage, so
        the published value only ever ratchets up."""
        with self._flock(os.path.join(self._range_dir(rid),
                                      "lease.lock")):
            g = _read_json(self._grant_path(rid))
            if not g or g.get("owner") != owner \
                    or int(g.get("token", -1)) != int(token):
                raise StaleLeaseError(
                    f"range {rid} grant is {g and g.get('owner')!r} "
                    f"token {g and g.get('token')}, not {owner!r} "
                    f"token {token}")
            g["expires_ms"] = _now_ms() + int(lease_ms)
            if closed_ts is not None:
                g["closed_ts"] = max(int(g.get("closed_ts", 0)),
                                     int(closed_ts))
            _write_json_atomic(self._grant_path(rid), g)
            return g

    def release(self, rid: int, owner: str, token: int) -> bool:
        """Zero our grant's expiry so a successor can acquire without
        waiting out the lease (graceful shutdown / forced transfer)."""
        with self._flock(os.path.join(self._range_dir(rid),
                                      "lease.lock")):
            g = _read_json(self._grant_path(rid))
            if not g or g.get("owner") != owner \
                    or int(g.get("token", -1)) != int(token):
                return False
            g["expires_ms"] = 0
            _write_json_atomic(self._grant_path(rid), g)
            return True


# ---- one hosted range --------------------------------------------------------
class RangeLeader:
    """A range this process leads: its own durable MVCC store (WAL
    replay on open makes takeover lossless for acked commits) plus the
    lease/fencing state the request gate checks — and the per-range
    pending-commit LEDGER the closed timestamp is computed from (the
    PR 11 `closed_info` slot protocol, scoped to this range's 2PC
    traffic).

    Ledger rules:
      * a prewrite ENTERS an entry pinned at its start_ts;
      * commit with done=True (single-range txn, or the coordinator's
        txn_done already covers it) RETIRES the entry;
      * commit with done=False (a cross-range participant whose
        secondaries are not yet durable everywhere) RE-PINS the entry
        at commit_ts and stamps the wall clock — the closed ts may
        not pass a half-committed transaction on ANY participant;
      * rollback / orphan resolution / a txn_done RPC retires;
      * a commit-pinned entry whose txn_done was lost (coordinator
        death, partition) self-retires after hold_ms — by then the
        locks its unresolved secondaries still hold pin the closed ts
        through the lock union, and resolution retires those.

    The published value is MONOTONIC: max over (ledger ∪ live locks
    → min-1, else newest commit), floored at the grant's closed_ts —
    the predecessor's published value after a leader transfer, the
    parent's after a split handoff. Safety: every published value is
    ≤ the TSO's current reading at publication (a pending entry pins
    below its txn's eventual commit_ts; with none, _max_commit is an
    already-allocated ts), and every future commit_ts allocation is
    strictly above the TSO — so a later prewrite that dips the
    candidate can never invalidate an already-published closed ts."""

    def __init__(self, spec: RangeSpec, grant: dict, data_dir: str,
                 sync_log: str = "commit",
                 hold_ms: int = 3000) -> None:
        self.spec = spec
        self.grant = dict(grant)
        self.store = MVCCStore(PyOrderedKV(data_dir, sync_log=sync_log))
        self._max_commit = self.store.max_commit_ts()
        self.fenced = False
        self.hold_ms = int(hold_ms)
        # ledger entries: start_ts -> [pin_ts, committed_wall_ms or 0]
        # (plain Lock, not hot-declared: every critical section is a
        # dict op; closed_ts() is called off the lease tick while
        # handlers mutate under the leader gate)
        self._ledger_mu = threading.Lock()
        self._pending: dict[int, list] = {}
        # transfer/split floor: never publish below what a predecessor
        # already published (routers may have trusted it)
        self._closed = int(grant.get("closed_ts", 0) or 0)
        # re-derive pending entries from replayed-but-unresolved
        # prewrites in the per-range WAL: a lock that survived replay
        # is a transaction whose fate this leader does not know yet
        for lk in self.store.all_locks():
            self._pending.setdefault(int(lk.start_ts),
                                     [int(lk.start_ts), 0.0])
        # split/serve exclusion: every data handler holds this across
        # its fencing check AND its store op, and split_range holds it
        # exclusively while it bumps the epoch and partitions the
        # store — so a request that passed the gate pre-split can
        # never mutate the parent after the child copy was cut. Plain
        # RLock, deliberately NOT hot-declared: the split does file
        # I/O under it, and handler critical sections already
        # serialize per range on MVCCStore._mu anyway.
        self.gate = threading.RLock()

    @property
    def term(self) -> int:
        return int(self.grant.get("term", 0))

    def note_commit(self, commit_ts: int) -> None:
        if commit_ts > self._max_commit:
            self._max_commit = commit_ts

    # ---- pending-commit ledger ----
    def ledger_enter(self, start_ts: int) -> None:
        with self._ledger_mu:
            self._pending.setdefault(int(start_ts),
                                     [int(start_ts), 0.0])

    def ledger_commit(self, start_ts: int, commit_ts: int,
                      done: bool) -> None:
        with self._ledger_mu:
            if done:
                self._pending.pop(int(start_ts), None)
            else:
                self._pending[int(start_ts)] = [int(commit_ts),
                                                _now_ms()]

    def ledger_retire(self, start_ts: int) -> None:
        with self._ledger_mu:
            self._pending.pop(int(start_ts), None)

    def adopt_handoff(self, floor: int, pending: dict) -> None:
        """Split handoff: inherit the parent's published floor and its
        pending entries before this child's closed_ts may advance.
        Entries for keys the sibling owns are harmless — they only
        delay closing until the coordinator's txn_done/hold expiry."""
        with self._ledger_mu:
            if int(floor) > self._closed:
                self._closed = int(floor)
            for ts, ent in dict(pending).items():
                self._pending.setdefault(int(ts), list(ent))

    def ledger_snapshot(self) -> dict:
        with self._ledger_mu:
            return {ts: list(ent)
                    for ts, ent in self._pending.items()}

    def closed_ts(self) -> int:
        """Everything at or below this ts is settled on this range —
        no routed read at or below it can ever meet an unresolved
        lock or miss a later-arriving commit."""
        now = _now_ms()
        with self._ledger_mu:
            if self._pending:
                # lost-txn_done fallback: a commit-pinned entry past
                # the hold deadline stops pinning (bounded liveness —
                # any still-unresolved secondary lock keeps pinning
                # through the lock union below)
                dead = [ts for ts, (pin, cms) in self._pending.items()
                        if cms and now - cms > self.hold_ms]
                for ts in dead:
                    del self._pending[ts]
            pins = [pin for pin, _cms in self._pending.values()]
        pins.extend(lk.start_ts for lk in self.store.all_locks())
        # an IDLE range still closes forward: every TSO implementation
        # allocates at or above its wall reading (physical<<18), so
        # with the cluster's shared/synced clock no future commit_ts
        # can land at or below (now - margin) — the PR 11 protocol's
        # min(tso.current(), pending-1) with the wall clock standing
        # in for the oracle the range tier doesn't own
        idle = max(self._max_commit,
                   max(0, int(time.time() * 1000) - 5) << 18)
        cand = min(pins) - 1 if pins else idle
        with self._ledger_mu:
            if cand > self._closed:
                self._closed = cand
            return self._closed

    def close(self) -> None:
        close = getattr(self.store.kv, "close", None)
        if close is not None:
            close()


def _kv_guarded(fn) -> dict:
    """Run one store operation and fold its typed KV failures into the
    response envelope — KV errors are RESULTS the committer interprets
    (resolve the lock, retry the conflict), not transport errors, so
    they must not burn the client's retry budget or trip its breaker."""
    try:
        # range.apply: the store mutation/read itself (child spans —
        # wal.append, wal.fsync — open inside the engine) riding back
        # to the coordinator's stitched trace via traced_response
        with obs.span("range.apply"):
            return {"ok": True, "v": fn()}
    except KeyIsLockedError as e:
        lk = e.lock
        return {"ok": False, "err_kv": {
            "kind": "locked", "key": lk.key, "primary": lk.primary,
            "start_ts": lk.start_ts, "op": lk.op, "ttl": lk.ttl}}
    except WriteConflictError as e:
        return {"ok": False, "err_kv": {
            "kind": "conflict", "key": e.key, "start_ts": e.start_ts,
            "conflict_ts": e.conflict_ts}}
    except TxnNotFoundError as e:
        return {"ok": False, "err_kv": {"kind": "txn_not_found",
                                        "msg": str(e)}}
    except KVError as e:
        return {"ok": False, "err_kv": {"kind": "kv", "msg": str(e)}}


# ---- the server ---------------------------------------------------------------
class RangeServer(FrameListener):
    """Per-range write leadership over the frame protocol."""

    _thread_prefix = "titpu-range"

    def __init__(self, root: str, listen: str = "127.0.0.1:0",
                 lease_ms: int = 1000, specs: Optional[list] = None,
                 sync_log: str = "commit", events=None,
                 heat=None, auto_split: bool = False,
                 split_cooldown_ms: int = 10_000,
                 max_auto_splits: int = 4,
                 hold_ms: int = 3000) -> None:
        self.directory = RangeDirectory(root)
        self.specs = self.directory.bootstrap(specs)
        self.lease_ms = int(lease_ms)
        # how long a cross-range commit may hold a range's ledger open
        # waiting for the coordinator's txn_done (mirrors the orphan
        # resolve TTL: past it, resolution owns the cleanup)
        self.hold_ms = int(hold_ms)
        self.events = events
        self._sync_log = str(sync_log)
        # heat-driven auto-split actuator knobs ([ranges] auto-split /
        # split-cooldown-ms / max-auto-splits; all hot-reloadable).
        # Disabled (the default) the lease tick returns before touching
        # the heat plane — the zero-work contract the poison test pins.
        self.auto_split = bool(auto_split)
        self.split_cooldown_ms = int(split_cooldown_ms)
        self.max_auto_splits = int(max_auto_splits)
        self._auto_splits = 0
        self._last_auto_split_ms = 0.0
        # keyspace heat recorder: the LEADER apply is the single
        # counting site for routed writes (the range tier's committers
        # carry no recorder — see kv/twopc.py)
        self.heat = heat
        # guards the hosted-leader map only — every critical section is
        # a dict op (HOT_LOCKS-declared: this sits on the 2PC data path)
        self._mu = lockcheck.lock("RangeServer._mu", hot=True)
        self._leaders: dict[int, RangeLeader] = {}
        self._closed = False
        fam, target = self._start_listener(listen)
        import socket as _socket
        if fam == _socket.AF_INET:
            host = target[0] or "127.0.0.1"
            self.address = f"{host}:{self.port}"
        else:
            self.address = str(listen)
        # one synchronous pass before serving: a just-constructed server
        # already hosts every free range (tests need no settle loop)
        self._lease_tick()
        self._stop = threading.Event()
        self._lease_thread = threading.Thread(
            target=self._lease_loop, name="titpu-range-lease",
            daemon=True)
        self._lease_thread.start()

    # ---- lease plane ----
    def _lease_loop(self) -> None:
        period = max(0.05, self.lease_ms / 3000.0)
        while not self._stop.wait(period):
            try:
                self._lease_tick()
            except Exception as e:  # keep the plane alive
                if self.events is not None:
                    self.events.record("range_lease_error", str(e),
                                       severity="warning")

    def _lease_tick(self) -> None:
        specs = self.directory.load_specs()
        if specs:
            self.specs = specs
        drop = failpoint.inject("range/lease-drop")
        # a child range mid-split (journal pending) has a possibly
        # partial data dir: nobody may serve it until the parent-side
        # recovery (or the splitter itself) marks it ready
        embargoed = self.directory.pending_children()
        for spec in self.specs:
            with self._mu:
                leader = self._leaders.get(spec.id)
            if leader is not None:
                leader.spec = spec  # adopt epoch bumps
                if drop is not None and (
                        drop is True or int(drop) == spec.id):
                    self.directory.release(spec.id, self.address,
                                           leader.grant["token"])
                    self._drop_leader(spec.id, "lease-drop failpoint")
                    continue
                try:
                    # the heartbeat publishes the range's closed ts:
                    # routers read it lock-free off the grant file /
                    # range_table RPC to compute read coverage
                    leader.grant = self.directory.renew(
                        spec.id, self.address, leader.grant["token"],
                        self.lease_ms, closed_ts=leader.closed_ts())
                except (StaleLeaseError, OSError) as e:
                    self._drop_leader(spec.id, f"lease lost: {e}")
            elif spec.id not in embargoed:
                try:
                    g = self.directory.acquire(spec.id, self.address,
                                               self.lease_ms)
                except OSError:
                    g = None
                if g:
                    self._open_leader(spec, g)
        self._recover_splits()
        self._auto_split_tick()

    def _open_leader(self, spec: RangeSpec, grant: dict,
                     floor: int = 0,
                     pending: Optional[dict] = None) -> None:
        leader = RangeLeader(spec, grant,
                             self.directory.data_dir(spec.id),
                             sync_log=self._sync_log,
                             hold_ms=self.hold_ms)
        if floor or pending:
            # split handoff: the parent's published floor + pending
            # ledger land on the child BEFORE it serves (grant floors
            # cover leader TRANSFER; a fresh child has no grant
            # history, so the splitter hands its own down explicitly)
            leader.adopt_handoff(floor, pending or {})
        with self._mu:
            self._leaders[spec.id] = leader
        obs.RANGE_LEADERS.inc()
        # publish immediately: until the first heartbeat lands, the
        # grant would otherwise advertise only the inherited floor
        try:
            leader.grant = self.directory.renew(
                spec.id, self.address, leader.grant["token"],
                self.lease_ms, closed_ts=leader.closed_ts())
        except (StaleLeaseError, OSError):
            pass  # the lease tick will fence or retry
        prev = grant.get("prev_owner", "")
        if prev and prev != self.address:
            obs.RANGE_TRANSFERS.inc()
            if self.events is not None:
                self.events.record(
                    "range_transfer",
                    f"r{spec.id} {prev} -> {self.address} "
                    f"term={grant['term']}", severity="warning")

    def _drop_leader(self, rid: int, why: str) -> None:
        with self._mu:
            leader = self._leaders.pop(rid, None)
        if leader is None:
            return
        leader.fenced = True
        obs.RANGE_LEADERS.dec()
        if self.events is not None:
            self.events.record("range_transfer",
                               f"r{rid} dropped by {self.address}: "
                               f"{why}", severity="warning")
        leader.close()

    # ---- online splits ----
    def split_range(self, rid: int, split_key: bytes,
                    trigger: str = "manual", advised_by: str = ""
                    ) -> tuple[RangeSpec, RangeSpec]:
        """Split one hosted range at split_key, online. Under the
        leader's gate (no data request interleaves): journal + commit
        the two-entry table delta (begin_split's meta rename is THE
        commit point and bumps both children to epoch parent+1), cut
        the child's WAL stream out of the parent's store, mark the
        journal ready, retire the parent's half, clear the journal —
        then lease and serve the child immediately. A kill-9 anywhere
        in that sequence is recovered deterministically by
        _recover_splits on whichever process next leads the parent:
        back before the meta commit, forward after. In-flight 2PC
        stamped with the parent's old epoch gets EpochNotMatchError
        and re-routes through the client's reload loop."""
        rid = int(rid)
        split_key = bytes(split_key)
        with self._mu:
            leader = self._leaders.get(rid)
        if leader is None or leader.fenced:
            raise NotLeaderError(f"range {rid} not led here")
        with leader.gate:
            left, right = self.directory.begin_split(
                rid, split_key, trigger=trigger)
            # table committed: the split now only moves forward (here,
            # or via _recover_splits on a successor)
            leader.spec = left
            failpoint.inject("range/split-after-meta-commit")
            self._materialize_child(leader, right)
            self.directory.mark_split_ready(rid)
            failpoint.inject("range/split-before-parent-retire")
            leader.store.discard_range(split_key, right.end_key)
            self.directory.clear_split(rid)
            # ledger handoff, captured under the gate: BOTH children
            # inherit the parent's published closed floor and pending
            # entries before either side's closed_ts may advance (the
            # left child IS the parent leader and keeps its ledger;
            # the right child receives a copy at adoption)
            handoff_floor = leader.closed_ts()
            handoff_pending = leader.ledger_snapshot()
        self.specs = self.directory.load_specs() or self.specs
        self._note_split(left, right, trigger, advised_by)
        self._adopt_child(right, floor=handoff_floor,
                          pending=handoff_pending)
        return left, right

    def _materialize_child(self, parent: RangeLeader,
                           child: RangeSpec) -> None:
        """Partition the per-range WAL stream: every lock/version whose
        decoded USER key falls in the child's bounds, rewritten into
        the child's own data dir so both sides replay independently.
        Rebuilds from scratch (rmtree first) so a recovery retry over
        a half-written child dir is idempotent; the parent still holds
        every pre-split byte until the retire step, which only ever
        runs after the journal says ready."""
        child_dir = self.directory.data_dir(child.id)
        if os.path.isdir(child_dir):
            shutil.rmtree(child_dir, ignore_errors=True)
        os.makedirs(child_dir, exist_ok=True)
        items = parent.store.export_range(child.start_key,
                                          child.end_key)
        kv = PyOrderedKV(child_dir, sync_log=self._sync_log)
        try:
            mid = max(1, len(items) // 2)
            for i, (cf, k, v) in enumerate(items):
                kv.put(cf, k, v)
                if i + 1 == mid:
                    failpoint.inject("range/split-mid-wal-partition")
            if not items:
                failpoint.inject("range/split-mid-wal-partition")
            kv.sync()
        finally:
            kv.close()

    def _adopt_child(self, child: RangeSpec, floor: int = 0,
                     pending: Optional[dict] = None) -> None:
        """Serve the fresh child now — its lease is free, its journal
        is cleared, and waiting a lease tick would stall writes to the
        upper half of the just-split keyspace."""
        try:
            g = self.directory.acquire(child.id, self.address,
                                       self.lease_ms)
        except OSError:
            g = None
        if g:
            self._open_leader(child, g, floor=floor, pending=pending)

    def _note_split(self, left: RangeSpec, right: RangeSpec,
                    trigger: str, advised_by: str = "") -> None:
        if self.heat is not None:
            # re-key the heat plane: the parent's pre-split cells span
            # bounds no live range has — both children start clean
            self.heat.on_split(left.id, self.specs)
        obs.RANGE_SPLITS.inc(trigger=str(trigger))
        if self.events is not None:
            detail = (f"r{left.id} -> r{left.id}+r{right.id} at "
                      f"{right.start_key.hex()[:24]} "
                      f"epoch={left.epoch} trigger={trigger}")
            if advised_by:
                detail += f" advisory={advised_by}"
            self.events.record("range_split", detail, severity="info")

    def _recover_splits(self) -> None:
        """Finish — or deterministically roll back — any split journal
        a crashed leader left on a range we now lead. Runs every lease
        tick; a journal with no leader here is someone else's to
        recover (whoever wins the parent's lease)."""
        for rid in self.hosted_ids():
            j = self.directory.read_split(rid)
            if j is None:
                continue
            with self._mu:
                leader = self._leaders.get(rid)
            if leader is None or leader.fenced:
                continue
            try:
                self._finish_split(leader, j)
            except Exception as e:
                if self.events is not None:
                    self.events.record(
                        "range_split_error",
                        f"r{rid} split recovery failed: {e}",
                        severity="warning")

    def _finish_split(self, leader: RangeLeader, j: dict) -> None:
        """One journal's recovery. The meta rename decides direction:
        child id absent from the table → the split never committed,
        roll BACK (scrap the partial child dir, withdraw the intent);
        present → roll FORWARD (rebuild the child unless the journal
        already says ready — a ready child may hold post-split writes
        and must NEVER be rebuilt — then retire the parent's half)."""
        rid = int(j["parent"])
        child_id = int(j["child"])
        split_key = bytes.fromhex(j["split"])
        trigger = str(j.get("trigger", "manual"))
        with leader.gate:
            specs = self.directory.load_specs() or []
            by_id = {s.id: s for s in specs}
            if child_id not in by_id:
                shutil.rmtree(self.directory.data_dir(child_id),
                              ignore_errors=True)
                self.directory.clear_split(rid)
                if self.events is not None:
                    self.events.record(
                        "range_split_rollback",
                        f"r{rid} pending split at "
                        f"{split_key.hex()[:24]} rolled back",
                        severity="warning")
                return
            left, right = by_id[rid], by_id[child_id]
            leader.spec = left
            if j.get("state") != "ready":
                self._materialize_child(leader, right)
                self.directory.mark_split_ready(rid)
            failpoint.inject("range/split-before-parent-retire")
            leader.store.discard_range(split_key, right.end_key)
            self.directory.clear_split(rid)
            handoff_floor = leader.closed_ts()
            handoff_pending = leader.ledger_snapshot()
        self.specs = self.directory.load_specs() or self.specs
        self._note_split(left, right, trigger)
        self._adopt_child(right, floor=handoff_floor,
                          pending=handoff_pending)

    def _auto_split_tick(self) -> None:
        """The heat→split actuator: consume PR 18 range-split-advisory
        findings and execute the advised split. Knob-gated and
        rate-limited; disabled (the default) this returns before
        touching the heat plane at all — the zero-work contract the
        poison test pins. At most one split per tick: the cooldown
        paces a salted-key workload instead of shattering it."""
        if not self.auto_split or self.heat is None \
                or not self.heat.enabled:
            return
        if self._auto_splits >= self.max_auto_splits:
            return
        if _now_ms() - self._last_auto_split_ms \
                < self.split_cooldown_ms:
            return
        for f in self.heat.findings():
            if f.get("rule") != "range-split-advisory":
                continue
            item = str(f.get("item", ""))
            try:
                rid = int(item.lstrip("r"))
            except ValueError:
                continue
            with self._mu:
                leader = self._leaders.get(rid)
            if leader is None or leader.fenced:
                continue
            if self.directory.read_split(rid) is not None:
                continue
            # the finding's value is a truncated hex digest for the
            # event board — refetch the full weighted-median key
            key = self.heat.split_advisory(rid)
            spec = leader.spec
            if key is None or not (
                    spec.start_key < key
                    and (not spec.end_key or key < spec.end_key)):
                continue
            try:
                failpoint.inject("range/auto-split")
                self.split_range(
                    rid, key, trigger="auto",
                    advised_by=str(f.get("value", ""))[:48])
            except RPCError as e:
                if self.events is not None:
                    self.events.record(
                        "range_split_error",
                        f"r{rid} auto-split failed: {e}",
                        severity="warning")
                continue
            self._auto_splits += 1
            self._last_auto_split_ms = _now_ms()
            return

    # ---- request gate ----
    @contextmanager
    def _gate(self, params: dict):
        """The fencing gate every data request passes BEFORE any data
        access; raises typed so the client refreshes + retries instead
        of acting on a stale view. Yields the leader WITH its gate
        lock held, so the fencing verdict stays true through the store
        op — a split that lands between the check and the apply would
        otherwise let a pre-split request mutate keys the child now
        owns. Traced as range.lease_gate so a fencing rejection's cost
        is visible in the stitched tree."""
        rc = get_range_ctx(params)
        if rc is None:
            raise RPCError("missing range context")
        rid = int(rc["range_id"])
        with self._mu:
            leader = self._leaders.get(rid)
        if leader is None or leader.fenced:
            g = self.directory.read_grant(rid)
            hint = (f" (grant: {g['owner']} term {g['term']})"
                    if g else "")
            raise NotLeaderError(f"range {rid} not led here{hint}")
        with leader.gate:
            with obs.span("range.lease_gate"):
                self._check_gate(leader, rc, rid)
            yield leader

    def _check_gate(self, leader: RangeLeader, rc: dict,
                    rid: int) -> None:
        if leader.fenced:
            raise NotLeaderError(f"range {rid} not led here")
        if float(leader.grant.get("expires_ms", 0)) <= _now_ms():
            # our own lease ran out and the renew loop hasn't caught it
            # yet — refusing here is what makes the lease a fence
            raise NotLeaderError(f"range {rid} lease expired on "
                                 f"{self.address}")
        if int(rc.get("epoch", 0)) != int(leader.spec.epoch):
            raise EpochNotMatchError(
                f"range {rid} epoch {rc.get('epoch')} != "
                f"{leader.spec.epoch} — reload the range table")
        cterm = int(rc.get("term", 0))
        if cterm < leader.term:
            raise StaleTermError(f"range {rid} request term {cterm} < "
                                 f"current {leader.term}")
        if cterm > leader.term:
            # the CLIENT has seen a newer tenure than ours: we are the
            # deposed one (a renew raced); never serve on a stale term
            raise NotLeaderError(f"range {rid} deposed: request term "
                                 f"{cterm} > local {leader.term}")

    # ---- dispatch ----
    def _dispatch(self, req) -> dict:
        if not isinstance(req, dict) or "m" not in req:
            return wire_error(None, RPCError("bad request"))
        rid = req.get("id")
        method = str(req.get("m"))
        params = req.get("p") if isinstance(req.get("p"), dict) else {}
        handler = getattr(self, f"_h_{method}", None) \
            if method.startswith("range_") else None
        if handler is None:
            return wire_error(rid, RPCError(
                f"unknown range method {method!r}"))
        return traced_response(rid, method, lambda: handler(params),
                               get_trace_ctx(req))

    # ---- percolator handlers ----
    def _h_range_prewrite(self, params: dict) -> dict:
        with self._gate(params) as leader:
            muts = [Mutation(bytes(m[0]), bytes(m[1]), bytes(m[2]))
                    for m in params["mutations"]]
            out = _kv_guarded(lambda: leader.store.prewrite(
                muts, bytes(params["primary"]),
                int(params["start_ts"]),
                int(params.get("ttl", 3000))))
            if out["ok"]:
                # the prewrite enters this range's pending-commit
                # ledger; primary-commit/rollback/txn_done retires it
                leader.ledger_enter(int(params["start_ts"]))
            # the leader-side apply is where a routed write lands on
            # the keyspace heatmap (exactly once: the coordinator's
            # committer carries no recorder over the range tier)
            if out["ok"] and self.heat is not None \
                    and self.heat.enabled:
                self.heat.note_range(
                    leader.spec.id,
                    write_rows=len(muts),
                    write_bytes=sum(len(m.value or b"")
                                    for m in muts),
                    keys=[m.key for m in muts])
        # applied-but-unacked: a kill here is the harshest prewrite
        # crash — the lock is durable, the coordinator never heard back
        failpoint.inject("range/before-prewrite-ack")
        return out

    def _h_range_commit(self, params: dict) -> dict:
        with self._gate(params) as leader:
            commit_ts = int(params["commit_ts"])
            out = _kv_guarded(lambda: leader.store.commit(
                [bytes(k) for k in params["keys"]],
                int(params["start_ts"]), commit_ts))
            if out["ok"]:
                leader.note_commit(commit_ts)
                # done=False: a cross-range participant — the entry
                # stays, re-pinned at commit_ts, until every
                # participant's secondaries are durable and the
                # coordinator's txn_done (or the hold TTL) retires it.
                # Absent flag = single-range traffic: retire now.
                leader.ledger_commit(int(params["start_ts"]),
                                     commit_ts,
                                     bool(params.get("done", True)))
        failpoint.inject("range/before-commit-ack")
        return out

    def _h_range_rollback(self, params: dict) -> dict:
        with self._gate(params) as leader:
            out = _kv_guarded(lambda: leader.store.rollback(
                [bytes(k) for k in params["keys"]],
                int(params["start_ts"])))
            if out["ok"]:
                leader.ledger_retire(int(params["start_ts"]))
            return out

    def _h_range_txn_done(self, params: dict) -> dict:
        """A cross-range transaction's secondaries are durable on every
        participant: release the ledger hold so closed_ts may pass its
        commit_ts. Best-effort by design — a lost txn_done is covered
        by the hold TTL + orphan resolution."""
        with self._gate(params) as leader:
            leader.ledger_retire(int(params["start_ts"]))
            return {"ok": True}

    def _h_range_get(self, params: dict) -> dict:
        with self._gate(params) as leader:
            out = _kv_guarded(lambda: leader.store.get(
                bytes(params["key"]), int(params["read_ts"])))
            if out["ok"] and self.heat is not None \
                    and self.heat.enabled:
                v = out["v"]
                self.heat.note_range(
                    leader.spec.id, read_rows=1,
                    read_bytes=len(v) if v else 0)
            return out

    def _h_range_scan(self, params: dict) -> dict:
        with self._gate(params) as leader:
            spec = leader.spec
            start = max(bytes(params.get("start", b"")),
                        spec.start_key)
            end = bytes(params.get("end", b""))
            if spec.end_key and (not end or end > spec.end_key):
                end = spec.end_key
            out = _kv_guarded(
                lambda: [list(kv) for kv in leader.store.scan(
                    start, end, int(params["read_ts"]),
                    int(params.get("limit", -1)))])
            if out["ok"] and self.heat is not None \
                    and self.heat.enabled:
                rows = out["v"]
                self.heat.note_range(
                    leader.spec.id, read_rows=len(rows),
                    read_bytes=sum(len(kv[1] or b"") for kv in rows))
            return out

    def _h_range_check_txn_status(self, params: dict) -> dict:
        with self._gate(params) as leader:

            def run():
                commit_ts, expired = leader.store.check_txn_status(
                    bytes(params["primary"]), int(params["lock_ts"]),
                    int(params["current_ts"]))
                return {"commit_ts": commit_ts, "expired": expired}

            out = _kv_guarded(run)
            if out["ok"] and (out["v"]["expired"]
                              or out["v"]["commit_ts"]):
                # the transaction's fate is decided (rolled back on
                # expiry / already committed): its ledger entry no
                # longer guards anything the lock union doesn't
                leader.ledger_retire(int(params["lock_ts"]))
            return out

    def _h_range_resolve_lock(self, params: dict) -> dict:
        with self._gate(params) as leader:
            out = _kv_guarded(lambda: leader.store.resolve_lock(
                bytes(params["key"]), int(params["start_ts"]),
                int(params["commit_ts"])))
            if out["ok"]:
                obs.RANGE_ORPHAN_RESOLUTIONS.inc()
                leader.ledger_retire(int(params["start_ts"]))
            return out

    def _h_range_split(self, params: dict) -> dict:
        """Operator-triggered online split (the chaos harness drives
        the in-process protocol through this same door)."""
        left, right = self.split_range(
            int(params["range_id"]), bytes(params["split_key"]),
            trigger=str(params.get("trigger", "manual")))
        return {"parent": left.to_wire(), "child": right.to_wire()}

    # ---- metadata / diagnostics ----
    def _h_range_table(self, params: dict) -> dict:
        """The routing bootstrap for clients without filesystem access
        to the shared root: table + every range's current grant."""
        specs = self.directory.load_specs() or self.specs
        grants = {}
        for s in specs:
            g = self.directory.read_grant(s.id)
            if g:
                grants[int(s.id)] = {"owner": g.get("owner", ""),
                                     "term": int(g.get("term", 0)),
                                     "expires_ms":
                                         float(g.get("expires_ms", 0)),
                                     "closed_ts":
                                         int(g.get("closed_ts", 0))}
        return {"specs": [s.to_wire() for s in specs],
                "grants": grants}

    def _h_range_info(self, params: dict) -> dict:
        return {"ranges": self.describe()}

    def describe(self) -> list[dict]:
        """Hosted ranges, one row each — what /status and cluster_info
        render."""
        with self._mu:
            leaders = sorted(self._leaders.items())
        out = []
        for rid, leader in leaders:
            rr, rb, wr, wb = self.heat.range_totals(rid) \
                if self.heat is not None else (0, 0, 0, 0)
            out.append({"range_id": rid, "leader": self.address,
                        "term": leader.term,
                        "epoch": leader.spec.epoch,
                        "token": int(leader.grant.get("token", 0)),
                        "closed_ts": leader.closed_ts(),
                        # commit progress independent of the heat
                        # plane: the closed-ts-stall rule compares it
                        # against a static closed_ts
                        "max_commit_ts": int(leader._max_commit),
                        "pending": len(leader._pending),
                        "start": leader.spec.start_key.hex(),
                        "end": leader.spec.end_key.hex(),
                        "read_rows": rr, "read_bytes": rb,
                        "write_rows": wr, "write_bytes": wb})
        return out

    def hosted_ids(self) -> list[int]:
        with self._mu:
            return sorted(self._leaders)

    def close(self, release: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._lease_thread.join(timeout=5.0)
        self._close_listener()
        with self._mu:
            leaders = dict(self._leaders)
            self._leaders.clear()
        for rid, leader in leaders.items():
            leader.fenced = True
            if release:
                try:
                    self.directory.release(rid, self.address,
                                           leader.grant["token"])
                except OSError:
                    pass
            obs.RANGE_LEADERS.dec()
            leader.close()


class RangePlane:
    """The [ranges]-armed subsystem one Storage owns: a RangeServer
    rooted under the storage path plus the knobs mirror. Entirely OFF
    the statement path — arming starts a listener and a lease loop;
    statements never consult it, which is what makes `[ranges]`
    disabled byte-identical to the pre-range engine."""

    def __init__(self, storage, count: int = 1, split_points=(),
                 lease_ms: int = 1000, resolve_ttl_ms: int = 3000,
                 listen: str = "127.0.0.1:0", auto_split: bool = False,
                 split_cooldown_ms: int = 10_000,
                 max_auto_splits: int = 4) -> None:
        self.storage = storage
        self.resolve_ttl_ms = int(resolve_ttl_ms)
        self.server = RangeServer(
            storage.path, listen=listen, lease_ms=int(lease_ms),
            specs=split_keyspace(int(count), split_points),
            events=storage.obs.events,
            heat=getattr(storage, "heat", None),
            auto_split=auto_split,
            split_cooldown_ms=split_cooldown_ms,
            max_auto_splits=max_auto_splits,
            hold_ms=int(resolve_ttl_ms))

    def router(self, **kw):
        from ..kv.rangeclient import RangeRouter
        return RangeRouter(root=self.storage.path, **kw)

    def closed_over(self, start: bytes,
                    end: bytes) -> list[tuple[int, int]]:
        """Per-range published closed timestamps over [start, end) —
        the same durable floors RangeRouter.closed_over serves remote
        readers, read straight off the directory (the plane shares its
        filesystem root, no client machinery). closed_ts 0 = no grant
        published yet, which counts as uncovered."""
        d = self.server.directory
        specs = d.load_specs() or self.server.specs
        out: list[tuple[int, int]] = []
        for s in sorted(specs, key=lambda s: s.start_key):
            if end and s.start_key and s.start_key >= end:
                break
            if s.end_key and s.end_key <= start:
                continue
            g = d.read_grant(s.id)
            out.append((int(s.id),
                        int(g.get("closed_ts", 0)) if g else 0))
        return out

    def committer(self, tso, **kw):
        from ..kv.twopc import TwoPhaseCommitter
        kw.setdefault("lock_ttl", self.resolve_ttl_ms)
        kw.setdefault("events", self.storage.obs.events)
        return TwoPhaseCommitter(self.router(), tso, **kw)

    def set_knobs(self, lease_ms: Optional[int] = None,
                  resolve_ttl_ms: Optional[int] = None,
                  auto_split: Optional[bool] = None,
                  split_cooldown_ms: Optional[int] = None,
                  max_auto_splits: Optional[int] = None) -> None:
        """The SIGHUP-reloadable subset."""
        if lease_ms is not None:
            self.server.lease_ms = max(int(lease_ms), 50)
        if resolve_ttl_ms is not None:
            self.resolve_ttl_ms = max(int(resolve_ttl_ms), 1)
            # the ledger hold mirrors the resolve TTL: past it, orphan
            # resolution owns the cleanup a lost txn_done left behind
            self.server.hold_ms = self.resolve_ttl_ms
            with self.server._mu:
                leaders = list(self.server._leaders.values())
            for ld in leaders:
                ld.hold_ms = self.resolve_ttl_ms
        if auto_split is not None:
            self.server.auto_split = bool(auto_split)
        if split_cooldown_ms is not None:
            self.server.split_cooldown_ms = max(int(split_cooldown_ms),
                                                0)
        if max_auto_splits is not None:
            self.server.max_auto_splits = max(int(max_auto_splits), 0)

    def status(self) -> dict:
        return {"listen": self.server.address,
                "lease_ms": self.server.lease_ms,
                "resolve_ttl_ms": self.resolve_ttl_ms,
                "auto_split": self.server.auto_split,
                "split_cooldown_ms": self.server.split_cooldown_ms,
                "max_auto_splits": self.server.max_auto_splits,
                "auto_splits_done": self.server._auto_splits,
                "table": [s.to_wire() | {"start": s.start_key.hex(),
                                         "end": s.end_key.hex()}
                          for s in self.server.specs],
                "hosted": self.server.describe()}

    def close(self) -> None:
        self.server.close()


__all__ = ["RangeDirectory", "RangeLeader", "RangeServer", "RangePlane"]
