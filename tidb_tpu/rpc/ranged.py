"""Range-sharded write leadership: per-range leases over a shared
durable root, serving percolator RPCs with fencing checks.

The tier splits the keyspace into ranges (kv/rangemeta.py) whose write
leadership is held by INDEPENDENTLY-leased leaders — possibly different
processes per range — so durable writes scale past one commit lock /
one WAL and a single crash only stalls the ranges that process led
(reference: the region model — raftstore leaders per region, not per
store; PAPER.md L7). Three pieces:

* RangeDirectory — the filesystem directory service under
  `<root>/ranges/`: the range table (`meta.json`, first writer wins),
  and per range a grant file + fencing-term file + WAL directory.
  Lease acquisition takes an flock only for the read-modify-write of
  the grant; TENURE is the grant's wall-clock expiry, never the flock
  (a SIGKILLed holder's flock vanishes with the process — the grant
  must keep fencing until it times out). Terms bump exactly when
  ownership changes hands, and persist crash-atomically (the
  rpc/server.py write_term idiom), so a deposed leader — or a client
  that last spoke to it — presents a provably stale term forever after.

* RangeLeader — one hosted range: an MVCCStore over the range's own
  WAL directory (sync_log='commit' by default: acked means fsynced),
  replayed on open, plus the range's closed timestamp (min pending
  lock start_ts - 1, else max committed ts — the per-range analog of
  the PR 11 pending-commit ledger).

* RangeServer — a FrameListener answering `range_*` percolator RPCs.
  Every data request carries the client's (range_id, epoch, term)
  context and is gated BEFORE any data access: wrong host answers
  NotLeaderError, an older routing table answers EpochNotMatchError,
  a superseded term answers StaleTermError, and a grant past its
  expiry refuses to serve at all — stale routing can produce a typed
  retry, never a silently wrong result. A lease loop acquires unheld
  ranges (election = the deterministic lease race over the shared
  directory; the WAL replay makes takeover lossless for acked commits)
  and renews held ones.

Loss window (document over deny): leadership fencing is checked at
request entry, not per WAL byte. A leader paused (SIGSTOP) MID-handler
past its lease expiry can still append after a successor opened the
same WAL — the same bounded window the pull-replication tier documents.
Kill-9 (the failure mode the chaos suite drives) has no such window:
a dead process appends nothing.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from .. import obs
from ..analysis import lockcheck
from ..kv.mvcc import (KVError, KeyIsLockedError, MVCCStore, Mutation,
                       PyOrderedKV, TxnNotFoundError, WriteConflictError,
                       fsync_dir)
from ..kv.rangemeta import RangeSpec, split_keyspace
from ..util import failpoint
from .errors import (EpochNotMatchError, NotLeaderError, RPCError,
                     StaleLeaseError, StaleTermError, traced_response,
                     wire_error)
from .frame import get_range_ctx, get_trace_ctx
from .server import FrameListener, read_term, write_term


def _now_ms() -> float:
    # wall clock on purpose: grant expiries must compare across
    # processes, which monotonic clocks never do
    return time.time() * 1000.0


def _write_json_atomic(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---- the directory service ---------------------------------------------------
class RangeDirectory:
    """Range table + per-range lease grants under `<root>/ranges/`.

    Layout:
        ranges/meta.json          the range table (id, bounds, epoch)
        ranges/meta.lock          flock serializing table writes
        ranges/r<id>/lease.lock   flock serializing grant writes
        ranges/r<id>/grant.json   {owner, token, term, expires_ms, ...}
        ranges/r<id>/term         persisted fencing term (write_term)
        ranges/r<id>/data/        the range's own WAL directory
    """

    def __init__(self, root: str) -> None:
        self.root = root
        self.dir = os.path.join(root, "ranges")
        os.makedirs(self.dir, exist_ok=True)

    # ---- paths ----
    def _meta_path(self) -> str:
        return os.path.join(self.dir, "meta.json")

    def _range_dir(self, rid: int) -> str:
        return os.path.join(self.dir, f"r{int(rid)}")

    def data_dir(self, rid: int) -> str:
        return os.path.join(self._range_dir(rid), "data")

    def _grant_path(self, rid: int) -> str:
        return os.path.join(self._range_dir(rid), "grant.json")

    def _term_path(self, rid: int) -> str:
        return os.path.join(self._range_dir(rid), "term")

    @contextmanager
    def _flock(self, path: str):
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            os.close(fd)  # releases the flock with the fd

    # ---- the range table ----
    def bootstrap(self, specs: Optional[list] = None) -> list[RangeSpec]:
        """Write the range table if absent (first writer wins — every
        later bootstrapper adopts the existing table regardless of its
        own knobs, so concurrently started servers can never disagree
        about range bounds). Returns the authoritative table."""
        with self._flock(os.path.join(self.dir, "meta.lock")):
            existing = self.load_specs()
            if existing is not None:
                return existing
            specs = list(specs) if specs else split_keyspace(1)
            _write_json_atomic(self._meta_path(), {
                "ranges": [{"id": s.id, "start": s.start_key.hex(),
                            "end": s.end_key.hex(), "epoch": s.epoch}
                           for s in specs]})
            for s in specs:
                os.makedirs(self.data_dir(s.id), exist_ok=True)
            return specs

    def load_specs(self) -> Optional[list[RangeSpec]]:
        doc = _read_json(self._meta_path())
        if not doc:
            return None
        return [RangeSpec(int(r["id"]), bytes.fromhex(r["start"]),
                          bytes.fromhex(r["end"]), int(r.get("epoch", 1)))
                for r in doc["ranges"]]

    def bump_epoch(self, rid: int) -> int:
        """Advance one range's routing epoch (the metadata-changed
        signal: clients carrying the old epoch get EpochNotMatchError
        and reload the table). Bounds stay put — this repo reshapes
        tables offline, not live."""
        with self._flock(os.path.join(self.dir, "meta.lock")):
            doc = _read_json(self._meta_path())
            if not doc:
                raise RPCError("range table missing")
            new = 0
            for r in doc["ranges"]:
                if int(r["id"]) == int(rid):
                    r["epoch"] = new = int(r.get("epoch", 1)) + 1
            if not new:
                raise RPCError(f"unknown range {rid}")
            _write_json_atomic(self._meta_path(), doc)
            return new

    # ---- grants ----
    def read_grant(self, rid: int) -> Optional[dict]:
        """Lock-free grant read (atomic rename makes it torn-proof) —
        what routers use to find a range's current leader."""
        return _read_json(self._grant_path(rid))

    def acquire(self, rid: int, owner: str,
                lease_ms: int) -> Optional[dict]:
        """Take the range's lease if it is free, expired, or already
        ours. The token bumps on EVERY grant write (per-tenure fencing
        for renewal); the TERM bumps only when ownership changes hands
        (the cross-process fencing epoch a deposed leader can never
        re-present). Returns the grant, or None while another owner's
        grant is still live."""
        os.makedirs(self._range_dir(rid), exist_ok=True)
        with self._flock(os.path.join(self._range_dir(rid),
                                      "lease.lock")):
            g = _read_json(self._grant_path(rid))
            now = _now_ms()
            if g and g.get("owner") != owner \
                    and float(g.get("expires_ms", 0)) > now:
                return None  # live grant held elsewhere
            prev_owner = g.get("owner", "") if g else ""
            # the term floor survives a torn/corrupt grant file: the
            # dedicated term file is the durable fencing record
            term = max(int(g.get("term", 0)) if g else 0,
                       read_term(self._term_path(rid)))
            if prev_owner != owner:
                term += 1
                write_term(self._term_path(rid), term)
            grant = {"range_id": int(rid), "owner": owner,
                     "token": (int(g.get("token", 0)) if g else 0) + 1,
                     "term": term, "expires_ms": now + int(lease_ms),
                     "prev_owner": prev_owner}
            _write_json_atomic(self._grant_path(rid), grant)
            return grant

    def renew(self, rid: int, owner: str, token: int,
              lease_ms: int) -> dict:
        """Extend our own grant; StaleLeaseError when the grant is no
        longer ours (another process acquired while our lease was
        expired — the holder must fence itself immediately)."""
        with self._flock(os.path.join(self._range_dir(rid),
                                      "lease.lock")):
            g = _read_json(self._grant_path(rid))
            if not g or g.get("owner") != owner \
                    or int(g.get("token", -1)) != int(token):
                raise StaleLeaseError(
                    f"range {rid} grant is {g and g.get('owner')!r} "
                    f"token {g and g.get('token')}, not {owner!r} "
                    f"token {token}")
            g["expires_ms"] = _now_ms() + int(lease_ms)
            _write_json_atomic(self._grant_path(rid), g)
            return g

    def release(self, rid: int, owner: str, token: int) -> bool:
        """Zero our grant's expiry so a successor can acquire without
        waiting out the lease (graceful shutdown / forced transfer)."""
        with self._flock(os.path.join(self._range_dir(rid),
                                      "lease.lock")):
            g = _read_json(self._grant_path(rid))
            if not g or g.get("owner") != owner \
                    or int(g.get("token", -1)) != int(token):
                return False
            g["expires_ms"] = 0
            _write_json_atomic(self._grant_path(rid), g)
            return True


# ---- one hosted range --------------------------------------------------------
class RangeLeader:
    """A range this process leads: its own durable MVCC store (WAL
    replay on open makes takeover lossless for acked commits) plus the
    lease/fencing state the request gate checks."""

    def __init__(self, spec: RangeSpec, grant: dict, data_dir: str,
                 sync_log: str = "commit") -> None:
        self.spec = spec
        self.grant = dict(grant)
        self.store = MVCCStore(PyOrderedKV(data_dir, sync_log=sync_log))
        self._max_commit = self.store.max_commit_ts()
        self.fenced = False

    @property
    def term(self) -> int:
        return int(self.grant.get("term", 0))

    def note_commit(self, commit_ts: int) -> None:
        if commit_ts > self._max_commit:
            self._max_commit = commit_ts

    def closed_ts(self) -> int:
        """Everything at or below this ts is settled on this range: one
        pending prewrite holds it at start_ts-1 (that txn may still
        commit anywhere above its start), otherwise the newest commit
        — the per-range pending-commit ledger."""
        locks = self.store.all_locks()
        if locks:
            return min(l.start_ts for l in locks) - 1
        return self._max_commit

    def close(self) -> None:
        close = getattr(self.store.kv, "close", None)
        if close is not None:
            close()


def _kv_guarded(fn) -> dict:
    """Run one store operation and fold its typed KV failures into the
    response envelope — KV errors are RESULTS the committer interprets
    (resolve the lock, retry the conflict), not transport errors, so
    they must not burn the client's retry budget or trip its breaker."""
    try:
        # range.apply: the store mutation/read itself (child spans —
        # wal.append, wal.fsync — open inside the engine) riding back
        # to the coordinator's stitched trace via traced_response
        with obs.span("range.apply"):
            return {"ok": True, "v": fn()}
    except KeyIsLockedError as e:
        lk = e.lock
        return {"ok": False, "err_kv": {
            "kind": "locked", "key": lk.key, "primary": lk.primary,
            "start_ts": lk.start_ts, "op": lk.op, "ttl": lk.ttl}}
    except WriteConflictError as e:
        return {"ok": False, "err_kv": {
            "kind": "conflict", "key": e.key, "start_ts": e.start_ts,
            "conflict_ts": e.conflict_ts}}
    except TxnNotFoundError as e:
        return {"ok": False, "err_kv": {"kind": "txn_not_found",
                                        "msg": str(e)}}
    except KVError as e:
        return {"ok": False, "err_kv": {"kind": "kv", "msg": str(e)}}


# ---- the server ---------------------------------------------------------------
class RangeServer(FrameListener):
    """Per-range write leadership over the frame protocol."""

    _thread_prefix = "titpu-range"

    def __init__(self, root: str, listen: str = "127.0.0.1:0",
                 lease_ms: int = 1000, specs: Optional[list] = None,
                 sync_log: str = "commit", events=None,
                 heat=None) -> None:
        self.directory = RangeDirectory(root)
        self.specs = self.directory.bootstrap(specs)
        self.lease_ms = int(lease_ms)
        self.events = events
        # keyspace heat recorder: the LEADER apply is the single
        # counting site for routed writes (the range tier's committers
        # carry no recorder — see kv/twopc.py)
        self.heat = heat
        # guards the hosted-leader map only — every critical section is
        # a dict op (HOT_LOCKS-declared: this sits on the 2PC data path)
        self._mu = lockcheck.lock("RangeServer._mu", hot=True)
        self._leaders: dict[int, RangeLeader] = {}
        self._closed = False
        fam, target = self._start_listener(listen)
        import socket as _socket
        if fam == _socket.AF_INET:
            host = target[0] or "127.0.0.1"
            self.address = f"{host}:{self.port}"
        else:
            self.address = str(listen)
        # one synchronous pass before serving: a just-constructed server
        # already hosts every free range (tests need no settle loop)
        self._lease_tick()
        self._stop = threading.Event()
        self._lease_thread = threading.Thread(
            target=self._lease_loop, name="titpu-range-lease",
            daemon=True)
        self._lease_thread.start()

    # ---- lease plane ----
    def _lease_loop(self) -> None:
        period = max(0.05, self.lease_ms / 3000.0)
        while not self._stop.wait(period):
            try:
                self._lease_tick()
            except Exception as e:  # keep the plane alive
                if self.events is not None:
                    self.events.record("range_lease_error", str(e),
                                       severity="warning")

    def _lease_tick(self) -> None:
        specs = self.directory.load_specs()
        if specs:
            self.specs = specs
        drop = failpoint.inject("range/lease-drop")
        for spec in self.specs:
            with self._mu:
                leader = self._leaders.get(spec.id)
            if leader is not None:
                leader.spec = spec  # adopt epoch bumps
                if drop is not None and (
                        drop is True or int(drop) == spec.id):
                    self.directory.release(spec.id, self.address,
                                           leader.grant["token"])
                    self._drop_leader(spec.id, "lease-drop failpoint")
                    continue
                try:
                    leader.grant = self.directory.renew(
                        spec.id, self.address, leader.grant["token"],
                        self.lease_ms)
                except (StaleLeaseError, OSError) as e:
                    self._drop_leader(spec.id, f"lease lost: {e}")
            else:
                try:
                    g = self.directory.acquire(spec.id, self.address,
                                               self.lease_ms)
                except OSError:
                    g = None
                if g:
                    self._open_leader(spec, g)

    def _open_leader(self, spec: RangeSpec, grant: dict) -> None:
        leader = RangeLeader(spec, grant,
                             self.directory.data_dir(spec.id))
        with self._mu:
            self._leaders[spec.id] = leader
        obs.RANGE_LEADERS.inc()
        prev = grant.get("prev_owner", "")
        if prev and prev != self.address:
            obs.RANGE_TRANSFERS.inc()
            if self.events is not None:
                self.events.record(
                    "range_transfer",
                    f"r{spec.id} {prev} -> {self.address} "
                    f"term={grant['term']}", severity="warning")

    def _drop_leader(self, rid: int, why: str) -> None:
        with self._mu:
            leader = self._leaders.pop(rid, None)
        if leader is None:
            return
        leader.fenced = True
        obs.RANGE_LEADERS.dec()
        if self.events is not None:
            self.events.record("range_transfer",
                               f"r{rid} dropped by {self.address}: "
                               f"{why}", severity="warning")
        leader.close()

    # ---- request gate ----
    def _leader_for(self, params: dict) -> RangeLeader:
        """The fencing gate every data request passes BEFORE any data
        access; raises typed so the client refreshes + retries instead
        of acting on a stale view. Traced as range.lease_gate so a
        fencing rejection's cost is visible in the stitched tree."""
        with obs.span("range.lease_gate"):
            return self._leader_for_gated(params)

    def _leader_for_gated(self, params: dict) -> RangeLeader:
        rc = get_range_ctx(params)
        if rc is None:
            raise RPCError("missing range context")
        rid = int(rc["range_id"])
        with self._mu:
            leader = self._leaders.get(rid)
        if leader is None or leader.fenced:
            g = self.directory.read_grant(rid)
            hint = (f" (grant: {g['owner']} term {g['term']})"
                    if g else "")
            raise NotLeaderError(f"range {rid} not led here{hint}")
        if float(leader.grant.get("expires_ms", 0)) <= _now_ms():
            # our own lease ran out and the renew loop hasn't caught it
            # yet — refusing here is what makes the lease a fence
            raise NotLeaderError(f"range {rid} lease expired on "
                                 f"{self.address}")
        if int(rc.get("epoch", 0)) != int(leader.spec.epoch):
            raise EpochNotMatchError(
                f"range {rid} epoch {rc.get('epoch')} != "
                f"{leader.spec.epoch} — reload the range table")
        cterm = int(rc.get("term", 0))
        if cterm < leader.term:
            raise StaleTermError(f"range {rid} request term {cterm} < "
                                 f"current {leader.term}")
        if cterm > leader.term:
            # the CLIENT has seen a newer tenure than ours: we are the
            # deposed one (a renew raced); never serve on a stale term
            raise NotLeaderError(f"range {rid} deposed: request term "
                                 f"{cterm} > local {leader.term}")
        return leader

    # ---- dispatch ----
    def _dispatch(self, req) -> dict:
        if not isinstance(req, dict) or "m" not in req:
            return wire_error(None, RPCError("bad request"))
        rid = req.get("id")
        method = str(req.get("m"))
        params = req.get("p") if isinstance(req.get("p"), dict) else {}
        handler = getattr(self, f"_h_{method}", None) \
            if method.startswith("range_") else None
        if handler is None:
            return wire_error(rid, RPCError(
                f"unknown range method {method!r}"))
        return traced_response(rid, method, lambda: handler(params),
                               get_trace_ctx(req))

    # ---- percolator handlers ----
    def _h_range_prewrite(self, params: dict) -> dict:
        leader = self._leader_for(params)
        muts = [Mutation(bytes(m[0]), bytes(m[1]), bytes(m[2]))
                for m in params["mutations"]]
        out = _kv_guarded(lambda: leader.store.prewrite(
            muts, bytes(params["primary"]), int(params["start_ts"]),
            int(params.get("ttl", 3000))))
        # the leader-side apply is where a routed write lands on the
        # keyspace heatmap (exactly once: the coordinator's committer
        # carries no recorder over the range tier)
        if out["ok"] and self.heat is not None and self.heat.enabled:
            self.heat.note_range(
                leader.spec.id,
                write_rows=len(muts),
                write_bytes=sum(len(m.value or b"") for m in muts),
                keys=[m.key for m in muts])
        # applied-but-unacked: a kill here is the harshest prewrite
        # crash — the lock is durable, the coordinator never heard back
        failpoint.inject("range/before-prewrite-ack")
        return out

    def _h_range_commit(self, params: dict) -> dict:
        leader = self._leader_for(params)
        commit_ts = int(params["commit_ts"])
        out = _kv_guarded(lambda: leader.store.commit(
            [bytes(k) for k in params["keys"]],
            int(params["start_ts"]), commit_ts))
        if out["ok"]:
            leader.note_commit(commit_ts)
        failpoint.inject("range/before-commit-ack")
        return out

    def _h_range_rollback(self, params: dict) -> dict:
        leader = self._leader_for(params)
        return _kv_guarded(lambda: leader.store.rollback(
            [bytes(k) for k in params["keys"]],
            int(params["start_ts"])))

    def _h_range_get(self, params: dict) -> dict:
        leader = self._leader_for(params)
        out = _kv_guarded(lambda: leader.store.get(
            bytes(params["key"]), int(params["read_ts"])))
        if out["ok"] and self.heat is not None and self.heat.enabled:
            v = out["v"]
            self.heat.note_range(
                leader.spec.id, read_rows=1,
                read_bytes=len(v) if v else 0)
        return out

    def _h_range_scan(self, params: dict) -> dict:
        leader = self._leader_for(params)
        spec = leader.spec
        start = max(bytes(params.get("start", b"")), spec.start_key)
        end = bytes(params.get("end", b""))
        if spec.end_key and (not end or end > spec.end_key):
            end = spec.end_key
        out = _kv_guarded(lambda: [list(kv) for kv in leader.store.scan(
            start, end, int(params["read_ts"]),
            int(params.get("limit", -1)))])
        if out["ok"] and self.heat is not None and self.heat.enabled:
            rows = out["v"]
            self.heat.note_range(
                leader.spec.id, read_rows=len(rows),
                read_bytes=sum(len(kv[1] or b"") for kv in rows))
        return out

    def _h_range_check_txn_status(self, params: dict) -> dict:
        leader = self._leader_for(params)

        def run():
            commit_ts, expired = leader.store.check_txn_status(
                bytes(params["primary"]), int(params["lock_ts"]),
                int(params["current_ts"]))
            return {"commit_ts": commit_ts, "expired": expired}

        return _kv_guarded(run)

    def _h_range_resolve_lock(self, params: dict) -> dict:
        leader = self._leader_for(params)
        out = _kv_guarded(lambda: leader.store.resolve_lock(
            bytes(params["key"]), int(params["start_ts"]),
            int(params["commit_ts"])))
        if out["ok"]:
            obs.RANGE_ORPHAN_RESOLUTIONS.inc()
        return out

    # ---- metadata / diagnostics ----
    def _h_range_table(self, params: dict) -> dict:
        """The routing bootstrap for clients without filesystem access
        to the shared root: table + every range's current grant."""
        specs = self.directory.load_specs() or self.specs
        grants = {}
        for s in specs:
            g = self.directory.read_grant(s.id)
            if g:
                grants[int(s.id)] = {"owner": g.get("owner", ""),
                                     "term": int(g.get("term", 0)),
                                     "expires_ms":
                                         float(g.get("expires_ms", 0))}
        return {"specs": [s.to_wire() for s in specs],
                "grants": grants}

    def _h_range_info(self, params: dict) -> dict:
        return {"ranges": self.describe()}

    def describe(self) -> list[dict]:
        """Hosted ranges, one row each — what /status and cluster_info
        render."""
        with self._mu:
            leaders = sorted(self._leaders.items())
        out = []
        for rid, leader in leaders:
            rr, rb, wr, wb = self.heat.range_totals(rid) \
                if self.heat is not None else (0, 0, 0, 0)
            out.append({"range_id": rid, "leader": self.address,
                        "term": leader.term,
                        "epoch": leader.spec.epoch,
                        "token": int(leader.grant.get("token", 0)),
                        "closed_ts": leader.closed_ts(),
                        "start": leader.spec.start_key.hex(),
                        "end": leader.spec.end_key.hex(),
                        "read_rows": rr, "read_bytes": rb,
                        "write_rows": wr, "write_bytes": wb})
        return out

    def hosted_ids(self) -> list[int]:
        with self._mu:
            return sorted(self._leaders)

    def close(self, release: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._lease_thread.join(timeout=5.0)
        self._close_listener()
        with self._mu:
            leaders = dict(self._leaders)
            self._leaders.clear()
        for rid, leader in leaders.items():
            leader.fenced = True
            if release:
                try:
                    self.directory.release(rid, self.address,
                                           leader.grant["token"])
                except OSError:
                    pass
            obs.RANGE_LEADERS.dec()
            leader.close()


class RangePlane:
    """The [ranges]-armed subsystem one Storage owns: a RangeServer
    rooted under the storage path plus the knobs mirror. Entirely OFF
    the statement path — arming starts a listener and a lease loop;
    statements never consult it, which is what makes `[ranges]`
    disabled byte-identical to the pre-range engine."""

    def __init__(self, storage, count: int = 1, split_points=(),
                 lease_ms: int = 1000, resolve_ttl_ms: int = 3000,
                 listen: str = "127.0.0.1:0") -> None:
        self.storage = storage
        self.resolve_ttl_ms = int(resolve_ttl_ms)
        self.server = RangeServer(
            storage.path, listen=listen, lease_ms=int(lease_ms),
            specs=split_keyspace(int(count), split_points),
            events=storage.obs.events,
            heat=getattr(storage, "heat", None))

    def router(self, **kw):
        from ..kv.rangeclient import RangeRouter
        return RangeRouter(root=self.storage.path, **kw)

    def committer(self, tso, **kw):
        from ..kv.twopc import TwoPhaseCommitter
        kw.setdefault("lock_ttl", self.resolve_ttl_ms)
        kw.setdefault("events", self.storage.obs.events)
        return TwoPhaseCommitter(self.router(), tso, **kw)

    def set_knobs(self, lease_ms: Optional[int] = None,
                  resolve_ttl_ms: Optional[int] = None) -> None:
        """The SIGHUP-reloadable subset."""
        if lease_ms is not None:
            self.server.lease_ms = max(int(lease_ms), 50)
        if resolve_ttl_ms is not None:
            self.resolve_ttl_ms = max(int(resolve_ttl_ms), 1)

    def status(self) -> dict:
        return {"listen": self.server.address,
                "lease_ms": self.server.lease_ms,
                "resolve_ttl_ms": self.resolve_ttl_ms,
                "table": [s.to_wire() | {"start": s.start_key.hex(),
                                         "end": s.end_key.hex()}
                          for s in self.server.specs],
                "hosted": self.server.describe()}

    def close(self) -> None:
        self.server.close()


__all__ = ["RangeDirectory", "RangeLeader", "RangeServer", "RangePlane"]
