"""Typed transport errors (reference: store/tikv/region_request.go's
error taxonomy — every failure class retries differently, and what
cannot be retried surfaces to the session with a real error code).

All of these are CodedError subclasses: a follower whose leader is gone
answers MySQL clients with errno 9001 (ER_TIKV_SERVER_TIMEOUT), not a
hang and not a bare 1105.
"""

from __future__ import annotations

from ..errno import ER_TIKV_SERVER_TIMEOUT, ER_WRITE_CONFLICT, CodedError


class RPCError(CodedError):
    """Base of the transport error surface."""

    errno = ER_TIKV_SERVER_TIMEOUT
    sqlstate = "HY000"


class LeaderUnavailable(RPCError):
    """The store leader could not be reached within the backoff budget.

    Carries the Backoffer's typed retry history in the message so an
    operator sees WHY the budget burned (reference: backoff.go
    exhaustion strings). Followers raise this from every write path
    while degraded — reads keep serving the last replicated state."""


class StaleLeaseError(RPCError):
    """A fenced operation arrived with a superseded lease token.

    The holder lost its lease (partition/pause) and another mutator may
    have run; the local buffered mutations were reverted, so retrying
    the whole statement at a fresh view is safe — hence the
    write-conflict errno clients already retry on."""

    errno = ER_WRITE_CONFLICT
    sqlstate = "40001"


class StaleTermError(RPCError):
    """A fenced operation carried a superseded fencing TERM.

    Terms (fencing epochs) outlive connections and leases: a promoted
    leader bumps the cluster term, so a zombie holding the old term —
    the deposed leader itself, or a client that last spoke to it — has
    every mutation rejected before it can split-brain the WAL
    (reference analog: raft terms rejecting a deposed leader's
    proposals). Clients react by re-resolving the leader, not by
    retrying the same request."""

    errno = ER_WRITE_CONFLICT
    sqlstate = "40001"


class NotLeaderError(RPCError):
    """The addressed server does not (or no longer) lead the range the
    request named. Carries the server's current view of the grant in
    the message; the client reacts by refreshing its range-leader cache
    and retrying against the new holder (reference:
    region_request.go onNotLeader — retry with the hinted leader),
    never by failing the statement. Like the region-miss class it maps
    to the write-conflict errno so a statement that does escape retries
    is safely re-runnable."""

    errno = ER_WRITE_CONFLICT
    sqlstate = "40001"


class EpochNotMatchError(RPCError):
    """The request's range epoch is older than the server's routing
    table — the range METADATA changed (split/reshard) since the client
    cached it. Distinct from NotLeaderError: the cure is reloading the
    range table itself, not just the leader grant (reference:
    region_request.go onRegionError EpochNotMatch — invalidate the
    region cache entry and re-locate)."""

    errno = ER_WRITE_CONFLICT
    sqlstate = "40001"


class ResultUndetermined(RPCError):
    """A WAL publish may or may not have landed (the leader became
    unreachable after the request was sent and before a response
    arrived, and retries exhausted the budget).

    The reference surfaces exactly this as ErrResultUndetermined
    (store/tikv terror): the client must treat the statement's outcome
    as unknown rather than failed. Locally the buffered records are
    reverted to the last replicated state; if the append DID land, the
    next successful tail re-applies it."""


class ReplicaStaleError(RPCError):
    """A routed replica read could not be served at the requested
    timestamp: the replica's applied/closed ts does not cover read_ts
    (apply stalled, serving disabled, or the bounded ReadIndex-style
    wait expired). The ROUTER reacts by failing over to the next
    candidate and finally to the leader — the statement never fails
    and never returns stale rows (reference analog: a follower read
    whose ReadIndex wait times out retries the leader peer)."""


class WalOffsetMismatch(RPCError):
    """An append's expected WAL position no longer matches the file.

    Only reachable when fencing was bypassed (or the leader lost state);
    kept distinct from StaleLeaseError so chaos tests can tell the two
    protections apart."""

    errno = ER_WRITE_CONFLICT
    sqlstate = "40001"


def traced_response(rid, method: str, fn, trace_ctx) -> dict:
    """The one traced-dispatch envelope both RPC servers answer with:
    run `fn` (under a SpanCollector when the request carried trace
    context), return {'id','r'[,'sp']} or the wire_error shape."""
    from .. import obs
    try:
        result, spans = obs.run_remote_traced(
            trace_ctx, f"remote.{method}", fn)
        out = {"id": rid, "r": result}
        if spans is not None:
            out["sp"] = spans
        return out
    except Exception as e:  # noqa: BLE001 — keep the server alive
        return wire_error(rid, e)


def wire_error(rid, e: BaseException) -> dict:
    """One server-side error as a response envelope — the single place
    the err-dict wire shape is produced (CoordRPCServer and the diag
    listeners both answer with it; WIRE_ERRORS re-raises it typed)."""
    if isinstance(e, CodedError):
        return {"id": rid, "err": {"type": type(e).__name__,
                                   "msg": str(e), "errno": e.errno}}
    return {"id": rid, "err": {"type": "RPCError",
                               "msg": f"{type(e).__name__}: {e}"}}


# wire name -> class, for re-raising a server-side error client-side
WIRE_ERRORS = {
    "LeaderUnavailable": LeaderUnavailable,
    "StaleLeaseError": StaleLeaseError,
    "StaleTermError": StaleTermError,
    "NotLeaderError": NotLeaderError,
    "EpochNotMatchError": EpochNotMatchError,
    "ResultUndetermined": ResultUndetermined,
    "ReplicaStaleError": ReplicaStaleError,
    "WalOffsetMismatch": WalOffsetMismatch,
    "RPCError": RPCError,
}


__all__ = ["RPCError", "LeaderUnavailable", "StaleLeaseError",
           "StaleTermError", "NotLeaderError", "EpochNotMatchError",
           "ResultUndetermined", "ReplicaStaleError",
           "WalOffsetMismatch", "WIRE_ERRORS", "wire_error",
           "traced_response"]
