"""Typed transport errors (reference: store/tikv/region_request.go's
error taxonomy — every failure class retries differently, and what
cannot be retried surfaces to the session with a real error code).

All of these are CodedError subclasses: a follower whose leader is gone
answers MySQL clients with errno 9001 (ER_TIKV_SERVER_TIMEOUT), not a
hang and not a bare 1105.
"""

from __future__ import annotations

from ..errno import ER_TIKV_SERVER_TIMEOUT, ER_WRITE_CONFLICT, CodedError


class RPCError(CodedError):
    """Base of the transport error surface."""

    errno = ER_TIKV_SERVER_TIMEOUT
    sqlstate = "HY000"


class LeaderUnavailable(RPCError):
    """The store leader could not be reached within the backoff budget.

    Carries the Backoffer's typed retry history in the message so an
    operator sees WHY the budget burned (reference: backoff.go
    exhaustion strings). Followers raise this from every write path
    while degraded — reads keep serving the last replicated state."""


class StaleLeaseError(RPCError):
    """A fenced operation arrived with a superseded lease token.

    The holder lost its lease (partition/pause) and another mutator may
    have run; the local buffered mutations were reverted, so retrying
    the whole statement at a fresh view is safe — hence the
    write-conflict errno clients already retry on."""

    errno = ER_WRITE_CONFLICT
    sqlstate = "40001"


class ResultUndetermined(RPCError):
    """A WAL publish may or may not have landed (the leader became
    unreachable after the request was sent and before a response
    arrived, and retries exhausted the budget).

    The reference surfaces exactly this as ErrResultUndetermined
    (store/tikv terror): the client must treat the statement's outcome
    as unknown rather than failed. Locally the buffered records are
    reverted to the last replicated state; if the append DID land, the
    next successful tail re-applies it."""


class WalOffsetMismatch(RPCError):
    """An append's expected WAL position no longer matches the file.

    Only reachable when fencing was bypassed (or the leader lost state);
    kept distinct from StaleLeaseError so chaos tests can tell the two
    protections apart."""

    errno = ER_WRITE_CONFLICT
    sqlstate = "40001"


# wire name -> class, for re-raising a server-side error client-side
WIRE_ERRORS = {
    "LeaderUnavailable": LeaderUnavailable,
    "StaleLeaseError": StaleLeaseError,
    "ResultUndetermined": ResultUndetermined,
    "WalOffsetMismatch": WalOffsetMismatch,
    "RPCError": RPCError,
}


__all__ = ["RPCError", "LeaderUnavailable", "StaleLeaseError",
           "ResultUndetermined", "WalOffsetMismatch", "WIRE_ERRORS"]
