"""Network fault injection at the framed-RPC seam.

Every coordination/range RPC byte in the system crosses
`rpc/frame.py`'s send_frame/recv_frame. This module gives those two
functions a deterministic per-peer fault plane — delay, silent loss,
duplication, and partition — armed through the ordinary failpoint
registry so the kill-9 harnesses' env/process plumbing works unchanged
(reference: the message-filter layer TiKV's raftstore tests use —
`test_raftstore`'s `PartitionFilterFactory`/`DelayFilter` — collapsed
onto one socket seam).

Fault kinds (failpoint names) and their schedule values:

    net/delay       {"peer": "...", "dir": "...", "ms": 5}
                    sleep `ms` before the frame op (a slow link)
    net/drop        {"peer": "...", "dir": "...", "nth": 3}
                    every nth matching frame silently vanishes; a
                    dropped request surfaces as the client's request
                    timeout, a dropped response the same — retry
                    machinery must absorb both
    net/dup         {"peer": "...", "nth": 3}
                    every nth matching frame is sent twice (send-side
                    only) — drives request idempotency and the
                    client's stale-response request-id fencing
    net/partition   {"peer": "...", "side": "...", "dir": "..."}
                    matching frames raise ConnectionResetError — the
                    wire is cut; disable the failpoint to heal

A schedule is one rule dict or a list of rule dicts. Common fields:

    peer   substring matched against either endpoint address of the
           socket ("host:port" or a unix path); missing = all peers
    side   which endpoint must match `peer`: "peer" (the remote end —
           traffic other nodes aim at that address), "local" (sockets
           the named server owns), "any" (default). `side` + `dir`
           express ASYMMETRIC partitions: {"peer": A, "side": "peer",
           "dir": "send"} cuts frames other processes send TOWARD A
           while A's own sends still flow.
    dir    "send" | "recv" | "both" — which frame ops the rule
           applies to. Default "both" for delay/partition, "send" for
           drop/dup (both endpoints of a link often live in one
           process, and a "both" loss rule would drop the same frame
           twice, once per side)

Scalar schedule values (env arming, `TIDB_TPU_FAILPOINTS=net/delay=5`)
coerce: a number means {"ms": N} for delay and {"nth": N} for
drop/dup; `true` means one match-everything rule.

Determinism: no randomness anywhere — `nth` counts frames per
(kind, rule) under a lock, delays are fixed, partitions are
level-triggered until healed.

Zero-work contract: when no net/* failpoint is armed, the frame path
pays ONE module-attribute read (`ACTIVE`) per operation and nothing
else. `WORK` counts armed-path entries and is the poison pin the
hygiene test asserts stays flat during unarmed traffic. ACTIVE is
recomputed by a failpoint arming-change listener, never polled.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from ..util import failpoint

KINDS = ("net/delay", "net/drop", "net/dup", "net/partition")

# the one flag the unarmed hot path reads; flipped only by _refresh()
ACTIVE = False
# armed-path entry counter — the zero-work poison pin
WORK = 0

_mu = threading.Lock()
_counts: dict[tuple, int] = {}


def _refresh() -> None:
    global ACTIVE
    armed = any(failpoint.is_enabled(k) for k in KINDS)
    if armed != ACTIVE:
        ACTIVE = armed
        if not armed:
            with _mu:
                _counts.clear()


failpoint.on_change(_refresh)
_refresh()  # env-armed net/* points predate this import


def reset() -> None:
    """Clear nth-counters and the WORK pin (test isolation)."""
    global WORK
    with _mu:
        _counts.clear()
        WORK = 0


# ---- arming helpers --------------------------------------------------------
def arm(kind: str, **rule: Any) -> None:
    """failpoint.enable('net/<kind>', rule) with appending semantics:
    arming the same kind again extends the schedule instead of
    replacing it, so a harness can partition two peers independently."""
    name = kind if kind.startswith("net/") else f"net/{kind}"
    if name not in KINDS:
        raise ValueError(f"unknown net fault kind {kind!r}")
    rules = _schedule(name) if failpoint.is_enabled(name) else []
    failpoint.enable(name, rules + [dict(rule)])


def heal(kind: Optional[str] = None) -> None:
    """Disable one net fault kind, or all of them."""
    if kind is None:
        for k in KINDS:
            failpoint.disable(k)
        return
    name = kind if kind.startswith("net/") else f"net/{kind}"
    failpoint.disable(name)


# ---- schedule evaluation ---------------------------------------------------
def _rules_of(value: Any) -> list[dict]:
    if value is None:
        return []
    if isinstance(value, dict):
        return [value]
    if isinstance(value, (list, tuple)):
        return [r for r in value if isinstance(r, dict)]
    if value is True:
        return [{}]
    if isinstance(value, (int, float)):
        return [{"ms": float(value), "nth": int(value) or 1}]
    return []


def _addr_label(addr: Any) -> str:
    if isinstance(addr, (tuple, list)) and len(addr) >= 2:
        return f"{addr[0]}:{addr[1]}"
    return str(addr or "")


def _labels(sock) -> tuple[str, str]:
    """(peer endpoint, local endpoint) of the socket, best-effort —
    a half-dead socket matches by whichever endpoint still resolves."""
    try:
        peer = _addr_label(sock.getpeername())
    except OSError:
        peer = ""
    try:
        local = _addr_label(sock.getsockname())
    except OSError:
        local = ""
    return peer, local


def _matches(rule: dict, peer: str, local: str, direction: str,
             default_dir: str = "both") -> bool:
    d = str(rule.get("dir", default_dir))
    if d != "both" and d != direction:
        return False
    pat = str(rule.get("peer", ""))
    if not pat:
        return True
    side = str(rule.get("side", "any"))
    if side == "peer":
        return pat in peer
    if side == "local":
        return pat in local
    return pat in peer or pat in local


def _nth_fires(kind: str, idx: int, nth: int) -> bool:
    if nth <= 1:
        return True
    with _mu:
        k = (kind, idx)
        n = _counts.get(k, 0) + 1
        _counts[k] = n
        return n % nth == 0


def _schedule(kind: str) -> list[dict]:
    try:
        return _rules_of(failpoint.inject(kind))
    except Exception:
        # a non-schedule value (exception-armed by mistake) must not
        # corrupt the transport with an unexpected error type
        return []


# one literal inject site per kind: the failpoint-registry lint maps
# DECLARED <-> inject sites textually, and these are the real read
# points the frame hooks below evaluate on every armed operation
def _sched_partition() -> list[dict]:
    try:
        return _rules_of(failpoint.inject("net/partition"))
    except Exception:
        return []


def _sched_delay() -> list[dict]:
    try:
        return _rules_of(failpoint.inject("net/delay"))
    except Exception:
        return []


def _sched_drop() -> list[dict]:
    try:
        return _rules_of(failpoint.inject("net/drop"))
    except Exception:
        return []


def _sched_dup() -> list[dict]:
    try:
        return _rules_of(failpoint.inject("net/dup"))
    except Exception:
        return []


def on_send(sock, nbytes: int) -> int:
    """Armed-path send hook. Returns how many copies of the frame to
    put on the wire: 1 = pass, 0 = net/drop, 2 = net/dup. Raises
    ConnectionResetError for a matching net/partition; sleeps for a
    matching net/delay."""
    global WORK
    WORK += 1
    peer, local = _labels(sock)
    for i, r in enumerate(_sched_partition()):
        if _matches(r, peer, local, "send"):
            raise ConnectionResetError(
                f"net/partition: send to {peer or local} cut")
    for i, r in enumerate(_sched_delay()):
        if _matches(r, peer, local, "send"):
            time.sleep(float(r.get("ms", 1.0)) / 1000.0)
    # drop/dup default to the send direction only: both endpoints of a
    # link often live in one process (the in-process chaos harness),
    # and a dir="both" loss rule would otherwise count — and drop —
    # the same frame twice, once per side of the wire
    for i, r in enumerate(_sched_drop()):
        if _matches(r, peer, local, "send", "send") and \
                _nth_fires("net/drop", i, int(r.get("nth", 1))):
            return 0
    for i, r in enumerate(_sched_dup()):
        if _matches(r, peer, local, "send", "send") and \
                _nth_fires("net/dup", i, int(r.get("nth", 1))):
            return 2
    return 1


def on_recv(sock, nbytes: int) -> bool:
    """Armed-path receive hook, called with one fully-read frame.
    Returns True to discard it (net/drop on the inbound path — the
    reader loops for the next frame). Raises for net/partition,
    sleeps for net/delay."""
    global WORK
    WORK += 1
    peer, local = _labels(sock)
    for i, r in enumerate(_sched_partition()):
        if _matches(r, peer, local, "recv"):
            raise ConnectionResetError(
                f"net/partition: recv from {peer or local} cut")
    for i, r in enumerate(_sched_delay()):
        if _matches(r, peer, local, "recv"):
            time.sleep(float(r.get("ms", 1.0)) / 1000.0)
    for i, r in enumerate(_sched_drop()):
        if _matches(r, peer, local, "recv", "send") and \
                _nth_fires("net/drop", i, int(r.get("nth", 1))):
            return True
    return False


__all__ = ["KINDS", "ACTIVE", "WORK", "arm", "heal", "reset",
           "on_send", "on_recv"]
