"""Follower-side adapters: storage plumbing over the RPC client.

These present the exact surfaces the shared-directory deployment wires
into Storage — the ordered-KV engine, the mutation-section coordinator,
the owner managers — so the storage/session layers run unchanged on a
server that shares NOTHING with the leader but a socket (reference: a
tidb-server knows TiKV only through the client in store/tikv/; swapping
mockstore for a real cluster is a constructor argument).

Replication model: the leader's WAL is the single bus. A follower
mirrors it by position-based tailing (RemoteKV.refresh), and publishes
its own mutations by appending the records it buffered during the
flock-granted mutation section — flushed BEFORE the lease is released,
under its fencing token, so the next section holder's refresh always
sees them. If the flush is fenced off (lease lost) the buffered
records are REVERTED from the local maps via their undo log: the
follower returns to exactly the replicated state and the statement
fails with a typed, retryable error — never a divergent store."""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..kv.backoff import BO_TXN_LOCK, Backoffer
from ..kv.mvcc import PyOrderedKV
from ..store.coordinator import SharedDirCoordinator
from .client import RpcClient, RpcOptions
from .errors import LeaderUnavailable, ResultUndetermined, RPCError
from .frame import MAX_FRAME


class RemoteKV(PyOrderedKV):
    """In-memory ordered KV mirroring the leader's WAL over RPC.

    Inherits the maps/scan machinery and the record format from the
    pure-python engine; overrides the durability plane: appends buffer
    locally (with an undo log) until the mutation section flushes them
    to the leader, and refresh() tails the leader instead of a file.

    With `mirror_dir` the follower also keeps an on-disk MIRROR of the
    replicated stream — the leader's snapshot.kv byte-for-byte plus
    every tailed/published WAL byte in the same order. The mirror is
    what makes in-place PROMOTION possible: a follower that wins the
    election re-opens its mirror as the authoritative (snapshot, WAL)
    pair, and because every follower's mirror is a byte-prefix of the
    dead leader's file, surviving peers keep tailing from their own
    offsets against the new leader without re-bootstrapping."""

    def __init__(self, client: RpcClient,
                 mirror_dir: Optional[str] = None,
                 sync_log: str = "off",
                 sync_interval_ms: int = 100) -> None:
        super().__init__(path=None, sync_log=sync_log,
                         sync_interval_ms=sync_interval_ms)
        self._client = client
        self._applied_off = 0          # leader-WAL byte position
        self._buf: list[bytes] = []    # records awaiting flush
        self._undo: list = []          # (cf, key, old_value) LIFO
        self._seq = 0                  # client-assigned append sequence
        self.mirror_dir = mirror_dir
        self._mirror_wal = None
        from ..kv.mvcc import SyncPolicy
        self._mirror_sync = SyncPolicy(sync_log, sync_interval_ms,
                                       self._fsync_mirror)
        if mirror_dir is not None:
            import os
            os.makedirs(mirror_dir, exist_ok=True)
            # a stale mirror (earlier join, possibly of a different
            # leader epoch) cannot be trusted to prefix-match the
            # current stream: restart the mirror with the bootstrap
            for name in ("wal.log", "snapshot.kv"):
                try:
                    os.remove(os.path.join(mirror_dir, name))
                except OSError:
                    pass
            self._mirror_wal = open(
                os.path.join(mirror_dir, "wal.log"), "wb")

    # ---- bootstrap / tail --------------------------------------------------
    def bootstrap(self) -> None:
        # the snapshot streams in chunks like the WAL (a store with a
        # long pre-shared life can exceed any single frame); a record
        # split at a chunk boundary carries over as `rem`
        import os
        off, rem = 0, b""
        snap_tmp = None
        if self.mirror_dir is not None:
            snap_tmp = open(
                os.path.join(self.mirror_dir, "snapshot.tmp"), "wb")
        try:
            while True:
                r = self._client.call(
                    "wal_bootstrap", offset=off,
                    _budget_ms=self._client.options.lock_budget_ms)
                data = r.get("snapshot", b"")
                off += len(data)
                if snap_tmp is not None and data:
                    snap_tmp.write(data)
                if rem or data:
                    valid, _ = self._replay_bytes(rem + data, queue=False)
                    rem = (rem + data)[valid:]
                if not r.get("more"):
                    break
            if snap_tmp is not None:
                snap_tmp.flush()
                os.fsync(snap_tmp.fileno())
                snap_tmp.close()
                snap_tmp = None
                if off:
                    from ..kv.mvcc import fsync_dir
                    os.replace(
                        os.path.join(self.mirror_dir, "snapshot.tmp"),
                        os.path.join(self.mirror_dir, "snapshot.kv"))
                    fsync_dir(self.mirror_dir)
                else:
                    os.remove(
                        os.path.join(self.mirror_dir, "snapshot.tmp"))
        finally:
            if snap_tmp is not None:
                snap_tmp.close()
        self._applied_off = 0
        self.refresh()  # the log itself streams via chunked tailing
        self.pending_refresh.clear()  # nothing folded yet; _recover scans

    def _replay_bytes(self, data: bytes, queue: bool = True
                      ) -> tuple[int, int]:
        """Apply the valid record prefix of `data`; returns
        (valid_byte_length, records_applied). A torn tail (leader mid-
        append) is left for the next tail to complete."""
        import struct
        off = n = 0
        ln = len(data)
        while off + 10 <= ln:
            op, cf = data[off], data[off + 1]
            klen, vlen = struct.unpack_from("<II", data, off + 2)
            end = off + 10 + klen + vlen
            if cf >= 3 or op not in (1, 2) or end > ln:
                break
            key = data[off + 10:off + 10 + klen]
            val = data[off + 10 + klen:end]
            if op == 1:
                self._apply_put(cf, key, val)
            else:
                self._apply_delete(cf, key)
            if queue:
                self.pending_refresh.append((op, cf, key, val))
            off = end
            n += 1
        return off, n

    def refresh(self) -> int:
        total = 0
        opts = self._client.options
        # degraded fast path: serve the last replicated state instead of
        # paying the backoff budget per statement; the heartbeat probes
        # recovery and clears the flag (follower-read degrade, the
        # bounded-staleness mode the status port reports)
        if self._client.degraded and opts.stale_reads:
            return 0
        limit = 0  # 0 = server's chunk; grows when a record spans chunks
        while True:
            try:
                r = self._client.call("wal_tail",
                                      offset=self._applied_off,
                                      limit=limit)
            except RPCError:
                if opts.stale_reads:
                    return total
                raise
            data = r.get("data", b"")
            ws = r.get("wal_size")
            if isinstance(ws, int) and ws < self._applied_off:
                # the serving leader holds LESS log than we replicated:
                # a post-failover leader that never saw our tail (the
                # documented loss window). Silently waiting would hang
                # forever; diverged state needs an operator (or a
                # re-join with a fresh working dir).
                raise RPCError(
                    f"replication diverged: this follower is at WAL "
                    f"offset {self._applied_off} but the leader holds "
                    f"only {ws} bytes; re-join with a fresh working "
                    "dir to resync")
            if not data:
                return total
            valid, n = self._replay_bytes(data)
            self._mirror_append(data[:valid])
            self._applied_off += valid
            total += n
            if not r.get("more"):
                # the server reached its file tip; a residual partial
                # record is the leader mid-append — the next tail
                # completes it (valid < len(data) is NOT an error here)
                return total
            # more bytes exist server-side, so a partial record at the
            # chunk edge is a chunking artifact: loop. A record larger
            # than the chunk makes no progress — double the ask.
            if valid == 0 and len(data) >= MAX_FRAME - 4096:
                # the record cannot fit ANY frame: fail typed, never
                # spin (the leader's local append path has no frame cap)
                raise RPCError(
                    f"WAL record at offset {self._applied_off} exceeds "
                    f"the transport frame limit ({MAX_FRAME}); this "
                    "follower cannot mirror the store")
            limit = min(2 * len(data), MAX_FRAME - 4096) \
                if valid == 0 else 0

    def tail_clean(self) -> None:
        pass  # the leader owns the file; its tail hygiene applies

    # ---- on-disk mirror ----------------------------------------------------
    def _fsync_mirror(self) -> None:
        import os
        mw = self._mirror_wal
        if mw is not None and not mw.closed:
            mw.flush()
            os.fsync(mw.fileno())

    def _mirror_append(self, data: bytes) -> None:
        if self._mirror_wal is None or not data:
            return
        self._mirror_wal.write(data)
        self._mirror_wal.flush()
        self._mirror_sync.mark_dirty()
        # mirror durability is promotion-quality, not the ack path
        # (the leader's fsync is) — a failed mirror fsync must not
        # fail replication
        try:
            self._mirror_sync.boundary()
        except OSError:
            pass

    def close(self) -> None:
        self._mirror_sync.close()
        super().close()
        if self._mirror_wal is not None:
            try:
                self._mirror_wal.close()
            except OSError:
                pass
            self._mirror_wal = None

    # ---- buffered append with undo -----------------------------------------
    def _log(self, op: int, cf: int, key: bytes, value: bytes) -> None:
        import struct
        self._undo.append((cf, key, self._maps[cf].get(key)))
        self._buf.append(struct.pack("<BBII", op, cf, len(key),
                                     len(value)) + key + value)

    def flush_section(self, token: Optional[int]) -> None:
        """Publish the section's records to the leader WAL; called by
        the coordinator while the mutation lease is still held. Any
        failure reverts the local application wholesale."""
        if not self._buf:
            return
        data = b"".join(self._buf)
        if len(data) + 4096 > MAX_FRAME:
            # fail typed BEFORE the wire: a frame this large would be
            # rejected locally by send_frame, and retrying it under
            # BO_RPC would burn the budget into a misleading
            # ResultUndetermined for a deterministic local condition
            self._revert()
            raise RPCError(
                f"transaction publishes {len(data)} bytes in one "
                f"mutation section, over the transport frame limit "
                f"({MAX_FRAME}); split the statement or commit in "
                "smaller transactions")
        self._seq += 1
        try:
            r = self._client.call("wal_append", seq=self._seq,
                                  expected=self._applied_off, data=data,
                                  token=token or 0,
                                  term=self._client.term)
        except LeaderUnavailable as e:
            # the request may have landed before the leader went dark:
            # the outcome is UNKNOWN, not failed (reference:
            # ErrResultUndetermined). Locally we revert to the last
            # replicated state; if the append did land, the next tail
            # re-applies it — either way the store never diverges.
            self._revert()
            raise ResultUndetermined(
                f"wal publish outcome unknown: {e}") from None
        except BaseException:
            # typed rejections (stale lease, offset fence) and local
            # faults: the leader definitively did NOT apply the records
            self._revert()
            raise
        # the leader wrote our records at exactly `expected` (the offset
        # fence guarantees it), so the mirror appends the same bytes at
        # the same position — prefix equality with the leader's file is
        # preserved through our own publishes
        self._mirror_append(data)
        self._applied_off = int(r["offset"])
        self._buf, self._undo = [], []

    def _revert(self) -> None:
        for cf, key, old in reversed(self._undo):
            if old is None:
                self._apply_delete(cf, key)
            else:
                self._apply_put(cf, key, old)
        self._buf, self._undo = [], []


class RemoteCoordinator:
    """The SharedDirCoordinator surface over RPC: the mutation critical
    section becomes a leader-granted lease on the same store.lock flock,
    and the kill mailbox/process registry become calls."""

    def __init__(self, client: RpcClient,
                 options: Optional[RpcOptions] = None) -> None:
        self.client = client
        self.options = options or client.options
        self.engine: Optional[RemoteKV] = None  # wired by Storage
        self._tlock = threading.RLock()
        self._depth = 0
        self._token: Optional[int] = None
        self._kill_seq = 0
        self.node_id = int(client.call("node_claim")["node_id"])

    # ---- mutation critical section ----------------------------------------
    def acquire(self) -> None:
        self._tlock.acquire()
        self._depth += 1
        if self._depth > 1:
            return
        try:
            if self.client.degraded:
                raise LeaderUnavailable(
                    "store leader unreachable: this server is serving "
                    "reads only (writes need the mutation lease)")
            bo = Backoffer(budget_ms=self.options.lock_budget_ms)
            while True:
                r = self.client.call("lock_acquire", name="mutation",
                                     term=self.client.term)
                if r.get("granted"):
                    self._token = int(r["token"])
                    return
                bo.sleep(BO_TXN_LOCK)
        except BaseException:
            self._depth -= 1
            self._tlock.release()
            raise

    def release(self) -> None:
        self._depth -= 1
        try:
            if self._depth == 0:
                token, self._token = self._token, None
                try:
                    if self.engine is not None:
                        self.engine.flush_section(token)
                finally:
                    try:
                        self.client.call("lock_release", name="mutation",
                                         token=token or 0, _budget_ms=500)
                    except RPCError:
                        pass  # the lease reaper will reclaim it
        finally:
            # a flush failure must surface typed, never with the RLock
            # still held — that would hang every later writer
            self._tlock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # ---- registry / kill mailbox -------------------------------------------
    global_conn_id = staticmethod(SharedDirCoordinator.global_conn_id)
    split_conn_id = staticmethod(SharedDirCoordinator.split_conn_id)

    def register_server(self, port: int, status_port) -> None:
        self.client.call("node_register", node_id=self.node_id,
                         port=port, status_port=status_port)

    def servers(self) -> dict:
        return self.client.call("servers").get("servers", {})

    def post_kill(self, conn_id: int, query_only: bool) -> None:
        self.client.call("kill_post", conn_id=conn_id,
                         query_only=query_only)

    def poll_kills(self) -> list[tuple[int, bool]]:
        # the poll consumes the mailbox server-side, so a retry of the
        # SAME poll must replay the consumed result, not drain an empty
        # box — the seq gives the server that dedup key (same contract
        # as wal_append). Advance only on success: a poll that died
        # after the server drained the box is replayed by the next one.
        seq = self._kill_seq + 1
        try:
            r = self.client.call("kill_poll", node_id=self.node_id,
                                 seq=seq, _budget_ms=500)
        except RPCError:
            return []  # mailbox polling must never kill the poller
        self._kill_seq = seq
        return [(int(local), bool(qo)) for local, qo in r.get("kills", [])]


class RemoteOwnerManager:
    """Owner election over a leader-granted lease (reference:
    owner/manager.go etcd campaign; the flock manager's shape kept so
    storage wiring is a one-line swap). A lost leader surfaces as a
    failed campaign — DDL fails typed instead of running unfenced."""

    def __init__(self, client: RpcClient, key: str = "ddl") -> None:
        self.client = client
        self.key = key
        self._thread_lock = threading.RLock()
        self._token: Optional[int] = None

    def try_campaign(self) -> bool:
        if not self._thread_lock.acquire(blocking=False):
            return False
        try:
            r = self.client.call("lock_acquire", name=self.key,
                                 _budget_ms=1000)
        except RPCError:
            self._thread_lock.release()
            if self.client.degraded:
                raise
            return False
        if r.get("granted"):
            self._token = int(r["token"])
            return True
        self._thread_lock.release()
        return False

    def campaign(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while True:
            if self.try_campaign():
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def resign(self) -> None:
        token, self._token = self._token, None
        try:
            self.client.call("lock_release", name=self.key,
                             token=token or 0, _budget_ms=500)
        except RPCError:
            pass
        try:
            self._thread_lock.release()
        except RuntimeError:
            pass

    def close(self) -> None:
        pass

    def __enter__(self):
        if not self.campaign():
            raise LeaderUnavailable(
                f"could not become {self.key} owner (store leader "
                "unreachable or lease held)")
        return self

    def __exit__(self, *exc) -> None:
        self.resign()


__all__ = ["RemoteKV", "RemoteCoordinator", "RemoteOwnerManager"]
