"""Cluster diagnostics plane: per-server diag endpoints + RPC fan-out.

Counterpart of the reference's cluster memtables (reference: TiDB 4.0
infoschema/cluster.go + executor/memtable_reader.go — every server
exposes its processlist/slow-log/metrics over its status port, and the
`information_schema.cluster_*` tables fan out to all members listed in
PD's registry). Here:

* DiagService  — answers diag queries from THIS server's live state
  (processlist provider, slow-query ring, statement digests, metrics
  registries, build/config info).
* DiagListener — a minimal frame-protocol server every follower runs so
  peers can reach its DiagService; the leader needs none (its
  CoordRPCServer dispatches diag_* to the same service).
* cluster_members / cluster_rows — membership enumeration (the leader's
  registry, fed by diag_register + heartbeat pings) and the fan-out
  that materializes the cluster_* memtables: one sub-request per live
  member under the normal BO_RPC budget, an unreachable peer degrading
  to an error row + session warning, never a failed query.

Failpoint sites at the fan-out edge (armed by tests/test_cluster_obs.py):
  diag/peer-down  — the peer call fails immediately (dead-peer path)
  diag/slow-peer  — latency injection ahead of the peer call

Trust model: the diag endpoints answer unauthenticated, the SAME model
as the coordination port they extend (which already streams the whole
WAL) and the HTTP status port (which already serves slow-query SQL
text) — the transport plane assumes a trusted network segment; bind
diag-listen/transport.listen accordingly.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Any, Optional

from .. import obs
from ..util import failpoint
from .errors import RPCError, traced_response, wire_error
from .frame import get_trace_ctx
from .server import FrameListener

# cluster table -> diag RPC method serving its per-server rows
TABLE_METHODS = {
    "cluster_info": "diag_info",
    "cluster_processlist": "diag_processlist",
    "cluster_slow_query": "diag_slow_query",
    "cluster_statements_summary": "diag_statements",
    "cluster_load": "diag_load",
    "cluster_top_sql": "diag_top_sql",
    "cluster_mesh_shards": "diag_mesh_shards",
    "cluster_mesh_storage": "diag_mesh_storage",
    "cluster_inspection_result": "diag_inspection",
    "cluster_statements_summary_history": "diag_history",
    "cluster_plan_history": "diag_plan_history",
    "cluster_tidb_wait_profile": "diag_wait_profile",
    "cluster_hot_ranges": "diag_hot_ranges",
}


class DiagService:
    """One server's diagnostics, in wire-encodable form. Every method
    returns {"rows": [...]} shaped exactly like the matching cluster_*
    table minus the (instance, error) columns the fan-out adds."""

    def __init__(self, storage) -> None:
        self.storage = storage

    def _role(self) -> str:
        if getattr(self.storage, "remote", False):
            return "follower"
        if getattr(self.storage, "rpc_server", None) is not None:
            return "leader"
        return "shared" if getattr(self.storage, "shared", False) \
            else "local"

    def diag_info(self) -> dict:
        from ..server.conn import SERVER_VERSION
        started = getattr(self.storage, "_start_time", 0.0)
        coord = getattr(self.storage, "coord", None)
        rows = [[
            self._role(),
            int(getattr(coord, "node_id", 0) or 0),
            SERVER_VERSION,
            os.getpid(),
            time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(started))
            if started else "",
            round(time.time() - started, 3) if started else 0.0,
            *self._replica_cols(),
            None, None, None, None, None, None, None, None,
        ]]
        # one type='range' row per range whose write leadership this
        # member currently holds ([ranges] disabled adds nothing);
        # the trailing four are the keyspace heat plane's lifetime
        # traffic columns (zeros while [heatmap] is disabled)
        plane = getattr(self.storage, "ranges", None)
        if plane is not None:
            for d in plane.server.describe():
                rows.append(["range", None, None, None, None, None,
                             None, None, None,
                             int(d["range_id"]), str(d["leader"]),
                             int(d["term"]), int(d["closed_ts"]),
                             int(d.get("read_rows", 0)),
                             int(d.get("read_bytes", 0)),
                             int(d.get("write_rows", 0)),
                             int(d.get("write_bytes", 0))])
        return {"rows": rows}

    def _replica_cols(self) -> list:
        """The follower-read-tier columns of cluster_info: this
        server's applied/closed ts, apply lag, and whether it serves
        routed replica reads. A leader's 'applied' point is the newest
        issued timestamp (it serves every read, but not as a replica —
        serving stays 0)."""
        st = self.storage
        eng = getattr(st, "apply_engine", None)
        if eng is not None:
            # the SAME serving condition the heartbeat advertises
            # (enabled AND synced at least once) — the two surfaces an
            # operator compares must never contradict each other
            return [int(eng.applied_ts), round(eng.lag_ms(), 1),
                    1 if (st.replica_read.enabled
                          and eng.applied_ts > 0) else 0]
        tso = getattr(st, "tso", None)
        cur = int(tso.current()) if tso is not None else 0
        return [cur, 0.0, 0]

    def diag_replica_read(self, sql: str = "", db: str = "",
                          read_ts: int = 0, term: int = 0,
                          time_zone: str = "SYSTEM") -> dict:
        """A routed snapshot SELECT served from this follower's local
        engine at exactly read_ts (rpc/replica.py serve_replica_read:
        term fence, bounded closed-ts wait, SELECT-only)."""
        from .replica import serve_replica_read
        return serve_replica_read(self.storage, sql=sql, db=db,
                                  read_ts=read_ts, term=term,
                                  time_zone=time_zone)

    def diag_processlist(self) -> dict:
        provider = getattr(self.storage, "processlist", None)
        rows = []
        for r in (provider() if provider is not None else []):
            rows.append([int(r[0]), str(r[1] or ""), str(r[2] or ""),
                         str(r[3] or ""), str(r[4] or ""), int(r[5]),
                         str(r[6] or ""),
                         None if r[7] is None else str(r[7])])
        return {"rows": rows}

    def diag_slow_query(self) -> dict:
        rows = []
        for e in self.storage.obs.slow_queries():
            rows.append([e["ts"], e["db"], float(e["duration_ms"]),
                         e["sql"], e.get("plan_digest", ""),
                         obs.fmt_stages_ms(e.get("stages")),
                         int(e.get("mem_max", 0)),
                         int(e.get("spill_count", 0)),
                         obs.fmt_ops_ms(e.get("operators")),
                         float(e.get("mesh_skew", 0.0)),
                         obs.fmt_waits_ms(e.get("waits"))])
        return {"rows": rows}

    def diag_top_sql(self) -> dict:
        """This server's Top SQL attribution windows, row-shaped for
        information_schema.tidb_top_sql (the cluster_top_sql fan-out
        adds instance/error). Empty while topsql is disabled."""
        return {"rows": self.storage.obs.topsql.table_rows()}

    def diag_wait_profile(self) -> dict:
        """This server's typed wait-state attribution windows,
        row-shaped for information_schema.tidb_wait_profile. Empty
        while performance.wait-profile-enabled is false."""
        return {"rows": self.storage.obs.waitprofile.table_rows()}

    def diag_hot_ranges(self) -> dict:
        """This server's keyspace heat matrix, row-shaped for
        information_schema.tidb_hot_ranges (the cluster_hot_ranges
        fan-out adds instance/error). Empty — with zero recorder
        work — while [heatmap] is disabled."""
        return {"rows": self.storage.heat.table_rows()}

    def diag_mesh_shards(self) -> dict:
        """This server's mesh flight-recorder dispatch ring (empty
        while the mesh plane is inactive). Reads the EXISTING client —
        a diag scrape never builds a mesh or grabs a backend."""
        from ..copr import mesh as _mesh
        return {"rows": _mesh.shard_rows(self.storage)}

    def diag_mesh_storage(self) -> dict:
        """This server's per-device HBM provenance ledger."""
        from ..copr import mesh as _mesh
        return {"rows": _mesh.storage_rows(self.storage)}

    def diag_events(self) -> dict:
        """The structured server event ring, newest last."""
        rows = []
        for e in self.storage.obs.events.snapshot():
            rows.append([int(e["id"]), e["ts"], e["kind"], e["severity"],
                         int(e["conn_id"]), e["digest"], e["detail"]])
        return {"rows": rows}

    def diag_history(self) -> dict:
        """This server's workload-history windows (durable records +
        the live window), row-shaped for statements_summary_history.
        Empty — with zero work — while history.enabled is false."""
        h = self.storage.history
        return {"rows": h.table_rows() if h.enabled else []}

    def diag_plan_history(self) -> dict:
        """Per-(digest, plan) rollup of this server's retained
        history, row-shaped for tidb_plan_history."""
        h = self.storage.history
        return {"rows": h.plan_rows() if h.enabled else []}

    def diag_inspection(self) -> dict:
        """This server's inspection findings: every registered rule of
        the obs_inspect engine evaluated over one telemetry snapshot.
        Empty — with ZERO rule work — while diagnostics.enabled is
        false (obs_inspect.result_rows short-circuits)."""
        from .. import obs_inspect
        return {"rows": obs_inspect.result_rows(self.storage)}

    def diag_statements(self) -> dict:
        rows = []
        for e in self.storage.obs.statements.snapshot():
            rows.append([
                e["digest"], e["schema_name"], e["digest_text"],
                e["sample_text"], e["exec_count"], e["errors"],
                round(e["sum_latency_ms"], 3),
                round(e["max_latency_ms"], 3), e["sum_rows"],
                e["last_seen"]])
        return {"rows": rows}

    def diag_load(self) -> dict:
        """Current gauge/counter values — the device/host telemetry the
        cluster_load table correlates with bench regressions."""
        obs.run_gauge_probes()
        rows = []
        for reg in (self.storage.obs.metrics, obs.PROCESS_METRICS):
            for name, v in reg.flat_samples():
                dev = name.startswith(("tidb_device_", "tidb_jit_",
                                       "tidb_copr_", "tidb_mesh_"))
                rows.append(["device" if dev else "host", name,
                             float(v)])
        return {"rows": rows}

    def diag_election(self) -> dict:
        """This server's candidacy state for leader elections (polled by
        peers' FailoverManagers over the diag port): node id, replicated
        WAL position, known term, and — once anyone has promoted or
        repointed — where the CURRENT leader answers coordination RPC.
        Leaders answer too, so a partitioned follower that regains this
        endpoint immediately learns who rules."""
        st = self.storage
        rpc_server = getattr(st, "rpc_server", None)
        if rpc_server is not None:
            return {"node_id": int(getattr(st.coord, "node_id", 0) or 0),
                    "wal_pos": rpc_server._wal_size(),
                    "term": rpc_server.term,
                    "role": "leader",
                    "leader_addr": rpc_server.address}
        if getattr(st, "_promoting", False):
            # mid-promotion: neither follower nor leader yet. Voters
            # must HOLD their election open — treating this window as
            # "not an elector" elected a second leader (split brain)
            return {"node_id": int(getattr(st.coord, "node_id", 0) or 0),
                    "wal_pos": 0, "term": 0,
                    "role": "promoting", "leader_addr": ""}
        client = getattr(st, "_rpc_client", None)
        engine = getattr(st.kv, "kv", None)
        return {"node_id": int(getattr(st.coord, "node_id", 0) or 0),
                "wal_pos": int(getattr(engine, "_applied_off", 0)),
                "term": int(getattr(client, "term", 0) or 0),
                "role": self._role(),
                "leader_addr": str(getattr(client, "addr", "") or "")
                if client is not None and not client.degraded else ""}

    def handle(self, method: str, **params) -> dict:
        fn = getattr(self, method, None)
        if fn is None or not method.startswith("diag_"):
            raise RPCError(f"unknown diag method {method}")
        return fn(**params) if params else fn()


class DiagListener(FrameListener):
    """Minimal frame-protocol listener serving ONE service: this
    server's DiagService. Followers run it (registered with the leader
    at hello/heartbeat time) so any peer can pull their diagnostics.
    The socket machinery — accept/serve loops, oversized-response
    guard, accept-waking teardown — is the shared FrameListener core
    CoordRPCServer also runs on; there is no lease state here."""

    _thread_prefix = "titpu-diag"

    def __init__(self, storage, listen: str = "127.0.0.1:0") -> None:
        self.service = DiagService(storage)
        fam, target = self._start_listener(listen, backlog=16)
        if fam == socket.AF_INET:
            host = self._listener.getsockname()[0]
            if host in ("0.0.0.0", "::", ""):
                # the bound address is what gets REGISTERED with the
                # leader and dialed by every peer — a wildcard would
                # hand them an unconnectable 0.0.0.0 (each peer's own
                # loopback); fail loudly at startup instead
                self._close_listener()
                raise ValueError(
                    f"diag-listen {listen!r} binds a wildcard address; "
                    "peers must be handed a routable host (e.g. "
                    "\"10.0.0.5:0\")")
            self.address = f"{host}:{self.port}"
        else:
            self.address = f"unix:{target}"

    def _dispatch(self, req: Any) -> dict:
        if not isinstance(req, dict) or "m" not in req:
            return wire_error(None, RPCError("bad request"))
        rid = req.get("id")
        method = str(req.get("m"))
        params = req.get("p") if isinstance(req.get("p"), dict) else {}
        return traced_response(
            rid, method,
            lambda: self.service.handle(method, **params),
            get_trace_ctx(req))

    def close(self) -> None:
        self._close_listener()


# ---- membership + fan-out ---------------------------------------------------

def cluster_members(storage, budget_ms: int = 1000) -> list[dict]:
    """Live members as {id, addr, role, hb_age_s}. The leader reads its
    own registry; a follower asks the leader — and when the leader is
    unreachable, the leader stays listed with a `down` marker so its
    absence surfaces as an error row + warning rather than a silently
    shrunken cluster. Local/shared-dir stores are single-member."""
    rpc_server = getattr(storage, "rpc_server", None)
    if rpc_server is not None:
        return rpc_server.members()
    if getattr(storage, "remote", False):
        own = {"id": int(getattr(storage.coord, "node_id", 0) or 0),
               "addr": storage.diag_address, "role": "follower",
               "hb_age_s": 0.0}
        client = storage._rpc_client
        cached = storage._last_members
        age = time.monotonic() - storage._last_members_ts
        if cached and not client.degraded \
                and age < client.options.lease_ms / 1000.0:
            # fresh-enough registry view: /status scrapes and repeated
            # cluster_* reads must not add a leader round-trip (and a
            # turn on the shared coordination client's mutex) per call;
            # staleness is bounded by the lease, the heartbeat cadence
            return [dict(m) for m in cached]
        if not (client.degraded and cached):
            try:
                r = client.call("members", _budget_ms=budget_ms)
                members = [m for m in r.get("members", [])
                           if isinstance(m, dict)]
                for m in members:
                    if m.get("role") == "leader":
                        # the leader self-advertises its bound host,
                        # which under a wildcard bind is loopback;
                        # substitute the address THIS follower provably
                        # reaches it at (its transport.remote target)
                        m["addr"] = str(client.addr)
                if not any(m.get("addr") == own["addr"]
                           for m in members):
                    members.append(own)  # not registered yet
                storage._last_members = members
                storage._last_members_ts = time.monotonic()
                return members
            except RPCError as e:
                down = f"{type(e).__name__}: {e}"[:250]
        else:
            # heartbeat already knows the leader is gone: serve the
            # cached shape without paying another backoff budget (the
            # /status scrape path calls this on every poll)
            down = "leader unreachable (degraded)"
        # leader unreachable: fall back to the last registry view so
        # the OTHER followers stay visible (live ones answer their
        # diag ports directly; the leader degrades to an error row
        # instead of the cluster silently shrinking to one server)
        cached = storage._last_members
        if cached:
            out = []
            for m in cached:
                m = dict(m)
                if m.get("role") == "leader":
                    m["down"] = down
                out.append(m)
            return out
        return [own, {"id": 0, "addr": str(client.addr),
                      "role": "leader", "hb_age_s": None, "down": down}]
    return [{"id": 0, "addr": "", "role": "local", "hb_age_s": 0.0}]


def _peer_client(storage, addr: str):
    """Cached non-heartbeating RpcClient per peer diag address (cache
    and lock live on the Storage, initialized in its __init__ so two
    first-queries cannot race the setup)."""
    from .client import RpcClient, RpcOptions
    with storage._diag_clients_lock:
        c = storage._diag_clients.get(addr)
        if c is None:
            opts = storage._rpc_options or RpcOptions()
            c = storage._diag_clients[addr] = RpcClient(
                addr, opts, _heartbeat=False)
        return c


def close_peer_clients(storage) -> None:
    with storage._diag_clients_lock:
        clients, storage._diag_clients = storage._diag_clients, {}
    for c in clients.values():
        c.close()


def _call_member(storage, member: dict, method: str) -> dict:
    """One member's diag payload: local members answer in-process, remote
    ones over their diag endpoint under the BO_RPC budget. The failpoint
    sites live HERE, on the remote edge, so chaos lands on the fan-out
    and not on the local rows."""
    down = member.get("down")
    if down:
        # already known unreachable (e.g. the leader, discovered during
        # membership): surface the error row without burning another
        # backoff budget against a dead endpoint
        raise RPCError(str(down))
    addr = str(member.get("addr") or "")
    if not addr or addr == storage.diag_address:
        return storage.diag.handle(method)
    if failpoint.inject("diag/peer-down"):
        raise RPCError(f"failpoint diag/peer-down: peer {addr}")
    d = failpoint.inject("diag/slow-peer")
    if isinstance(d, (int, float)) and not isinstance(d, bool) and d > 0:
        time.sleep(float(d))
    client = _peer_client(storage, addr)
    if client.breaker_state == "open":
        # the peer already burned breaker-threshold budgets: degrade to
        # the error row NOW instead of rediscovering the dead endpoint
        # (and paying another Backoffer budget) on every fan-out; the
        # half-open probe after the cooldown re-admits it
        raise RPCError(
            f"peer {addr}: rpc circuit breaker open (failing fast)")
    # capped below the transport budget: cluster_processlist fans out
    # while holding the viewer-sensitive infoschema lock, and a dead
    # peer must not push the hold time toward that lock's 10s acquire
    # timeout (siblings would see 'information_schema busy')
    budget = min(client.options.backoff_budget_ms, 2000)
    return client.call(method, _budget_ms=budget)


def cluster_rows(storage, tname: str, ncols: int,
                 viewer=None) -> list[list]:
    """Materialize one cluster_* table: fan out to every member, tag
    rows with the member's instance address, and degrade an unreachable
    peer to [instance, NULL..., error] plus a session warning.

    Members are queried in PARALLEL (reference: memtable_reader.go
    issues its per-store requests concurrently), so N dead peers cost
    one capped budget of wall time, not N — which also bounds how long
    cluster_processlist holds the viewer-sensitive infoschema lock.
    Under an active TRACE each worker runs beneath its own child
    collector and the caller grafts the subtrees back (the span stack
    is thread-local), so the stitched tree matches sequential hops."""
    method = TABLE_METHODS[tname]
    members = cluster_members(storage)
    results: list = [None] * len(members)
    parent = obs.active_collector()
    into = parent._stack[-1] if parent is not None else None
    child_colls: list = [None] * len(members)

    def fetch(i: int, member: dict, use_child: bool) -> None:
        try:
            if use_child:
                # worker thread: its own collector (the caller's span
                # stack is thread-local), grafted back after the join;
                # it inherits the statement's trace_id so the peer's
                # spans stay attributable to ONE Dapper trace
                with obs.SpanCollector("diag.fanout") as child:
                    child.trace_id = parent.trace_id
                    child_colls[i] = child
                    results[i] = (_call_member(storage, member, method),
                                  None)
            else:
                # caller thread: the active collector (if any) is
                # already in TLS — spans open directly on it
                results[i] = (_call_member(storage, member, method),
                              None)
        except Exception as e:  # noqa: BLE001 — ANY per-member failure
            # (typed transport error, malformed peer payload, handler
            # bug) must degrade to an error row, never fail the query
            results[i] = (None, f"{type(e).__name__}: {e}"[:250])

    if len(members) <= 1:
        for i, member in enumerate(members):
            fetch(i, member, use_child=False)
    else:
        threads = [threading.Thread(target=fetch,
                                    args=(i, m, parent is not None),
                                    name="titpu-diag-fanout",
                                    daemon=True)
                   for i, m in enumerate(members)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if parent is not None:
        for child in child_colls:
            if child is not None:
                obs.graft_collector(parent, into, child)

    # evict cached clients for addresses that left the membership —
    # follower restarts bind fresh ephemeral ports, and without this a
    # long-lived server accretes one dead client per churned address
    addrs = {str(m.get("addr") or "") for m in members}
    with storage._diag_clients_lock:
        stale = [a for a in storage._diag_clients if a not in addrs]
        dropped = [storage._diag_clients.pop(a) for a in stale]
    for c in dropped:
        c.close()

    out: list[list] = []
    for member, (payload, err) in zip(members, results):
        inst = str(member.get("addr") or member.get("role") or "local")
        if err is not None:
            out.append([inst] + [None] * (ncols - 2) + [err])
            if viewer is not None and hasattr(viewer, "add_warning"):
                viewer.add_warning(
                    f"cluster diagnostics: member {inst} unreachable "
                    f"({err})")
            continue
        for r in payload.get("rows", []):
            out.append([inst] + list(r) + [None])
    if tname == "cluster_processlist" and viewer is not None \
            and viewer.user is not None \
            and not storage.privileges.check(
                viewer.user, "PROCESS", "*", "*",
                roles=viewer.active_roles):
        # without PROCESS only your own connections are visible (the
        # rule the per-server processlist table already applies);
        # error rows (user column NULL, error set) stay visible
        out = [r for r in out
               if r[2] == viewer.user or r[-1] is not None]
    return out


__all__ = ["DiagService", "DiagListener", "TABLE_METHODS",
           "cluster_members", "cluster_rows", "close_peer_clients"]
