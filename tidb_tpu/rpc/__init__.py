"""Socket RPC tier for the multi-process plane.

Counterpart of the reference's TiKV client stack (reference:
store/tikv/client.go sendRequest over gRPC, client_batch.go request
batching/recycling, region_request.go typed retry against region and
transport errors). The shared-directory deployment keeps working as the
fast local mode; this package carries the same three coordination
services — TSO allocation, WAL append/tail, KILL mailbox — over a
length-prefixed-frame protocol on TCP or unix sockets, so a second
tidb_tpu server can join a cluster WITHOUT sharing a disk.

Layers:

* frame.py  — wire format: u32 length-prefixed frames carrying a
  tagged binary encoding (None/bool/int/bytes/str/list/dict).
* errors.py — the typed error surface (all CodedError subclasses, so
  exhaustion/lease-loss reach MySQL clients with real errnos).
* server.py — CoordRPCServer: embedded in the store-owning process,
  granting leases/locks via the SAME flocks the shared-dir mode uses
  (local and remote mutators stay mutually exclusive).
* client.py — RpcClient: per-request Backoffer (BO_RPC), connect/read
  timeouts, transparent reconnect, failpoint sites at every edge.
* remote.py — the follower-side adapters (RemoteKV, RemoteCoordinator,
  RemoteOwnerManager) that plug the client into storage unchanged.
* diag.py   — per-server diagnostics endpoints + the cluster_* fan-out.
* failover.py — leader-loss detection, deterministic election, in-place
  promotion / repoint.
* apply.py  — the follower read tier's apply engine: continuous mirror
  fold + the closed-timestamp protocol (applied_ts on every heartbeat).
* replica.py — snapshot-consistent replica routing: eligible SELECTs
  route to the least-loaded serving replica whose closed ts covers the
  statement's read_ts, with typed fallback to the leader.
"""
