"""Length-prefixed frames + tagged binary payload encoding.

The wire format of the coordination RPC tier: every message is one
frame — a little-endian u32 byte length followed by that many payload
bytes (the gRPC message framing of the reference collapsed to its
essentials; reference: store/tikv/client.go streams delimited
protobufs). The payload is a self-describing tagged encoding rather
than pickle: the server must never execute a peer's bytes, and WAL
records are raw byte strings that JSON would force through base64.

Supported values: None, bool, int (arbitrary precision — timestamps are
physical_ms<<18), bytes, str, list, dict (any supported value as key).
"""

from __future__ import annotations

import socket
import struct
from typing import Any

# one frame must hold a WAL tail chunk; cap well above TAIL_CHUNK but
# low enough that a corrupt length prefix cannot balloon memory
MAX_FRAME = 64 << 20


class FrameError(Exception):
    """Malformed frame or payload (protocol violation, torn stream)."""


# ---- value encoding --------------------------------------------------------
def _enc(v: Any, out: list) -> None:
    if v is None:
        out.append(b"N")
    elif v is True:
        out.append(b"T")
    elif v is False:
        out.append(b"F")
    elif isinstance(v, int):
        b = v.to_bytes((v.bit_length() + 8) // 8 or 1, "little",
                       signed=True)
        out.append(b"I" + bytes([len(b)]) + b)
    elif isinstance(v, float):
        out.append(b"f" + struct.pack("<d", v))
    elif isinstance(v, (bytes, bytearray, memoryview)):
        v = bytes(v)
        out.append(b"B" + struct.pack("<I", len(v)) + v)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(b"S" + struct.pack("<I", len(b)) + b)
    elif isinstance(v, (list, tuple)):
        out.append(b"L" + struct.pack("<I", len(v)))
        for item in v:
            _enc(item, out)
    elif isinstance(v, dict):
        out.append(b"D" + struct.pack("<I", len(v)))
        for k, val in v.items():
            _enc(k, out)
            _enc(val, out)
    else:
        raise FrameError(f"unencodable value type {type(v).__name__}")


def _dec(buf: bytes, off: int) -> tuple[Any, int]:
    tag = buf[off:off + 1]
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"I":
        n = buf[off]
        off += 1
        return int.from_bytes(buf[off:off + n], "little", signed=True), \
            off + n
    if tag == b"f":
        return struct.unpack_from("<d", buf, off)[0], off + 8
    if tag == b"B":
        n = struct.unpack_from("<I", buf, off)[0]
        off += 4
        return buf[off:off + n], off + n
    if tag == b"S":
        n = struct.unpack_from("<I", buf, off)[0]
        off += 4
        return buf[off:off + n].decode("utf-8"), off + n
    if tag == b"L":
        n = struct.unpack_from("<I", buf, off)[0]
        off += 4
        items = []
        for _ in range(n):
            item, off = _dec(buf, off)
            items.append(item)
        return items, off
    if tag == b"D":
        n = struct.unpack_from("<I", buf, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            k, off = _dec(buf, off)
            v, off = _dec(buf, off)
            d[k] = v
        return d, off
    raise FrameError(f"bad tag {tag!r} at offset {off - 1}")


def encode(v: Any) -> bytes:
    out: list = []
    _enc(v, out)
    return b"".join(out)


def decode(buf: bytes) -> Any:
    try:
        v, off = _dec(buf, 0)
    except (IndexError, struct.error) as e:
        raise FrameError(f"truncated payload: {e}") from None
    if off != len(buf):
        raise FrameError(f"{len(buf) - off} trailing bytes in payload")
    return v


# ---- framing ---------------------------------------------------------------
# The network fault plane (rpc/netfault.py) hooks exactly here: every
# framed byte in the system crosses these two functions, so per-peer
# delay/drop/dup/partition schedules need no other seam. Unarmed cost
# is one module-attribute read per operation (netfault.ACTIVE).
from . import netfault  # noqa: E402 — after the codec it instruments


def send_frame(sock: socket.socket, payload: bytes) -> None:
    if len(payload) > MAX_FRAME:
        raise FrameError(f"frame too large: {len(payload)}")
    data = struct.pack("<I", len(payload)) + payload
    if netfault.ACTIVE:
        copies = netfault.on_send(sock, len(data))
        for _ in range(copies):  # 0 = net/drop, 2 = net/dup
            sock.sendall(data)
        return
    sock.sendall(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> bytes:
    """One payload; raises ConnectionError on clean EOF between frames
    too — callers treat any tear identically (reconnect + retry)."""
    while True:
        hdr = _recv_exact(sock, 4)
        n = struct.unpack("<I", hdr)[0]
        if n > MAX_FRAME:
            raise FrameError(f"frame length {n} exceeds cap")
        payload = _recv_exact(sock, n)
        if netfault.ACTIVE and netfault.on_recv(sock, n):
            continue  # net/drop on the inbound path: frame vanishes
        return payload


# ---- trace context ---------------------------------------------------------
# Dapper-style propagation (Sigelman et al., 2010): a request issued
# under an active TRACE carries this key so the remote side can run its
# handler under a SpanCollector and return its span rows for stitching.
TRACE_KEY = "tc"


def make_trace_ctx(trace_id: str, parent_span_id: int) -> dict:
    return {"trace_id": str(trace_id),
            "parent_span_id": int(parent_span_id)}


def get_trace_ctx(req) -> Any:
    """The request's trace context, or None when absent/malformed."""
    if not isinstance(req, dict):
        return None
    tc = req.get(TRACE_KEY)
    if isinstance(tc, dict) and tc.get("trace_id"):
        return tc
    return None


# ---- range routing context --------------------------------------------------
# Every range-addressed request carries this key: the client's cached
# view of (range id, routing-table epoch, leadership term). The server
# gates on it BEFORE touching data — a mismatch answers typed
# (EpochNotMatchError / NotLeaderError / StaleTermError) so stale
# routing can never produce a silently wrong result (reference: the
# kvrpcpb.Context every TiKV request carries — region_id, region_epoch,
# peer — checked by raftstore before proposing).
RANGE_KEY = "rc"


def make_range_ctx(range_id: int, epoch: int, term: int) -> dict:
    return {"range_id": int(range_id), "epoch": int(epoch),
            "term": int(term)}


def get_range_ctx(params) -> Any:
    """The request's range context, or None when absent/malformed."""
    if not isinstance(params, dict):
        return None
    rc = params.get(RANGE_KEY)
    if isinstance(rc, dict) and "range_id" in rc:
        return rc
    return None


# ---- addresses -------------------------------------------------------------
def parse_addr(addr) -> tuple[int, Any]:
    """'host:port' / ('host', port) -> AF_INET; 'unix:/path' or a bare
    path containing '/' -> AF_UNIX."""
    if isinstance(addr, (tuple, list)):
        return socket.AF_INET, (addr[0], int(addr[1]))
    addr = str(addr)
    if addr.startswith("unix:"):
        return socket.AF_UNIX, addr[5:]
    if ":" not in addr and "/" in addr:
        return socket.AF_UNIX, addr
    host, _, port = addr.rpartition(":")
    return socket.AF_INET, (host or "127.0.0.1", int(port))


__all__ = ["FrameError", "encode", "decode", "send_frame", "recv_frame",
           "parse_addr", "MAX_FRAME", "TRACE_KEY", "make_trace_ctx",
           "get_trace_ctx", "RANGE_KEY", "make_range_ctx",
           "get_range_ctx"]
