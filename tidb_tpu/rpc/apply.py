"""Follower apply engine: continuous WAL apply + closed timestamps.

The serving half of the follower-read tier (reference: TiFlash learner
replicas applying the raft log continuously + the closed-timestamp /
resolved-ts protocol that makes follower snapshot reads safe,
store/tikv follower read via ReadIndex). PR 4's followers already
mirror the leader's (snapshot, WAL) byte stream, but only folded it
into the columnar epochs lazily, per statement, with a leader
round-trip on the statement path. This engine runs the SAME fold
continuously in the background and — crucially — tracks the highest
timestamp the local replica is COMPLETE at:

  * Each tick asks the leader for `closed_info`: a (wal_size, closed_ts)
    pair with the invariant that every commit whose commit_ts is at or
    below closed_ts has its WAL records inside the first wal_size
    bytes. The leader computes the pair under its commit lock, capping
    closed_ts below any remote commit timestamp that is still
    unpublished (the pending-commit ledger in rpc/server.py).
  * The tick then tails/folds the mirror to at least wal_size and
    adopts closed_ts as this replica's `applied_ts` — the fence the
    read router checks before sending a snapshot read here.
  * A read whose read_ts is above applied_ts WAITS (wait_for, the
    ReadIndex analog: kick a tick, block on the advance condition) up
    to a small bound instead of failing — a healthy replica closes a
    fresh leader timestamp within one round-trip.

Every heartbeat ping advertises (applied_ts, apply_lag_ms, serving,
load, term) so the leader's membership registry — and through it the
read router, information_schema.cluster_info and /status — always
knows which replicas can serve and how far behind they are.

Failpoint `replica/apply-stall` freezes the advance (the tick still
refreshes lag/heartbeat state) so tests can pin the staleness fence:
a stalled replica must cause a typed leader fallback, never a stale
answer.

Mirroring is never blocked: the fold path is the same refresh the
statement path already uses (MVCCStore serializes them), and a fold
failure only delays the NEXT advance — the byte mirror (the promotion
substrate) keeps its own cadence inside RemoteKV.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..kv.tso import _LOGICAL_BITS
from ..util import failpoint


def ts_physical_ms(ts: int) -> int:
    """Physical milliseconds of a hybrid timestamp (the PD layout
    kv/tso.py owns; this module and rpc/replica.py convert through
    these two helpers only)."""
    return int(ts) >> _LOGICAL_BITS


def ts_at_physical_ms(ms: int) -> int:
    """The smallest hybrid timestamp at physical time `ms` (inverse of
    ts_physical_ms; the bounded-staleness read point)."""
    return max(0, int(ms)) << _LOGICAL_BITS


class ApplyEngine:
    """Background fold + closed-timestamp tracker for ONE follower
    Storage. Thread-light: one daemon thread, woken early by kick()
    (a replica read waiting for coverage) and joined by close()."""

    def __init__(self, storage, interval_ms: int = 200) -> None:
        self.storage = storage
        self.interval_ms = max(10, int(interval_ms))
        self._cv = threading.Condition()
        self.applied_ts = 0          # highest CLOSED ts fully applied
        self.applied_wal = 0         # WAL bytes folded at that ts
        self.last_advance = time.monotonic()
        self.ticks = 0
        self.errors = 0
        self.last_error: Optional[str] = None
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="titpu-replica-apply", daemon=True)
        self._thread.start()

    # ---- the loop ----------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the apply loop
                # must survive any leader hiccup; the next tick retries
                self.errors += 1
                self.last_error = f"{type(e).__name__}: {str(e)[:200]}"
            self._kick.wait(self.interval_ms / 1000.0)
            self._kick.clear()

    def tick(self) -> bool:
        """One apply round: fetch the leader's closed point, fold the
        mirror past it, adopt the closed ts. Returns True when the
        applied_ts advanced. Heartbeat/lag state refreshes either way,
        so a stalled replica reports its growing lag instead of its
        last healthy numbers."""
        storage = self.storage
        if not storage.remote or self._stop.is_set():
            return False
        advanced = False
        stalled = bool(failpoint.inject("replica/apply-stall"))
        try:
            if not stalled:
                client = storage._rpc_client
                r = client.call(
                    "closed_info",
                    _budget_ms=min(client.options.backoff_budget_ms,
                                   1000))
                target = int(r.get("wal_size", 0))
                closed = int(r.get("closed_ts", 0))
                # the same fold the statement path runs (kv.refresh +
                # columnar drain); MVCCStore serializes concurrent
                # callers
                storage.kv.refresh()
                storage._drain_refresh()
                eng = storage.kv.kv
                off = int(getattr(eng, "_applied_off", 0))
                if off >= target and closed > self.applied_ts:
                    with self._cv:
                        self.applied_ts = closed
                        self.applied_wal = off
                        self.last_advance = time.monotonic()
                        self._cv.notify_all()
                    advanced = True
        finally:
            # publish EVEN when the leader call raised: a replica whose
            # ticks keep failing must advertise its growing lag, not
            # its last healthy numbers — the router's freshness check
            # and the follower-apply-lag rule both read this
            self.ticks += 1
            self._publish()
        return advanced

    def _publish(self) -> None:
        """Refresh the gauge + the heartbeat's replica advertisement."""
        storage = self.storage
        lag_s = self.lag_ms() / 1000.0
        storage.obs.apply_lag.set(lag_s)
        client = storage._rpc_client
        if client is None:
            return
        gate = getattr(storage, "admission", None)
        load = 0
        if gate is not None:
            st = gate.stats()
            load = int(st.get("running", 0)) + int(st.get("queue_depth", 0))
        # REPLACE the dict (atomic assignment): the heartbeat thread
        # unpacks ping_params concurrently, and an in-place update that
        # adds keys could resize it mid-iteration
        client.ping_params = {
            **client.ping_params,
            "applied_ts": int(self.applied_ts),
            "apply_lag_ms": round(self.lag_ms(), 1),
            # serving is withheld until the FIRST successful sync: a
            # just-started replica's lag reads near zero (it is the
            # engine's age), and advertising it would draw routed
            # reads that can only burn the serve-side wait
            "serving": bool(storage.replica_read.enabled
                            and self.applied_ts > 0),
            "load": load,
            "term": int(client.term),
        }

    # ---- read-side fences --------------------------------------------------
    def lag_ms(self) -> float:
        """How far behind leader time the applied prefix is. Before the
        first successful tick this is merely the engine's age — which
        is why _publish withholds the serving flag until applied_ts
        moves (an un-synced replica must never look fresh to the
        router)."""
        if self.applied_ts <= 0:
            return (time.monotonic() - self.last_advance) * 1000.0
        return max(0.0, time.time() * 1000.0
                   - ts_physical_ms(self.applied_ts))

    def wait_for(self, read_ts: int, timeout_s: float) -> bool:
        """Block until applied_ts covers read_ts (the ReadIndex analog:
        kick an immediate tick, then wait on the advance condition).
        Returns False when the bound expires — the caller answers with
        a typed staleness rejection and the router falls back."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self.applied_ts < read_ts:
                remain = deadline - time.monotonic()
                if remain <= 0 or self._stop.is_set():
                    return False
                self._kick.set()
                self._cv.wait(min(remain, 0.05))
        return True

    def info(self) -> dict:
        """Snapshot for /debug/replicas and transport health."""
        return {
            "applied_ts": int(self.applied_ts),
            "applied_wal": int(self.applied_wal),
            "apply_lag_ms": round(self.lag_ms(), 1),
            "interval_ms": self.interval_ms,
            "ticks": self.ticks,
            "errors": self.errors,
            "last_error": self.last_error,
        }

    def close(self) -> None:
        self._stop.set()
        self._kick.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=5.0)


__all__ = ["ApplyEngine", "ts_physical_ms", "ts_at_physical_ms"]
