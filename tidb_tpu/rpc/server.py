"""CoordRPCServer: the store-owning process's coordination endpoint.

Embedded in the leader (the one server whose Storage owns the durable
directory), it exports the three services every other deployment shape
gets from the shared directory — TSO allocation, WAL append/tail, KILL
mailbox — plus the named leases (mutation section, DDL/GC owner) that
serialize cluster mutators. The reference splits these across PD (TSO,
store/tikv/oracle/oracles/pd.go), TiKV raftstore (the log), and etcd
(owner election, owner/manager.go); one process plays all three here
because the storage tier is embedded.

Crucial property: remote grants take the SAME flocks the shared-dir
mode uses (store.lock, ddl.owner.lock, ...), so a socket follower and a
disk-sharing sibling can coexist against one directory — local and
remote mutators stay mutually exclusive through the kernel.

Safety under lease loss: every grant carries a fencing token; a WAL
append from a deposed holder (lease expired while it was paused or
partitioned) is rejected with StaleLeaseError BEFORE touching the file,
and the append offset is double-checked against the file size as a
second net (reference analog: raft terms fencing a deposed leader's
proposals)."""

from __future__ import annotations

import fcntl
import os
import socket
import threading
import time
from typing import Any, Optional

from ..analysis import lockcheck
from ..errno import CodedError
from .errors import RPCError, StaleLeaseError, StaleTermError, \
    WalOffsetMismatch, traced_response, wire_error
from .frame import MAX_FRAME, FrameError, decode, encode, get_trace_ctx, \
    parse_addr, recv_frame, send_frame

# one tail response carries at most this many bytes; clients loop
TAIL_CHUNK = 4 << 20


def read_term(path: str) -> int:
    """The persisted fencing term (0 when absent/corrupt — a torn term
    file reads as 'unknown', and the caller re-persists)."""
    try:
        with open(path) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def write_term(path: str, term: int) -> None:
    """Crash-atomic term persistence: tmp + fsync + rename + dir fsync
    (losing a term bump to power loss would let the next incarnation
    reuse a fenced epoch)."""
    from ..kv.mvcc import fsync_dir
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(int(term)))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


class _Client:
    __slots__ = ("last_seen", "node_id", "node_fd", "last_seq",
                 "last_seq_result", "kill_seq", "kill_result",
                 "diag_addr", "role", "diag_departed",
                 "applied_ts", "apply_lag_ms", "serving", "load",
                 "peer_term", "pending_commit")

    def __init__(self) -> None:
        self.last_seen = time.monotonic()
        self.node_id: Optional[int] = None
        self.node_fd: Optional[int] = None
        self.last_seq = -1
        self.last_seq_result: Optional[int] = None
        self.kill_seq = -1
        self.kill_result: Optional[list] = None
        # membership registry fields (the diag plane): where this
        # client's diagnostics listener answers, and what role it plays;
        # diag_departed latches on clean unregister so a straggler
        # heartbeat (its ping was in flight during the peer's close)
        # cannot resurrect the dead address
        self.diag_addr: Optional[str] = None
        self.role: Optional[str] = None
        self.diag_departed = False
        # follower-read tier advertisement (rpc/apply.py rides the
        # heartbeat): closed/applied ts, apply lag, the serving flag,
        # the admission-gate load signal, and the term the peer lives
        # in (a lower term marks a deposed-epoch replica non-serving)
        self.applied_ts = 0
        self.apply_lag_ms: Optional[float] = None
        self.serving = False
        self.load = 0
        self.peer_term = 0
        # the ONE remote commit timestamp this client may be holding
        # unpublished (issued by tso_commit, retired by tso_commit_done
        # / the next tso_commit / the mutation-lease release) — the
        # pending-commit ledger closed_info caps the closed ts under
        self.pending_commit = 0


class _Grant:
    __slots__ = ("client_id", "token")

    def __init__(self, client_id: str, token: int) -> None:
        self.client_id = client_id
        self.token = token


class FrameListener:
    """Frame-protocol server core shared by CoordRPCServer and the diag
    listeners (rpc/diag.py): bind + accept loop, the per-connection
    serve loop with the oversized-response guard (an over-MAX_FRAME
    payload answers typed instead of tearing the stream — a torn stream
    would make the client retry a deterministic failure), and a
    teardown that wakes a blocked accept(). Subclasses implement
    `_dispatch(req) -> response dict`."""

    _thread_prefix = "titpu-frame"

    def _start_listener(self, listen, backlog: int = 64):
        """Bind + start accepting; returns (family, target) so the
        subclass can compute its advertised address."""
        self._shutdown = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_mu = threading.Lock()
        fam, target = parse_addr(listen)
        ls = socket.socket(fam, socket.SOCK_STREAM)
        if fam == socket.AF_INET:
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind(target)
        ls.listen(backlog)
        self._listener = ls
        self.port = ls.getsockname()[1] if fam == socket.AF_INET else 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"{self._thread_prefix}-accept", daemon=True)
        self._accept_thread.start()
        return fam, target

    def _accept_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                break
            with self._conns_mu:
                self._conns.add(sock)
            threading.Thread(target=self._serve_conn, args=(sock,),
                             name=f"{self._thread_prefix}-conn",
                             daemon=True).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        try:
            while not self._shutdown.is_set():
                try:
                    req = decode(recv_frame(sock))
                except (ConnectionError, FrameError, OSError):
                    return  # torn stream: client reconnects
                resp = self._dispatch(req)
                payload = encode(resp)
                if len(payload) > MAX_FRAME:
                    payload = encode({"id": resp.get("id"), "err": {
                        "type": "RPCError",
                        "msg": f"response too large for one frame "
                               f"({len(payload)} > {MAX_FRAME})"}})
                try:
                    send_frame(sock, payload)
                except OSError:
                    return
        finally:
            with self._conns_mu:
                self._conns.discard(sock)
            try:
                sock.close()
            except OSError:
                pass

    def _close_listener(self) -> None:
        self._shutdown.set()
        try:
            # wake a blocked accept() — closing the fd alone leaves the
            # accept thread parked until a connection arrives
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conns_mu:
            conns = list(self._conns)
            self._conns.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass
        self._accept_thread.join(timeout=5.0)


class CoordRPCServer(FrameListener):
    _thread_prefix = "titpu-rpc"

    def __init__(self, storage, listen="127.0.0.1:0",
                 lease_ms: int = 3000,
                 tail_chunk: int = TAIL_CHUNK,
                 term: Optional[int] = None) -> None:
        if storage.path is None:
            raise ValueError("RPC coordination needs a durable store dir")
        self.storage = storage
        self.path = storage.path
        self.lease_ms = lease_ms
        # the server owns the chunk size; clients drive the tail loop
        # off the response's `more` flag, never off their own constant
        self.tail_chunk = tail_chunk
        self._mu = lockcheck.lock("CoordRPCServer._mu")
        self._clients: dict[str, _Client] = {}
        self._grants: dict[str, _Grant] = {}   # lock name -> grant
        self._lock_fds: dict[str, int] = {}    # lock name -> flock fd
        self._next_token = 1
        self._wal_path = os.path.join(self.path, "kv", "wal.log")
        self._snap_path = os.path.join(self.path, "kv", "snapshot.kv")
        os.makedirs(os.path.join(self.path, "kv"), exist_ok=True)
        # the cluster fencing TERM, persisted beside the WAL it fences:
        # a fresh leader starts at 1, a clean restart resumes the stored
        # term, and a PROMOTED follower passes term=stored+1. Mutating
        # requests carrying a lower term are rejected (StaleTermError) —
        # the raft-term analog that stops a deposed leader's clients
        # from split-braining the log.
        self._term_path = os.path.join(self.path, "kv", "term")
        self.term = int(term) if term is not None else \
            max(1, read_term(self._term_path))
        write_term(self._term_path, self.term)
        # O_APPEND handle for remote records: interleaves safely with
        # the leader engine's own appends (both under the mutation flock)
        self._append_f = open(self._wal_path, "ab")
        # remote appends honor the SAME storage.sync-log policy as the
        # engine's own WAL writes (one shared evaluator, kv/mvcc.py)
        from ..kv.mvcc import SyncPolicy
        engine = storage.kv.kv
        self._append_sync = SyncPolicy(
            getattr(engine, "sync_log", "off"),
            getattr(engine, "sync_interval_ms", 100),
            self._fsync_append)
        # cross-commit group fsync for remote appends: concurrent
        # wal_append handler threads rendezvous on one fsync instead of
        # serializing one disk barrier per append (the mutation lease
        # serializes WRITERS, but pipelined appends from the leased
        # client's sessions still overlap their durability waits)
        self._append_sync.defer_commit = True
        self._append_sync.on_batch = storage._note_group_commit
        self._append_sync.on_stall = getattr(
            getattr(engine, "_syncer", None), "on_stall", None)
        fam, target = self._start_listener(listen)
        if fam == socket.AF_INET:
            # the advertised address doubles as the leader's dialable
            # diag endpoint in members(); a wildcard bind can't name a
            # single routable host, so loopback stands in and followers
            # substitute the leader address they actually dialed
            # (rpc/diag.py cluster_members)
            host = self._listener.getsockname()[0]
            if host in ("0.0.0.0", "::", ""):
                host = "127.0.0.1"
            self.address = f"{host}:{self.port}"
        else:
            self.address = f"unix:{target}"
        threading.Thread(target=self._reaper_loop,
                         name="titpu-rpc-reaper", daemon=True).start()

    def _fsync_append(self) -> None:
        f = self._append_f
        if not f.closed:
            f.flush()
            os.fsync(f.fileno())

    # ---- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._append_sync.close()
        self._close_listener()
        with self._mu:
            for name in list(self._grants):
                self._release_locked(name)
            for fd in self._lock_fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass
            self._lock_fds.clear()
            for c in self._clients.values():
                if c.node_fd is not None:
                    try:
                        os.close(c.node_fd)
                    except OSError:
                        pass
            self._clients.clear()
        try:
            self._append_f.close()
        except OSError:
            pass

    # ---- dispatch ----------------------------------------------------------
    def _dispatch(self, req: Any) -> dict:
        if not isinstance(req, dict) or "m" not in req:
            return {"id": None,
                    "err": {"type": "RPCError", "msg": "bad request"}}
        rid = req.get("id")
        method = req.get("m")
        params = req.get("p") or {}
        client_id = str(req.get("c") or "")
        handler = getattr(self, f"_h_{method}", None)
        if handler is not None:
            fn = lambda: handler(client_id, **params)  # noqa: E731
            with self._mu:
                c = self._clients.get(client_id)
                if c is None:
                    c = self._clients[client_id] = _Client()
                c.last_seen = time.monotonic()
        elif isinstance(method, str) and method.startswith("diag_"):
            # the diag service (shared with follower DiagListeners)
            # serves the leader's own diagnostics over this port;
            # registry methods like diag_register keep _h_ handlers.
            # NO _Client entry: diag fan-out callers are not cluster
            # participants and must not inflate client_count()
            fn = lambda: self.storage.diag.handle(  # noqa: E731
                method, **(params if isinstance(params, dict) else {}))
        else:
            return wire_error(rid, RPCError(f"unknown method {method}"))
        # trace propagation: a request under an active client TRACE
        # runs its handler beneath a SpanCollector and ships the span
        # rows back for stitching (rpc/frame.py trace ctx)
        return traced_response(rid, method, fn, get_trace_ctx(req))

    # ---- liveness ----------------------------------------------------------
    def _h_ping(self, client_id: str, diag_addr=None, role=None,
                node_id=None, applied_ts=None, apply_lag_ms=None,
                serving=None, load=None, term=None) -> dict:
        # heartbeats may carry the sender's diag registration so a
        # restarted leader relearns the membership within one beat
        if diag_addr:
            self._register_member(client_id, str(diag_addr),
                                  str(role or "follower"))
        if node_id is not None or applied_ts is not None:
            with self._mu:
                c = self._clients.get(client_id)
                if c is not None:
                    if node_id is not None and c.node_id is None:
                        # a follower that repointed here after a
                        # promotion keeps its original node id; record
                        # it so members() and the election registry
                        # stay id-accurate
                        c.node_id = int(node_id)
                    if applied_ts is not None:
                        # the follower-read advertisement (rpc/apply.py)
                        c.applied_ts = int(applied_ts)
                        c.apply_lag_ms = float(apply_lag_ms or 0.0)
                        c.load = int(load or 0)
                        c.peer_term = int(term or 0)
                        # a replica living in a FENCED epoch (it last
                        # applied a deposed leader's stream) is never
                        # a serving candidate, whatever it advertises
                        c.serving = bool(serving) and \
                            c.peer_term >= self.term
        # the term rides every beat: clients track the cluster epoch
        # from it, and a client that knows a HIGHER term than ours
        # treats us as a deposed leader (StaleTermError client-side)
        return {"ok": True, "lease_ms": self.lease_ms, "term": self.term}

    def _h_hello(self, client_id: str) -> dict:
        return {"lease_ms": self.lease_ms,
                "wal_size": self._wal_size(),
                "term": self.term}

    def client_count(self) -> int:
        with self._mu:
            horizon = time.monotonic() - 3 * self.lease_ms / 1000.0
            return sum(1 for c in self._clients.values()
                       if c.last_seen >= horizon)

    # ---- membership registry (the diag plane) ------------------------------
    def _register_member(self, client_id: str, addr: str,
                         role: str) -> None:
        with self._mu:
            c = self._clients.get(client_id)
            if c is None:
                c = self._clients[client_id] = _Client()
            if c.diag_departed:
                return  # cleanly closed; a straggler ping can't rejoin
            c.diag_addr, c.role = addr, role

    def _h_diag_register(self, client_id: str, addr: str = "",
                         role: str = "follower") -> dict:
        self._register_member(client_id, str(addr), str(role))
        return {}

    def _h_diag_unregister(self, client_id: str) -> dict:
        """Clean shutdown: drop the member now instead of letting the
        cluster_* fan-out burn its budget against the closed address
        until the lease horizon passes."""
        with self._mu:
            c = self._clients.get(client_id)
            if c is None:
                c = self._clients[client_id] = _Client()
            c.diag_addr = c.role = None
            c.diag_departed = True
        return {}

    def members(self) -> list[dict]:
        """Cluster shape: the leader itself plus every registered client
        with a diag address, tagged with heartbeat age so operators (and
        the cluster_* fan-out) can judge liveness. The same 3-lease
        horizon client_count applies bounds how long a crashed peer
        keeps contributing error rows — past it the peer has departed."""
        # the leader row carries the serving-tier columns too: its
        # "applied" point is simply the newest issued timestamp, and it
        # is not a replica-read candidate (serving False) — leader
        # reads are just reads
        out = [{"id": 0, "addr": self.address, "role": "leader",
                "hb_age_s": 0.0,
                "applied_ts": int(self.storage.tso.current()),
                "apply_lag_ms": 0.0, "serving": False, "load": 0}]
        now = time.monotonic()
        horizon = 3 * self.lease_ms / 1000.0
        with self._mu:
            for c in self._clients.values():
                age = now - c.last_seen
                if c.diag_addr and age <= horizon:
                    out.append({
                        "id": c.node_id if c.node_id is not None else -1,
                        "addr": c.diag_addr,
                        "role": c.role or "follower",
                        "hb_age_s": round(age, 3),
                        "applied_ts": int(c.applied_ts),
                        "apply_lag_ms": c.apply_lag_ms,
                        "serving": bool(c.serving),
                        "load": int(c.load)})
        return out

    def _h_members(self, client_id: str) -> dict:
        return {"members": self.members()}

    # ---- TSO ---------------------------------------------------------------
    def _h_tso_next(self, client_id: str) -> dict:
        return {"ts": self.storage.tso.next_ts()}

    def _h_tso_commit(self, client_id: str) -> dict:
        """A COMMIT timestamp for a remote committer: allocated like any
        other, but entered into the pending-commit ledger until the
        records it stamps are published (or the commit dies).

        Allocation and registration happen under the SAME storage
        commit lock _h_closed_info computes under — otherwise a
        closed_info interleaving between next_ts() and the ledger write
        would see tso.current() >= ts with an empty pending list and
        close past an in-flight commit.

        ONE slot per client is safe: the follower's Storage serializes
        its whole commit phase (allocation through publish) under its
        own commit lock, so a new tso_commit from the same client means
        the previous commit finished — publish included — and its entry
        retires by replacement."""
        with self.storage._commit_lock:
            ts = self.storage.tso.next_ts()
            with self._mu:
                c = self._clients[client_id]
                c.pending_commit = ts
        return {"ts": ts}

    def _h_tso_commit_done(self, client_id: str, ts: int = 0) -> dict:
        """The remote commit phase completed (published or definitively
        not going to): retire the pending entry so the closed ts can
        advance past it. The retire is TS-MATCHED: a done that lost a
        race with the client's next tso_commit (the commit lock on the
        follower was released before the done RPC fired) must not wipe
        the successor's in-flight entry. Best-effort on the client
        side — a lost done is recovered by the client's next tso_commit
        or the client reaper."""
        with self._mu:
            c = self._clients.get(client_id)
            if c is not None and (not ts or c.pending_commit == int(ts)):
                c.pending_commit = 0
        return {}

    def _h_closed_info(self, client_id: str) -> dict:
        """The closed-timestamp point for follower serving: every commit
        with commit_ts <= closed_ts has its WAL records inside the
        first wal_size bytes (rpc/apply.py adopts the pair once its
        fold passes wal_size). Correctness: local commits allocate
        their commit_ts AND append their records under the storage
        commit lock we hold here, so anything after us is > our
        tso.current(); remote commits allocate via tso_commit, whose
        ledger caps us below any still-unpublished timestamp. (Disk-
        sharing sibling WRITER processes bypass both fences — the
        serving tier assumes the socket-cluster shape, where the
        leader process is the only local mutator.)"""
        st = self.storage
        # the WAL stat is disk I/O — kept OUTSIDE the commit lock
        # (blocking-call-under-hot-lock). Between appends the size
        # only grows, so a post-lock stat still covers every record of
        # commits <= closed_ts and the extra bytes belong to newer
        # commits the MVCC read at closed_ts never sees. The one size
        # DECREASE is a checkpoint rotating the WAL (which never held
        # this lock, before or after this change): bracketing stats
        # detect a rotation racing the closed-ts read and retry, so a
        # truncated size is never paired with a pre-truncation
        # closed_ts.
        # rotation epoch of the leader's WAL: in the serving shape the
        # leader is shared-mode and never rotates (PyOrderedKV shared
        # checkpoint is a no-op), so the generation is constant; the
        # bracket still guards any future rotation path — a size
        # comparison alone cannot see a truncate-then-regrow (same
        # file, size already past the pre-rotation stat)
        def _wal_gen() -> int:
            return int(getattr(getattr(st.kv, "kv", None),
                               "wal_generation", 0))

        for _ in range(3):
            gen0 = _wal_gen()
            before = self._wal_size()
            with st._commit_lock:
                closed = int(st.tso.current())
                with self._mu:
                    pend = [c.pending_commit
                            for c in self._clients.values()
                            if c.pending_commit]
                if pend:
                    closed = min(closed, min(pend) - 1)
            wal = self._wal_size()
            if _wal_gen() == gen0 and wal >= before:
                return {"wal_size": wal, "closed_ts": closed,
                        "term": self.term}
        # a rotation raced every retry: REFUSE to advance rather than
        # pair a fresh closed_ts with a size that may not cover its
        # records — the apply engine's `closed > applied_ts` guard
        # makes a zero pair one skipped tick, never a regression
        return {"wal_size": 0, "closed_ts": 0, "term": self.term}

    # ---- named leases (mutation section, ddl/gc owner) ---------------------
    def _lock_file(self, name: str) -> str:
        if name == "mutation":
            return os.path.join(self.path, "store.lock")
        if name in ("ddl", "gc"):
            return os.path.join(self.path, f"{name}.owner.lock")
        safe = "".join(ch if ch.isalnum() else "_" for ch in name)
        return os.path.join(self.path, f"rpc.{safe}.lock")

    def _lock_fd(self, name: str) -> int:
        fd = self._lock_fds.get(name)
        if fd is None:
            fd = os.open(self._lock_file(name),
                         os.O_CREAT | os.O_RDWR, 0o644)
            self._lock_fds[name] = fd
        return fd

    def _expired(self, client_id: str) -> bool:
        c = self._clients.get(client_id)
        return c is None or \
            time.monotonic() - c.last_seen > self.lease_ms / 1000.0

    def _release_locked(self, name: str) -> None:
        """Drop a grant; caller holds self._mu. Deliberately does NOT
        retire the holder's pending commit: mutation sections are also
        taken by NON-commit paths (pessimistic locking) on other
        sessions of the same client, and their release racing a
        sibling's in-flight commit would clear a live ledger entry —
        closed_ts would pass an unpublished commit. A pending entry a
        lost tso_commit_done leaves behind only delays closing (the
        client's next commit or the reaper clears it); conservative
        beats wrong."""
        self._grants.pop(name, None)
        fd = self._lock_fds.get(name)
        if fd is not None:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            except OSError:
                pass

    def _check_term(self, term) -> None:
        """Reject a mutator still living in a fenced epoch. term=0 means
        the caller predates term fencing (direct RpcClient users) and is
        admitted — the lease tokens still protect the WAL."""
        if term and int(term) < self.term:
            raise StaleTermError(
                f"request term {int(term)} is fenced: cluster is at "
                f"term {self.term} (a new leader was elected; "
                "re-resolve and retry)")

    def _h_lock_acquire(self, client_id: str, name: str = "",
                        term: int = 0) -> dict:
        self._check_term(term)
        with self._mu:
            grant = self._grants.get(name)
            if grant is not None:
                if grant.client_id == client_id:
                    return {"granted": True, "token": grant.token}
                if not self._expired(grant.client_id):
                    return {"granted": False}
                # deposed holder: force-release; its token is now stale
                self._release_locked(name)
            fd = self._lock_fd(name)
            try:
                # non-blocking: a local process (shared-dir sibling or
                # the leader itself) may hold the kernel lock
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                return {"granted": False}
            token = self._next_token
            self._next_token += 1
            self._grants[name] = _Grant(client_id, token)
            return {"granted": True, "token": token}

    def _h_lock_release(self, client_id: str, name: str = "",
                        token: int = 0) -> dict:
        with self._mu:
            grant = self._grants.get(name)
            if grant is not None and grant.client_id == client_id \
                    and grant.token == int(token):
                self._release_locked(name)
        return {}  # stale releases are no-ops (lease already reaped)

    def _reaper_loop(self) -> None:
        """Expire grants whose holder stopped heartbeating — this is
        what unblocks leader-local mutators stuck in the kernel flock
        behind a dead remote client."""
        interval = max(0.1, self.lease_ms / 2000.0)
        while not self._shutdown.wait(interval):
            with self._mu:
                for name, grant in list(self._grants.items()):
                    if self._expired(grant.client_id):
                        self._release_locked(name)
                horizon = time.monotonic() - \
                    max(10 * self.lease_ms / 1000.0, 30.0)
                for cid, c in list(self._clients.items()):
                    if c.last_seen < horizon:
                        if c.node_fd is not None:
                            try:
                                os.close(c.node_fd)  # frees the slot
                            except OSError:
                                pass
                        del self._clients[cid]

    # ---- WAL append/tail ---------------------------------------------------
    def _wal_size(self) -> int:
        try:
            return os.path.getsize(self._wal_path)
        except OSError:
            return 0

    def _h_wal_bootstrap(self, client_id: str, offset: int = 0) -> dict:
        """Initial mirror: the snapshot file (same record format as the
        WAL; present only when the directory had a pre-shared life),
        streamed in chunks exactly like wal_tail so neither the snapshot
        nor the log ever has to fit one frame."""
        try:
            with open(self._snap_path, "rb") as f:
                f.seek(int(offset))
                snap = f.read(self.tail_chunk)
                more = bool(snap) and f.read(1) != b""
        except OSError:
            snap, more = b"", False
        return {"snapshot": snap, "more": more,
                "wal_size": self._wal_size()}

    def _h_wal_tail(self, client_id: str, offset: int = 0,
                    limit: int = 0) -> dict:
        """Position-based incremental tail: bytes past `offset`. `more`
        tells the client whether the file extends past this response —
        the loop's ONLY termination signal, so server and client need no
        shared chunk constant. `limit` lets a client outgrow the default
        chunk when a single record spans it."""
        n = min(int(limit) or self.tail_chunk, MAX_FRAME - 4096)
        size = self._wal_size()
        try:
            with open(self._wal_path, "rb") as f:
                f.seek(int(offset))
                data = f.read(max(n, 1))
                more = bool(data) and f.read(1) != b""
        except OSError:
            data, more = b"", False
        # wal_size lets a tailer detect DIVERGENCE: an offset beyond the
        # file means the tailer replicated more than this leader holds
        # (possible only across a failover data-loss window) and must
        # fail typed instead of waiting forever for bytes that never come
        return {"data": data, "more": more, "wal_size": size}

    def _h_wal_append(self, client_id: str, seq: int = 0,
                      expected: int = 0, data: bytes = b"",
                      token: int = 0, term: int = 0) -> dict:
        seq = int(seq)
        self._check_term(term)
        with self._mu:
            c = self._clients[client_id]
            if seq == c.last_seq and c.last_seq_result is not None:
                # idempotent retry of the in-flight append (the response
                # was lost, not the write) — reference analog: region
                # request replay after a recycled connection
                return {"offset": c.last_seq_result}
            grant = self._grants.get("mutation")
            if grant is None or grant.client_id != client_id \
                    or grant.token != int(token):
                raise StaleLeaseError(
                    "wal append fenced: mutation lease "
                    f"{'lost' if grant is None else 'superseded'} "
                    f"(token {token})")
            size = self._wal_size()
            if int(expected) != size:
                raise WalOffsetMismatch(
                    f"append expected WAL at {expected} but file is at "
                    f"{size}")
            self._append_f.write(bytes(data))
            self._append_f.flush()
            off = size + len(data)
        # the ack below IS the follower's commit acknowledgement: honor
        # the sync-log policy first — but OUTSIDE self._mu, or every
        # unrelated RPC (pings, tso) queues behind each disk fsync.
        # commit mode rendezvous on a shared in-flight fsync (group
        # commit); a failed fsync propagates (typed) instead of acking
        # undurable.
        self._append_sync.mark_dirty()
        self._append_sync.boundary()
        self._append_sync.commit_sync()
        with self._mu:
            c = self._clients[client_id]
            c.last_seq = seq
            c.last_seq_result = off
        return {"offset": off}

    # ---- node registry + kill mailbox --------------------------------------
    def _h_node_claim(self, client_id: str) -> dict:
        from ..store.coordinator import TSO_NODE_SLICES
        with self._mu:
            c = self._clients[client_id]
            if c.node_id is not None:
                return {"node_id": c.node_id}
            for nid in range(TSO_NODE_SLICES):
                fd = os.open(
                    os.path.join(self.path, "procs", f"node{nid}.lock"),
                    os.O_CREAT | os.O_RDWR, 0o644)
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                except OSError:
                    os.close(fd)
                    continue
                c.node_id, c.node_fd = nid, fd
                return {"node_id": nid}
        raise CodedError("no free node slots in store dir")

    def _h_node_register(self, client_id: str, node_id: int = 0,
                         port: int = 0, status_port=None) -> dict:
        import json
        info = {"pid": -1, "client": client_id, "port": int(port),
                "status_port": status_port, "started": time.time(),
                "remote": True}
        p = os.path.join(self.path, "procs", f"node{int(node_id)}.json")
        with open(p + ".tmp", "w") as f:
            json.dump(info, f)
        os.replace(p + ".tmp", p)
        return {}

    def _h_servers(self, client_id: str) -> dict:
        coord = self.storage.coord
        return {"servers": coord.servers() if coord is not None else {}}

    def _h_kill_post(self, client_id: str, conn_id: int = 0,
                     query_only: bool = False) -> dict:
        self.storage.coord.post_kill(int(conn_id), bool(query_only))
        return {}

    def _h_kill_poll(self, client_id: str, node_id: int = 0,
                     seq: int = 0) -> dict:
        seq = int(seq)
        with self._mu:
            c = self._clients[client_id]
            if seq and seq == c.kill_seq and c.kill_result is not None:
                # retry of a poll that already drained the mailbox (the
                # response was lost): replay, don't lose the kills
                return {"kills": c.kill_result}
        kills = [[local, qo] for local, qo
                 in self.storage.coord.poll_kills(int(node_id))]
        with self._mu:
            c = self._clients[client_id]
            c.kill_seq, c.kill_result = seq, kills
        return {"kills": kills}


__all__ = ["CoordRPCServer", "FrameListener", "TAIL_CHUNK",
           "read_term", "write_term"]
