"""Snapshot-consistent replica routing: the follower read tier's router.

Counterpart of the reference's follower/stale reads (reference:
tidb_replica_read = "follower" in sessionctx/variable + the
ReadIndex-checked follower read in store/tikv, and the
`tidb_read_staleness` / `AS OF TIMESTAMP` bounded-staleness mode,
executor/stale_txn_reader). Three pieces live here:

  * try_route — the session-layer router. An ELIGIBLE statement (a
    plain autocommit snapshot SELECT over base tables: no DML, no
    FOR UPDATE, no user variables, no nondeterministic functions, no
    system schemas) is sent to the least-loaded live replica whose
    closed timestamp can cover the statement's read_ts, with
    per-replica circuit-breaker awareness (an OPEN breaker skips the
    candidate without burning a Backoffer budget) and typed fallback
    to the leader on staleness, term fencing, or unreachability —
    never a wrong or failed query.
  * serve_replica_read — the replica-side handler (reached over the
    diag endpoint as `diag_replica_read`). It fences on the cluster
    TERM (a replica following a deposed leader answers StaleTermError,
    the raft-term analog), waits bounded for its applied/closed ts to
    cover read_ts (the ReadIndex analog; rpc/apply.py), then executes
    the SELECT at EXACTLY read_ts on its local engine — bit-identical
    to the leader's answer because it is the same fold at the same
    timestamp. DML and every non-SELECT statement are rejected typed.
  * the wire row codec — result values that the frame encoding cannot
    carry natively (Decimal, DATE, DATETIME) travel as tagged dicts.

Trust model: the serving endpoint answers unauthenticated, like every
other diag method and the WAL stream itself (rpc/diag.py docstring) —
the transport plane assumes a trusted segment, and the ROUTER performs
the privilege checks before shipping the SQL (the replica executes as
an internal session).

Routing is observable end to end: the decision lands in the statement's
engine tags (`replica@host:port` in Session.last_engines and EXPLAIN
ANALYZE), a `replica_read` dispatch stage (slow log / Top SQL), the
`tidb_replica_reads_total{outcome=served|stale_fallback|
unreachable_fallback}` counter, and a session Note on every fallback.
"""

from __future__ import annotations

import datetime as _dt
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from .. import obs
from .apply import ts_at_physical_ms
from .errors import ReplicaStaleError, RPCError, StaleTermError


@dataclass
class ReplicaReadState:
    """Per-storage replica-read settings. Field names/defaults MIRROR
    config.ReplicaReadConfig (the TOML owner; Config.seed_replica_read
    copies the knobs in) — mirrored rather than imported so an embedded
    Storage never parses config (the DiagnosticsState pattern)."""

    # master switch: the follower apply engine + the serving endpoint +
    # the router all gate on it
    enabled: bool = True
    # bounded-staleness cap: how stale a routed (or tidb_read_staleness)
    # read may be, and how far behind a replica may run and still be a
    # routing candidate
    max_staleness_ms: int = 5000
    # follower apply-engine cadence (closed-ts fetch + columnar fold)
    apply_interval_ms: int = 200
    # route eligible SELECTs to followers by default (seeds the
    # tidb_replica_read sysvar default; sessions override per-session)
    prefer_follower: bool = False
    # range-aware covering: before dispatching, require every range the
    # statement's table spans touch to have published closed_ts >=
    # read_ts (the per-range ledger floors, rpc/ranged.py). False keeps
    # today's single-closed-ts routing byte-for-byte
    range_aware: bool = False


# functions whose value depends on WHERE/WHEN they run: routing them
# would let a replica answer differently from the leader (reference:
# expression.UnCacheableFunctions / the stale-read restrictions)
NONROUTABLE_FUNCS = frozenset({
    "NOW", "CURRENT_TIMESTAMP", "LOCALTIME", "LOCALTIMESTAMP",
    "CURDATE", "CURRENT_DATE", "CURTIME", "CURRENT_TIME", "SYSDATE",
    "UNIX_TIMESTAMP", "UTC_DATE", "UTC_TIME", "UTC_TIMESTAMP",
    "RAND", "UUID", "UUID_SHORT", "CONNECTION_ID", "CURRENT_USER",
    "USER", "SESSION_USER", "SYSTEM_USER", "DATABASE", "SCHEMA",
    "FOUND_ROWS", "ROW_COUNT", "LAST_INSERT_ID", "VERSION",
    "GET_LOCK", "RELEASE_LOCK", "IS_FREE_LOCK", "IS_USED_LOCK",
    "RELEASE_ALL_LOCKS", "SLEEP", "BENCHMARK", "NAME_CONST",
})

# schemas whose tables are per-server memtables: their rows are about
# THIS server, so routing them would silently answer about another one
_SYSTEM_DBS = frozenset({
    "information_schema", "metrics_schema", "performance_schema",
    "mysql",
})


# ---- wire row codec ---------------------------------------------------------

def wire_value(v: Any) -> Any:
    """Result scalar -> frame-encodable value. Decimal/date/datetime
    travel as tagged dicts (the frame codec deliberately has no
    arbitrary-object escape hatch — rpc/frame.py)."""
    from ..types.value import Decimal, encode_date, encode_datetime
    if isinstance(v, Decimal):
        return {"__t": "dec", "u": v.unscaled, "s": v.scale}
    if isinstance(v, _dt.datetime):
        return {"__t": "dtm", "us": encode_datetime(v)}
    if isinstance(v, _dt.date):
        return {"__t": "date", "d": encode_date(v)}
    return v


def unwire_value(v: Any) -> Any:
    from ..types.value import Decimal, decode_date, decode_datetime
    if isinstance(v, dict):
        t = v.get("__t")
        if t == "dec":
            return Decimal(int(v["u"]), int(v["s"]))
        if t == "dtm":
            return decode_datetime(int(v["us"]))
        if t == "date":
            return decode_date(int(v["d"]))
    return v


# ---- replica-side serving ---------------------------------------------------

def _serving_session(storage):
    """A pooled internal Session for replica reads (sessions are not
    thread-safe; the pool keeps plan caches warm across requests)."""
    with storage._replica_pool_lock:
        if storage._replica_pool:
            return storage._replica_pool.pop()
    from ..session.session import Session
    sess = Session(storage)
    sess._replica_serving = True  # never re-route from the serve path
    return sess

def _release_session(storage, sess) -> None:
    with storage._replica_pool_lock:
        if len(storage._replica_pool) < 8:
            storage._replica_pool.append(sess)


def serve_replica_read(storage, sql: str = "", db: str = "",
                       read_ts: int = 0, term: int = 0,
                       time_zone: str = "SYSTEM") -> dict:
    """Execute one routed snapshot SELECT at exactly `read_ts` on this
    FOLLOWER's local engine. Fences, in order: role, enabled switch,
    cluster term, closed-timestamp coverage (bounded wait). Rejections
    are typed so the router falls back instead of retrying blind."""
    if not getattr(storage, "remote", False):
        raise RPCError("replica read: this server is not a follower")
    st = storage.replica_read
    eng = storage.apply_engine
    if not st.enabled or eng is None:
        raise ReplicaStaleError(
            "replica read: serving disabled on this replica "
            "(replica-read.enabled = false)")
    my_term = int(getattr(storage._rpc_client, "term", 0) or 0)
    if term and my_term and int(term) != my_term:
        # either side living in a fenced epoch must refuse: a replica
        # mirroring a DEPOSED leader may hold a diverged prefix, and a
        # deposed leader's router must re-resolve, not read through us
        raise StaleTermError(
            f"replica read fenced: replica follows term {my_term}, "
            f"request carries term {int(term)}")
    read_ts = int(read_ts)
    # the ReadIndex analog: wait (bounded) for the apply engine to
    # close a leader timestamp covering read_ts; a stalled replica
    # times out typed and the router goes back to the leader
    wait_s = min(2.0, 0.25 + 2 * eng.interval_ms / 1000.0)
    if not eng.wait_for(read_ts, wait_s):
        raise ReplicaStaleError(
            f"replica not caught up: applied_ts {eng.applied_ts} < "
            f"read_ts {read_ts} after {wait_s:.2f}s "
            f"(apply lag {eng.lag_ms():.0f}ms)")
    from ..session.session import SQLError
    from ..sql import ast
    from ..sql.parser import ParseError, parse_sql
    from ..store.storage import Transaction
    try:
        stmts = parse_sql(sql)
    except ParseError as e:
        raise RPCError(f"replica read parse error: {e}") from None
    if len(stmts) != 1 or not isinstance(
            stmts[0], (ast.SelectStmt, ast.SetOpStmt)):
        raise RPCError("replica read accepts exactly one SELECT")
    stmt = stmts[0]
    if getattr(stmt, "for_update", False) or \
            getattr(stmt, "into_outfile", None) is not None:
        raise RPCError(
            "replica read: locking reads and INTO OUTFILE must run "
            "on the leader")
    sess = _serving_session(storage)
    txn = Transaction(storage, read_ts)
    sess.current_db = db or sess.current_db
    sess.vars["time_zone"] = time_zone or "SYSTEM"
    sess.txn = txn
    sess.in_explicit_txn = True  # _run_in_txn must not commit/retry
    # pin BEFORE building the snapshot so compaction cannot fold past
    # read_ts between the fence check and the read; released by the
    # rollback in the finally below
    storage.pin_snapshot_ts(read_ts)
    try:
        rs = sess._execute_observed(stmt, sql, digest_sql=sql)
    except SQLError as e:
        raise RPCError(f"replica read failed: {e}") from None
    finally:
        sess.in_explicit_txn = False
        sess.txn = None
        txn.rollback()  # releases the pinned snapshot ts
        _release_session(storage, sess)
    return {
        "cols": list(rs.column_names),
        "rows": [[wire_value(v) for v in row] for row in rs.rows],
        "applied_ts": int(eng.applied_ts),
        "term": my_term,
    }


# ---- the router -------------------------------------------------------------

@dataclass
class RoutedRead:
    rows: list
    cols: list
    addr: str
    read_ts: int
    wall_ms: float


def cluster_term(storage) -> int:
    if getattr(storage, "rpc_server", None) is not None:
        return int(storage.rpc_server.term)
    client = getattr(storage, "_rpc_client", None)
    return int(getattr(client, "term", 0) or 0)


def _has_nonroutable_funcs(stmt) -> bool:
    from ..sql import ast
    found = [False]

    def visit(n):
        if isinstance(n, ast.FuncCall) and \
                n.name.upper() in NONROUTABLE_FUNCS:
            found[0] = True
            return False
        return True

    ast.walk(stmt, visit)
    return found[0]


def _eligible(session, stmt, sql: Optional[str],
              has_vars: bool) -> bool:
    from ..sql import ast
    if sql is None or has_vars:
        return False
    if getattr(session, "_replica_serving", False):
        return False
    if session.in_explicit_txn:
        return False
    if getattr(stmt, "for_update", False) or \
            getattr(stmt, "into_outfile", None) is not None:
        return False
    tables = session._collect_table_names(stmt)
    if not tables:
        return False  # SELECT 1 / session-state reads stay local
    for t in tables:
        db = (t.db or session.current_db or "").lower()
        if db in _SYSTEM_DBS:
            return False
        # views stay on the leader: the eligibility walk sees only the
        # view NAME, so a view body could smuggle nondeterministic
        # functions or system memtables past the gate — and the replica
        # re-expands the body locally, evaluating them with ITS clock/
        # identity/state (a wrong answer, not a stale one)
        try:
            schema = session.catalog.schema(t.db or session.current_db)
        except KeyError:
            return False  # unresolvable reference: let the leader err
        if t.name.lower() in getattr(schema, "views", {}):
            return False
    if _has_nonroutable_funcs(stmt):
        return False
    return True


def _candidates(storage, read_ts: int, max_staleness_ms: int,
                self_addr: str) -> tuple[list[dict], int]:
    """(ordered routing candidates, serving-replica count). A follower
    is a candidate when it is serving, term-clean, and either already
    covers read_ts or is fresh enough (lag within the staleness cap)
    that its bounded ReadIndex-style wait will cover it."""
    from .diag import cluster_members
    try:
        members = cluster_members(storage, budget_ms=500)
    except Exception:  # noqa: BLE001 — membership trouble = no routing
        return [], 0
    serving = []
    for m in members:
        if not isinstance(m, dict) or m.get("down"):
            continue
        if m.get("role") != "follower" or not m.get("serving"):
            continue
        addr = str(m.get("addr") or "")
        if not addr or addr == self_addr:
            continue
        serving.append(m)
    cands = []
    for m in serving:
        applied = int(m.get("applied_ts") or 0)
        lag = m.get("apply_lag_ms")
        covered = applied >= read_ts
        fresh = lag is not None and float(lag) <= max_staleness_ms
        if covered or fresh:
            m = dict(m)
            m["_covered"] = covered
            cands.append(m)
    # replicas that ALREADY cover read_ts come first: an uncovered
    # candidate costs the serve-side bounded wait even on success, and
    # a lagging-but-"fresh" one may burn the whole wait before the
    # fallback — never pay that ahead of a replica that can answer now
    cands.sort(key=lambda m: (not m["_covered"],
                              int(m.get("load") or 0),
                              float(m.get("hb_age_s") or 0.0)))
    return cands, len(serving)


def _range_spans(session, stmt) -> Optional[list]:
    """[start, end) row-key spans of every base table the statement
    touches (kv/tablecodec.table_range), or None when one cannot be
    resolved — then the range gate is inapplicable and routing behaves
    exactly as without it (the leader errors on the real problem)."""
    from ..kv.tablecodec import table_range
    try:
        tables = session._collect_table_names(stmt)
    except Exception:  # noqa: BLE001 — gate is advisory, never fatal
        return None
    spans = []
    for t in tables:
        try:
            schema = session.catalog.schema(t.db or session.current_db)
        except KeyError:
            return None
        info = schema.tables.get(t.name.lower())
        if info is None:
            return None
        spans.append(table_range(int(info.id)))
    return spans or None


def _range_gate(storage, spans, read_ts: int,
                budget_s: float = 1.0) -> Optional[dict]:
    """Range-aware coverage check: the statement's COVERED timestamp is
    the min published closed_ts over every range its spans touch; a
    read above it may observe a torn cross-range transaction on a
    replica (a participant range's secondaries not yet durable), so
    the router refuses to ship it. Waits bounded (heartbeats publish
    every lease tick) under the `covered_ts` wait state, then reports
    which ranges still gate. None = no range plane armed here."""
    plane = getattr(storage, "ranges", None)
    if plane is None:
        return None

    def probe() -> dict:
        per: dict[int, int] = {}
        for start, end in spans:
            for rid, closed in plane.closed_over(start, end):
                per[rid] = closed
        return per

    t0 = time.perf_counter()
    per = probe()
    if not per:
        return None
    gated = sorted((rid, ts) for rid, ts in per.items()
                   if ts < read_ts)
    waited = 0.0
    if gated:
        with obs.wait("covered_ts"):
            deadline = t0 + budget_s
            while time.perf_counter() < deadline:
                time.sleep(0.02)
                per = probe()
                gated = sorted((rid, ts) for rid, ts in per.items()
                               if ts < read_ts)
                if not gated:
                    break
        waited = (time.perf_counter() - t0) * 1e3
    return {"covered": not gated, "gated": gated, "n": len(per),
            "waited_ms": waited}


def try_route(session, stmt, sql: Optional[str],
              has_vars: bool = False,
              expect_cols: Optional[int] = None) -> Optional[RoutedRead]:
    """Route one SELECT to a replica, or return None to execute on the
    leader (the caller's unchanged local path). Never raises for
    transport/staleness reasons — fallback is the contract."""
    storage = session.storage
    st = getattr(storage, "replica_read", None)
    if st is None or not st.enabled:
        return None
    from ..session.session import SQLError

    def var(name, default):
        try:
            v = session._sysvar_value(name)
            return default if v is None or v == "" else v
        except (TypeError, ValueError, SQLError):
            return default

    mode = str(var("tidb_replica_read", "leader")).lower()
    try:
        staleness_s = int(var("tidb_read_staleness", 0))
    except (TypeError, ValueError):
        staleness_s = 0
    want = mode == "follower" or st.prefer_follower or staleness_s < 0
    if not want or not _eligible(session, stmt, sql, has_vars):
        return None
    txn = session._ensure_txn()
    read_ts = txn.start_ts
    if staleness_s < 0:
        # bounded staleness (tidb_read_staleness semantics: -5 = up to
        # 5s stale), capped by replica-read.max-staleness-ms; the LOCAL
        # fallback reads at the same ts so routed and leader answers
        # are the same snapshot either way
        stale_ms = min(-staleness_s * 1000, st.max_staleness_ms)
        stale_ts = ts_at_physical_ms(int(time.time() * 1000) - stale_ms)
        read_ts = min(read_ts, stale_ts)
        txn.stmt_read_ts = read_ts  # cleared by _exec_select's finally
    self_addr = getattr(storage, "diag_address", "") or ""
    cands, n_serving = _candidates(storage, read_ts,
                                   st.max_staleness_ms, self_addr)
    if n_serving == 0:
        return None  # no serving tier: not a replica-read situation
    term = cluster_term(storage)
    counter = storage.obs.replica_reads
    if getattr(st, "range_aware", False):
        spans = _range_spans(session, stmt)
        gate = _range_gate(storage, spans, read_ts) if spans else None
        if gate is not None and not gate["covered"]:
            # typed fallback, same contract as replica staleness: the
            # leader serves the identical snapshot. The gating ranges
            # land in the engine tags (EXPLAIN ANALYZE / last_engines)
            # so "why didn't this route" is answerable per statement.
            for rid, ts in gate["gated"][:8]:
                obs.note_engine(f"range#{rid}@gated")
            counter.inc(outcome="stale_fallback")
            why = ", ".join(f"range#{rid} closed_ts={ts}"
                            for rid, ts in gate["gated"][:4])
            session.add_warning(
                f"replica read fell back to the leader "
                f"(stale_fallback): read_ts {read_ts} uncovered on "
                f"{len(gate['gated'])}/{gate['n']} ranges: {why}"[:512],
                level="Note")
            return None
        if gate is not None and gate["waited_ms"] > 1.0:
            obs.note_engine(f"ranges@covered(n={gate['n']},"
                            f"wait={gate['waited_ms']:.0f}ms)")
    stale_reason: Optional[str] = None
    unreachable_reason: Optional[str] = None
    from .diag import _peer_client
    for m in cands:
        addr = str(m["addr"])
        client = _peer_client(storage, addr)
        if client.breaker_state == "open":
            # the satellite bugfix: an OPEN breaker means this peer
            # already burned its budgets — fail over to the next
            # candidate immediately instead of rediscovering it
            unreachable_reason = f"{addr}: rpc circuit breaker open"
            continue
        t0 = time.perf_counter()
        try:
            with obs.stage("replica_read", span_name="replica.read"):
                r = client.call(
                    "diag_replica_read", sql=sql,
                    db=session.current_db or "", read_ts=read_ts,
                    term=term,
                    time_zone=str(var("time_zone", "SYSTEM")),
                    _budget_ms=min(client.options.backoff_budget_ms,
                                   4000))
        except (ReplicaStaleError, StaleTermError) as e:
            stale_reason = f"{addr}: {e}"
            continue
        except RPCError as e:
            unreachable_reason = f"{addr}: {type(e).__name__}: {e}"
            continue
        from ..util import interrupt
        interrupt.check()  # a KILL during the remote wait lands here
        cols = list(r.get("cols", []))
        if expect_cols is not None and len(cols) != expect_cols:
            # result shape disagrees with the local plan (schema drift
            # mid-flight): treat like staleness and fail over — and do
            # it BEFORE counting/tagging, or the local re-execution
            # would read as a served replica read
            stale_reason = (f"{addr}: replica answered {len(cols)} "
                            f"columns, local plan expects {expect_cols}")
            continue
        rows = [tuple(unwire_value(v) for v in row)
                for row in r.get("rows", [])]
        counter.inc(outcome="served")
        obs.note_engine(f"replica@{addr}")
        return RoutedRead(rows=rows, cols=cols, addr=addr,
                          read_ts=read_ts,
                          wall_ms=(time.perf_counter() - t0) * 1e3)
    # typed fallback: the leader serves, the reason is queryable
    if unreachable_reason is not None and stale_reason is None:
        outcome, why = "unreachable_fallback", unreachable_reason
    else:
        outcome = "stale_fallback"
        why = stale_reason or \
            f"no replica closed past read_ts {read_ts} " \
            f"({n_serving} serving)"
    counter.inc(outcome=outcome)
    session.add_warning(
        f"replica read fell back to the leader ({outcome}): {why}"[:512],
        level="Note")
    return None


# ---- surfaces ---------------------------------------------------------------

def debug_payload(storage) -> dict:
    """The /debug/replicas JSON: router knobs, per-member serving
    state, the local apply engine (followers), and the outcome
    counters — the one page that answers 'why is nothing routing'."""
    st = getattr(storage, "replica_read", None)
    out: dict = {
        "enabled": bool(st is not None and st.enabled),
        "prefer_follower": bool(st is not None and st.prefer_follower),
        "max_staleness_ms": st.max_staleness_ms if st is not None else 0,
        "range_aware": bool(st is not None
                            and getattr(st, "range_aware", False)),
        "term": cluster_term(storage),
    }
    try:
        from .diag import cluster_members
        members = []
        for m in cluster_members(storage, budget_ms=500):
            m = dict(m)
            addr = str(m.get("addr") or "")
            c = storage._diag_clients.get(addr)
            if c is not None:
                m["breaker"] = c.breaker_state
            members.append(m)
        out["members"] = members
    except Exception as e:  # noqa: BLE001 — scrape survives
        out["members_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    eng = getattr(storage, "apply_engine", None)
    if eng is not None:
        out["apply"] = eng.info()
    out["reads"] = {
        outcome: storage.obs.replica_reads.get(outcome=outcome)
        for outcome in ("served", "stale_fallback",
                        "unreachable_fallback")}
    return out


__all__ = ["ReplicaReadState", "RoutedRead", "try_route",
           "serve_replica_read", "wire_value", "unwire_value",
           "cluster_term", "debug_payload", "NONROUTABLE_FUNCS"]
