"""RPC client: typed retries, timeouts, reconnect, failpoint edges.

Counterpart of the reference's RPC client + retry plumbing (reference:
store/tikv/client.go sendRequest, region_request.go sendReqToRegion —
every send runs under a Backoffer, transport errors reconnect and
retry as boTiKVRPC, and exhaustion surfaces the typed history). Four
failpoint sites cover the transport edges chaos tests sever:

  rpc/conn-drop      — the connection dies before the request is sent
  rpc/delay          — latency injection ahead of the send
  rpc/partial-write  — the frame tears mid-write (half a header on the
                       wire), then the connection dies
  rpc/stale-response — a duplicated earlier response arrives first and
                       must be discarded by request-id matching

Retryable failures are OS/socket errors and timeouts; application
errors (a CodedError raised by a handler) are re-raised typed and are
NEVER retried here — idempotency of the retried ops is the server's
contract (WAL appends dedup on a client-assigned sequence)."""

from __future__ import annotations

import socket
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Optional

from .. import obs
from ..errno import CodedError
from ..kv.backoff import BO_RPC, Backoffer, BackoffExhausted
from ..util import failpoint
from .errors import WIRE_ERRORS, LeaderUnavailable, RPCError, \
    StaleTermError
from .frame import (TRACE_KEY, FrameError, decode, encode, make_trace_ctx,
                    parse_addr, recv_frame, send_frame)


@dataclass
class RpcOptions:
    """Transport knobs (config [transport] section; reference: the
    tikv-client timeouts in config.go TiKVClient)."""

    connect_timeout_ms: int = 1000
    request_timeout_ms: int = 5000
    # per-call retry budget; exhaustion raises LeaderUnavailable with
    # the typed history
    backoff_budget_ms: int = 4000
    # mutation-lock acquisition budget (lock waits are long-lived and
    # budgeted separately from transport retries)
    lock_budget_ms: int = 30000
    # leader-granted lease horizon; heartbeats renew it, and a grant
    # whose holder missed it is force-released (fencing tokens protect
    # the WAL from the deposed holder)
    lease_ms: int = 3000
    # degraded mode: serve reads at the last replicated timestamp when
    # the leader is unreachable (writes always fail typed)
    stale_reads: bool = True
    # max bytes per wal_tail response
    tail_chunk: int = 4 << 20
    # address a follower's diag listener binds (the per-server
    # diagnostics endpoint peers query for cluster_* tables)
    diag_listen: str = "127.0.0.1:0"
    # automatic leader failover: a follower whose heartbeat has been
    # failing for this long runs the election (0 disables — followers
    # then degrade to read-only forever, the pre-failover behavior)
    election_timeout_ms: int = 0
    # address this follower serves coordination RPC on IF it wins an
    # election and promotes (the bound host:port is what surviving
    # peers repoint to, so on multi-host clusters use a routable host)
    promote_listen: str = "127.0.0.1:0"
    # circuit breaker: after this many CONSECUTIVE calls exhausted
    # their transport-retry budget, fail fast for breaker-cooldown-ms
    # instead of burning a full BO_RPC budget per call, then let ONE
    # half-open probe through — success closes the breaker, failure
    # re-opens it (0 disables; application errors never count)
    breaker_threshold: int = 3
    breaker_cooldown_ms: int = 2000


class RpcClient:
    """One logical peer connection with transparent reconnect.

    Thread-safe: one in-flight request at a time (the reference batches
    concurrent requests onto one stream, client_batch.go; serializing
    is the same correctness with less machinery). The heartbeat runs on
    its OWN socket so lease renewal never queues behind a slow call."""

    def __init__(self, addr, options: Optional[RpcOptions] = None,
                 client_id: Optional[str] = None,
                 _heartbeat: bool = True) -> None:
        self.addr = addr
        self.options = options or RpcOptions()
        self.client_id = client_id or uuid.uuid4().hex
        self._mu = threading.RLock()
        self._sock: Optional[socket.socket] = None
        self._req_id = 0
        self._last_resp: Optional[bytes] = None  # stale-response replay
        self._closed = False
        # transport health (surfaced on the status port)
        self.calls = 0
        self.retries = 0
        self.degraded = False
        self.last_contact = 0.0
        # circuit breaker state: consecutive budget-exhausted calls;
        # while >= threshold the breaker is OPEN until the cooldown
        # deadline, then HALF-OPEN (one probe call allowed through)
        self._bk_lock = threading.Lock()
        self._bk_streak = 0
        self._bk_open_until = 0.0
        self._bk_probe = False
        # structured event sink (obs.EventLog): the owning Storage
        # wires its per-server ring so trips/recoveries are queryable
        # via information_schema.tidb_events after the fact
        self.events = None
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._hb_client: Optional["RpcClient"] = None
        self._want_heartbeat = _heartbeat
        # highest cluster fencing term witnessed (hello/ping responses
        # carry it); fenced requests attach it, and a peer answering
        # with a LOWER term is a deposed leader — calls to it fail
        # typed so the caller re-resolves instead of split-braining
        self.term = 0
        # extra params the heartbeat ping carries on every beat — the
        # diag plane rides this to (re)register the follower's diag
        # listener with the leader's membership registry, so a leader
        # restart relearns the cluster shape within one lease interval
        self.ping_params: dict = {}

    # ---- connection management --------------------------------------------
    def _connect(self) -> socket.socket:
        fam, target = parse_addr(self.addr)
        s = socket.socket(fam, socket.SOCK_STREAM)
        s.settimeout(self.options.connect_timeout_ms / 1000.0)
        try:
            s.connect(target)
        except OSError:
            s.close()
            raise
        s.settimeout(self.options.request_timeout_ms / 1000.0)
        if fam == socket.AF_INET:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # ---- the call path -----------------------------------------------------
    def call(self, method: str, _budget_ms: Optional[int] = None,
             **params: Any) -> dict:
        """One request with typed-retry semantics. Transport failures
        reconnect and retry under BO_RPC until the budget is spent;
        exhaustion raises LeaderUnavailable carrying the history and
        flips the client into degraded mode."""
        self._breaker_gate(method)
        bo = Backoffer(budget_ms=_budget_ms
                       if _budget_ms is not None
                       else self.options.backoff_budget_ms)
        last: Optional[BaseException] = None
        while True:
            if self._closed:
                raise RPCError("rpc client closed")
            t0 = time.monotonic()
            try:
                try:
                    # rpc_net is the CATCH-ALL wait state: network time
                    # not already typed by a more specific enclosing
                    # frame (a 2PC phase, tso_wait, resolve_lock) —
                    # fallback=True keeps the frame a no-op under one,
                    # so the specific state owns its wire time
                    with obs.wait("rpc_net", fallback=True):
                        r = self._call_once(method, params)
                except (OSError, FrameError, FrameProtocolError):
                    raise
                except BaseException:
                    # an application error (typed handler error, stale
                    # term) rode a COMPLETED round-trip: the transport
                    # is healthy, so the breaker counts it as success
                    self._breaker_note(ok=True)
                    raise
                self.degraded = False
                self.last_contact = time.monotonic()
                self._breaker_note(ok=True)
                return r
            except (OSError, FrameError, FrameProtocolError) as e:
                # covers ConnectionError, socket.timeout, refused, reset
                last = e
                self._drop_conn()
                self.retries += 1
                try:
                    # time burned BLOCKED in connect/read timeouts
                    # counts against the same budget as the sleeps — a
                    # stalled (not refusing) leader must exhaust in
                    # ~budget wall time, not timeout x attempts
                    bo.charge(BO_RPC, time.monotonic() - t0)
                    bo.sleep(BO_RPC)
                except BackoffExhausted as exhausted:
                    self.degraded = True
                    self._breaker_note(ok=False)
                    raise LeaderUnavailable(
                        f"rpc {method} to {self.addr!r} failed: "
                        f"{last!r}; {exhausted}") from None

    # ---- circuit breaker ---------------------------------------------------
    # (reference: the client-go region-cache's store liveness slow-score
    # gate; classic Nygard breaker states). Counted per CALL, not per
    # attempt: one exhausted BO_RPC budget = one failure, so a transient
    # blip inside a single call's retry window never trips it.
    def _breaker_gate(self, method: str) -> None:
        """Raise LeaderUnavailable immediately while the breaker is
        open; claim the single half-open probe slot after cooldown."""
        if self.options.breaker_threshold <= 0:
            return
        with self._bk_lock:
            if self._bk_streak < self.options.breaker_threshold:
                return
            now = time.monotonic()
            if now < self._bk_open_until:
                wait_s = self._bk_open_until - now
            elif self._bk_probe:
                wait_s = None  # half-open, probe slot taken
            else:
                self._bk_probe = True  # this call IS the probe
                return
        obs.RPC_BREAKER_FAST_FAILS.inc()
        self.degraded = True
        if wait_s is not None:
            raise LeaderUnavailable(
                f"rpc {method} to {self.addr!r}: circuit breaker open "
                f"after {self._bk_streak} consecutive transport "
                f"failures; half-open probe in {wait_s:.2f}s")
        raise LeaderUnavailable(
            f"rpc {method} to {self.addr!r}: circuit breaker "
            f"half-open, probe already in flight")

    def _breaker_note(self, ok: bool) -> None:
        if self.options.breaker_threshold <= 0:
            return
        tripped = recovered = False
        with self._bk_lock:
            self._bk_probe = False
            if ok:
                recovered = \
                    self._bk_streak >= self.options.breaker_threshold
                self._bk_streak = 0
            else:
                self._bk_streak += 1
                if self._bk_streak >= self.options.breaker_threshold:
                    self._bk_open_until = time.monotonic() \
                        + self.options.breaker_cooldown_ms / 1000.0
                    if self._bk_streak == self.options.breaker_threshold:
                        obs.RPC_BREAKER_TRIPS.inc()
                        tripped = True
            streak = self._bk_streak  # snapshot: the event detail must
            # not re-read it unlocked (a racing call could have moved it)
        # event emission OUTSIDE the breaker lock (the sink takes its
        # own lock; no reason to nest them)
        if tripped and self.events is not None:
            self.events.record(
                "breaker_trip", severity="warn",
                detail=f"rpc to {self.addr}: {streak} "
                       f"consecutive transport failures; failing fast "
                       f"for {self.options.breaker_cooldown_ms}ms")
        elif recovered and self.events is not None:
            self.events.record(
                "breaker_recover",
                detail=f"rpc to {self.addr}: half-open probe "
                       "succeeded, breaker closed")

    def _breaker_reset(self) -> None:
        with self._bk_lock:
            self._bk_streak = 0
            self._bk_open_until = 0.0
            self._bk_probe = False

    @property
    def breaker_state(self) -> str:
        with self._bk_lock:
            if self.options.breaker_threshold <= 0 or \
                    self._bk_streak < self.options.breaker_threshold:
                return "closed"
            if time.monotonic() < self._bk_open_until:
                return "open"
            return "half-open"

    def _call_once(self, method: str, params: dict) -> dict:
        # cross-server trace propagation: under an active TRACE the
        # request carries (trace_id, parent_span_id) and the peer's span
        # rows come back in the response to be stitched under this rpc
        # span — the hop stops being an opaque wall-clock gap
        coll = obs.active_collector()
        spctx = obs.span(f"rpc.{method}")
        sp = spctx.__enter__()
        try:
            resp = self._roundtrip(method, params, coll, sp)
        finally:
            spctx.__exit__(None, None, None)
        if sp is not None and coll is not None:
            remote_rows = resp.get("sp")
            if remote_rows:
                obs.stitch_remote_rows(coll, sp, remote_rows)
        err = resp.get("err")
        if err is not None:
            cls = WIRE_ERRORS.get(err.get("type"), CodedError)
            raise cls(err.get("msg", "rpc error"),
                      errno=err.get("errno"))
        r = resp.get("r") or {}
        t = r.get("term") if isinstance(r, dict) else None
        if isinstance(t, int) and t > 0:
            if t < self.term:
                # the peer lives in a fenced epoch: a restarted deposed
                # leader. NOT retryable against this address — the
                # caller must re-resolve the current leader.
                raise StaleTermError(
                    f"peer {self.addr!r} serves term {t} but the "
                    f"cluster is at term {self.term} (deposed leader)")
            if t > self.term:
                self.term = t
        return r

    def _roundtrip(self, method: str, params: dict, coll, sp) -> dict:
        with self._mu:
            if self._sock is None:
                self._sock = self._connect()
            sock = self._sock
            # -- transport-edge failpoints (armed by chaos tests) --
            v = failpoint.inject("rpc/conn-drop")
            if v:
                self._drop_conn()
                raise ConnectionResetError("failpoint rpc/conn-drop")
            d = failpoint.inject("rpc/delay")
            if isinstance(d, (int, float)) and not isinstance(d, bool) \
                    and d > 0:
                time.sleep(float(d))
            self._req_id += 1
            req_id = self._req_id
            self.calls += 1
            req = {"id": req_id, "m": method, "p": params,
                   "c": self.client_id}
            if sp is not None and coll is not None:
                # the rpc span carries its Dapper span id; the remote
                # root notes the same id as parent_span_id, so the two
                # halves of the hop are linkable in the rendered tree
                span_id = coll.alloc_span_id()
                sp.note = f"span_id={span_id}"
                req[TRACE_KEY] = make_trace_ctx(coll.trace_id, span_id)
            payload = encode(req)
            self._send(sock, payload)
            # evaluated ONCE per request: a persistently-enabled point
            # must inject one duplicated response, not starve the real
            # read forever
            stale = failpoint.inject("rpc/stale-response")
            while True:
                if stale and self._last_resp is not None:
                    raw, stale = self._last_resp, None  # old response
                else:
                    raw = recv_frame(sock)
                try:
                    resp = decode(raw)
                except Exception as e:  # torn/corrupt payload
                    raise FrameProtocolError(str(e)) from None
                if not isinstance(resp, dict) \
                        or resp.get("id") != req_id:
                    # stale or duplicated response: discard and keep
                    # reading — request ids fence every reply
                    continue
                # retained only while the chaos point is armed: keeping
                # every response would pin a full tail chunk per client
                if failpoint.is_enabled("rpc/stale-response"):
                    self._last_resp = raw
                return resp

    def _send(self, sock: socket.socket, payload: bytes) -> None:
        cut = failpoint.inject("rpc/partial-write")
        if cut:
            import struct as _struct
            data = _struct.pack("<I", len(payload)) + payload
            try:
                sock.sendall(data[:max(1, len(data) // 2)])
            finally:
                self._drop_conn()
            raise ConnectionResetError("failpoint rpc/partial-write")
        send_frame(sock, payload)

    # ---- liveness ----------------------------------------------------------
    def start_heartbeat(self) -> None:
        """Lease keepalive on a dedicated socket (reference: the
        store's liveness probes; oracle lease renewal in pd.go). Ping
        failures flip `degraded`; the next success clears it — that
        transition is what lets a follower recover automatically."""
        if not self._want_heartbeat or self._hb_thread is not None:
            return
        hb = RpcClient(self.addr, self.options,
                       client_id=self.client_id, _heartbeat=False)
        self._hb_client = hb
        interval = max(0.2, self.options.lease_ms / 3000.0)

        def beat() -> None:
            while not self._hb_stop.wait(interval):
                try:
                    if hb.addr != self.addr:
                        # the parent repointed to a promoted leader:
                        # the keepalive must follow or the lease renews
                        # against the corpse
                        hb.addr = self.addr
                        hb._drop_conn()
                    hb.term = max(hb.term, self.term)
                    hb.call("ping", _budget_ms=min(
                        self.options.backoff_budget_ms, 500),
                        **self.ping_params)
                    self.term = max(self.term, hb.term)
                    self.degraded = False
                    self.last_contact = time.monotonic()
                except RPCError:
                    # covers StaleTermError too: a deposed leader's
                    # pings must read as leader loss, not liveness
                    self.degraded = True
            hb.close()

        self._hb_thread = threading.Thread(
            target=beat, name="titpu-rpc-heartbeat", daemon=True)
        self._hb_thread.start()

    def repoint(self, addr, term: int = 0) -> None:
        """Re-resolve this client to a newly promoted leader: swap the
        address, adopt the new term, drop the dead connection, and clear
        the degraded latch so the next call goes straight through."""
        with self._mu:
            self.addr = addr
            if term:
                self.term = max(self.term, int(term))
            self._drop_conn()
        # a fresh leader deserves a closed breaker: the open state was
        # earned by the corpse this client just stopped talking to
        self._breaker_reset()
        self.degraded = False

    def health(self) -> dict:
        return {
            "peer": str(self.addr),
            "degraded": self.degraded,
            "calls": self.calls,
            "retries": self.retries,
            "breaker": self.breaker_state,
            "breaker_fail_streak": self._bk_streak,
            "last_contact_age_s": round(
                time.monotonic() - self.last_contact, 3)
            if self.last_contact else None,
        }

    def close(self) -> None:
        self._closed = True
        self._hb_stop.set()
        hb, t = self._hb_client, self._hb_thread
        if hb is not None:
            # wake a beat blocked in connect/recv (the accept-waking
            # pattern the listeners use): mark closed and tear the
            # socket down under the hb client's own lock-free path —
            # shutdown() interrupts a blocked recv immediately
            hb._closed = True
            s = hb._sock
            if s is not None:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
        if t is not None:
            t.join(timeout=5.0)
            self._hb_thread = None
            self._hb_client = None
        with self._mu:
            self._drop_conn()


class FrameProtocolError(Exception):
    """Client-side wrapper for torn/corrupt payloads: retried like a
    connection failure (the stream is unusable either way)."""


__all__ = ["RpcClient", "RpcOptions", "FrameProtocolError"]
