"""Automatic leader failover: detection, election, fenced promotion.

The socket cluster has ONE leader (the Storage that owns the durable
directory and serves coordination RPC). Before this module existed,
leader death left followers in degraded read-only mode forever. Now
every follower runs a FailoverManager:

* DETECT — the heartbeat (rpc/client.py) flips `degraded` on ping
  failure; a follower continuously degraded past the election timeout
  considers the leader dead (reference analog: raft election timeout,
  Ongaro & Ousterhout §5.2).

* ELECT — deterministic, no ballots: among the live members of the
  leader's diag registry (each polled over its diag endpoint for
  `diag_election`), the follower with the LONGEST replicated WAL
  position wins; ties break to the LOWEST node id. Every live voter
  computes the same winner from the same frozen positions (the dead
  leader no longer advances anyone), so the protocol needs no rounds —
  the raft up-to-date rule collapsed onto a total order.

* PROMOTE — the winner promotes IN PLACE (store/storage.py
  promote_to_leader): it re-opens its on-disk WAL mirror as the
  authoritative (snapshot, WAL) pair, bumps the fencing term, persists
  it, and starts serving coordination RPC on its promote-listen
  address. Because every follower's mirror is a byte-prefix of the dead
  leader's file, survivors repoint and keep tailing from their own
  offsets — no re-bootstrap.

* FENCE — the bumped term rejects the zombies: a client still carrying
  the old term has wal_append/lock_acquire refused (StaleTermError),
  and a restarted old leader answers with its stale term, which peers
  treat as leader loss, not liveness (rpc/client.py term checks).

Known loss window (documented in README): replication is PULL-based —
the dead leader may hold acked commits no follower tailed yet. Those
are on the old leader's durable disk (sync-log) but not on the new
leader; a restarted old leader must re-join as a follower with a fresh
working dir rather than serve its divergent tail. Quorum is also not
required: in a full network partition both sides can elect, exactly
like any non-quorum failover — deploy followers accordingly.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class FailoverManager:
    """Per-follower election driver. Started by Storage for socket
    followers when options.election_timeout_ms > 0; close() joins the
    thread (the no-leaked-threads contract every listener follows)."""

    def __init__(self, storage, options) -> None:
        self.storage = storage
        self.options = options
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._degraded_since: Optional[float] = None
        # consecutive failed diag polls per peer: a peer leaves the
        # electorate only after PEER_STRIKES misses, so one dropped
        # poll under load cannot shrink the voter roll and let two
        # followers both compute themselves the winner (split brain)
        self._peer_fails: dict = {}
        # observability (surfaced via transport_health)
        self.state = "healthy"
        self.elections = 0
        self.last_result = ""

    PEER_STRIKES = 3

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="titpu-failover", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def describe(self) -> dict:
        return {"state": self.state, "elections": self.elections,
                "last_result": self.last_result,
                "timeout_ms": self.options.election_timeout_ms}

    # ---- the watch loop ----------------------------------------------------
    def _loop(self) -> None:
        interval = max(0.2, self.options.lease_ms / 2000.0)
        refresh_every = max(1.0, self.options.lease_ms / 1000.0)
        last_refresh = 0.0
        while not self._stop.wait(interval):
            st = self.storage
            if not getattr(st, "remote", False):
                self.state = "promoted"
                return  # we are the leader now; nothing to watch
            client = st._rpc_client
            if client is None or client._closed:
                return
            now = time.monotonic()
            if not client.degraded:
                self._degraded_since = None
                self.state = "healthy"
                if now - last_refresh >= refresh_every:
                    # keep the membership view warm: it is the voter
                    # roll once the leader stops answering
                    try:
                        from .diag import cluster_members
                        cluster_members(st, budget_ms=500)
                    except Exception:  # noqa: BLE001
                        pass
                    last_refresh = now
                continue
            if self._degraded_since is None:
                self._degraded_since = now
                self.state = "degraded"
                continue
            if (now - self._degraded_since) * 1000.0 < \
                    self.options.election_timeout_ms:
                continue
            self.state = "electing"
            try:
                if self._run_election():
                    self._degraded_since = None
            except Exception as e:  # noqa: BLE001 — never kill the loop
                self.last_result = f"election error: {e}"[:200]

    # ---- one election round ------------------------------------------------
    def _candidacy(self) -> tuple[int, int]:
        st = self.storage
        engine = st.kv.kv
        return (int(getattr(engine, "_applied_off", 0)),
                int(getattr(st.coord, "node_id", 0) or 0))

    def _run_election(self) -> bool:
        """One deterministic round. Returns True when resolved (promoted
        or repointed); False re-arms the next poll tick — the computed
        winner may still be mid-promotion."""
        from .diag import _peer_client

        st = self.storage
        client = st._rpc_client
        self.elections += 1
        my_pos, my_id = self._candidacy()
        if st._last_members is None:
            # the voter roll was NEVER learned (the leader died inside
            # the join window): electing against an unknown electorate
            # means electing unopposed while unseen peers do the same.
            # Stay degraded; an operator (or a returning leader) must
            # resolve this one.
            self.last_result = "no membership view: refusing to elect"
            return False
        members = list(st._last_members)
        peers = [m for m in members
                 if m.get("role") != "leader" and m.get("addr")
                 and m.get("addr") != st.diag_address]
        votes = [(my_pos, my_id)]
        unresolved = False
        for m in peers:
            addr = str(m["addr"])
            try:
                r = _peer_client(st, addr).call(
                    "diag_election", _budget_ms=1500)
            except Exception:  # noqa: BLE001
                n = self._peer_fails.get(addr, 0) + 1
                self._peer_fails[addr] = n
                if n < self.PEER_STRIKES:
                    # maybe just a dropped poll: without its vote the
                    # winner computation could disagree with the
                    # peer's own — hold the election open this round
                    unresolved = True
                continue  # struck out: dead peer, not an elector
            self._peer_fails.pop(addr, None)
            term = int(r.get("term", 0) or 0)
            leader_addr = str(r.get("leader_addr") or "")
            if leader_addr and term > client.term:
                # someone already promoted (term bumped past ours):
                # adopt, don't re-elect
                st.repoint_leader(leader_addr, term)
                self.state = "repointed"
                self.last_result = \
                    f"repointed to {leader_addr} (term {term})"
                return True
            if r.get("role") == "follower":
                votes.append((int(r.get("wal_pos", 0) or 0),
                              int(r.get("node_id", 0) or 0)))
            elif not leader_addr:
                # transitional peer (mid-promotion, or a role we do
                # not recognize): neither a vote nor an exclusion —
                # hold the election open until it settles
                unresolved = True
        if unresolved:
            self.last_result = "election held open: peer poll failed " \
                               "(retrying before shrinking the roll)"
            return False
        # longest replicated WAL wins; ties to the lowest node id —
        # every live voter reaches the same answer from the same data
        win_pos, win_id = max(votes, key=lambda v: (v[0], -v[1]))
        if (win_pos, win_id) == (my_pos, my_id):
            addr = st.promote_to_leader(
                listen=self.options.promote_listen)
            self.state = "promoted"
            self.last_result = f"promoted at {addr} " \
                               f"(term {st.rpc_server.term})"
            return True
        self.last_result = \
            f"waiting for node {win_id} (wal {win_pos}) to promote"
        return False


__all__ = ["FailoverManager"]
