"""Hash/range-partition exchange over the mesh: the all_to_all data plane.

The reference's MPP tier has two exchange modes — broadcast and hash
partition (reference: planner/core/fragment.go:45 ExchangeSender types,
store/tikv/mpp.go:372 dispatch; TiFlash moves rows node->node over gRPC).
The TPU translation routes rows between devices with ONE XLA collective:
each device buckets its rows by destination, lays them out as a
[n_dev, capacity] send buffer, and `jax.lax.all_to_all` transposes the
device/bucket axes over ICI. Static shapes throughout: capacity is fixed
at trace time, and skew beyond it sets an overflow flag (psum'd to every
device) that the host turns into a fallback — never silent truncation.

Used by parallel/dist.py for:
* high-cardinality GROUP BY: route rows by group-key hash so every group
  lands wholly on one device, then run the per-device sorted-run
  candidate aggregation (copr/hcagg.py) on disjoint group partitions;
* partitioned (non-broadcast) joins: route probe rows by join-key range
  to the device owning that build shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mix_hash(keys: list[jnp.ndarray]) -> jnp.ndarray:
    """Deterministic int32 mix of one or more int32 key arrays (same key
    tuple -> same value on every device; wrapping int32 mul is fine)."""
    h = jnp.zeros_like(keys[0])
    for k in keys:
        h = h * jnp.int32(-1640531527) + k  # 0x9E3779B9 golden ratio
        h = h ^ (h >> 15)
    h = h * jnp.int32(-2048144789)  # 0x85EBCA6B murmur mix
    h = h ^ (h >> 13)
    return h


def capacity_for(m: int, n_dev: int, slack: float = 2.0) -> int:
    """Per-(device,dest) send capacity: expected m/n_dev rows with slack.
    Overflow under adversarial skew is detected, not truncated."""
    c = int(m * slack) // n_dev + 1
    return max(64, min(c, m))


def route_cols(dest, cols, mask, axis: str, n_dev: int, capacity: int):
    """route_rows over a fragment column list: packs [(data, valid), ...]
    plus the row mask, routes, and unpacks. Shared by the group-partition
    (hc) and join-partition exchanges."""
    payload: list = [mask]
    for d, v in cols:
        payload.append(d)
        payload.append(v)
    recv, recv_valid, overflow = route_rows(dest, payload, axis, n_dev,
                                            capacity)
    new_mask = recv[0] & recv_valid
    new_cols = [(recv[1 + 2 * i], recv[2 + 2 * i]) for i in range(len(cols))]
    return new_cols, new_mask, overflow


def route_rows(
    dest: jnp.ndarray,
    payload: list[jnp.ndarray],
    axis: str,
    n_dev: int,
    capacity: int,
):
    """Send row i of every payload array to device dest[i].

    Per-device view (inside shard_map): dest int32[m] in [0, n_dev);
    payload arrays shaped [m]. Returns (recv_payload, recv_valid,
    overflow) where recv arrays are [n_dev * capacity] (concatenated by
    source device), recv_valid marks real rows vs padding, and overflow
    is a replicated int32 >0 if ANY device overflowed a bucket.

    The layout pass is gather-only (sort + searchsorted + takes) — no
    scatter, so it maps cleanly onto the TPU's vector units.
    """
    m = dest.shape[0]
    iota = jnp.arange(m, dtype=jnp.int32)
    # stable sort by destination; perm brings payloads into dest order
    sd, perm = jax.lax.sort((dest, iota), num_keys=1, is_stable=True)
    start = jnp.searchsorted(sd, jnp.arange(n_dev, dtype=jnp.int32),
                             side="left").astype(jnp.int32)
    ends = jnp.append(start[1:], jnp.int32(m))
    counts = ends - start
    overflow = jnp.any(counts > capacity)

    slots = jnp.arange(n_dev * capacity, dtype=jnp.int32)
    d_idx = slots // capacity
    c_idx = slots % capacity
    src = jnp.clip(start[d_idx] + c_idx, 0, max(m - 1, 0))
    slot_valid = c_idx < counts[d_idx]

    def transpose(send):
        """[n_dev*capacity, ...] slot-space buffer -> received buffer."""
        send = send.reshape((n_dev, capacity) + send.shape[1:])
        recv = jax.lax.all_to_all(send, axis, 0, 0)
        return recv.reshape((n_dev * capacity,) + recv.shape[2:])

    def xch(x):
        return transpose(x[perm][src])  # row space -> slot space -> send

    recv_payload = [xch(x) for x in payload]
    # slot_valid is ALREADY slot-space: no row-permutation gather
    recv_valid = transpose(slot_valid)
    total_overflow = jax.lax.psum(overflow.astype(jnp.int32), axis)
    return recv_payload, recv_valid, total_overflow
