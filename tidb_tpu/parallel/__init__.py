from .dist import DistCopClient, make_mesh

__all__ = ["DistCopClient", "make_mesh"]
