"""Distributed coprocessor execution over a TPU mesh.

The multi-chip tier of the design (SURVEY.md §7 step 10): where the
reference fans coprocessor tasks out to TiKV regions over gRPC and runs MPP
exchanges between TiFlash nodes (reference: store/tikv/coprocessor.go:248
buildCopTasks; store/tikv/mpp.go:372 DispatchMPPTasks; exchange operators
from planner/core/fragment.go), the TPU framework shards the column epoch
across devices and lets XLA collectives do the exchange:

* scan fan-out (P1)  -> rows axis sharding of the padded column arrays
* partial aggregation (P2 partial stage) -> per-shard exact limb partials
* final merge (P2 final / P9 exchange)   -> psum/pmin/pmax over the mesh
  axis (ICI), all in native int32 — the limb partials are exact under
  addition (sumexact.py), so the collective needs no 64-bit emulation.

The partial layout is identical to the single-chip path, so the host final
stage is unchanged — it just receives partials that were already reduced
across devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs
from ..copr.client import CopClient

try:  # jax >= 0.5 exports shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # 0.4.x: the experimental home
    from jax.experimental.shard_map import shard_map

AXIS = "shard"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D data mesh over the given (or all) devices."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (AXIS,))


class DistCopClient(CopClient):
    """CopClient whose aggregation kernels run sharded over a device mesh.

    Row batches are padded to shape buckets (multiples of 256, so any
    power-of-two mesh divides them); each device reduces its row shard into
    the full dense segment space, then collectives over the mesh axis yield
    the global partials on every device. Inputs are placed with row-sharded
    NamedShardings so jit consumes them without host round-trips.
    """

    def __init__(self, mesh: Mesh) -> None:
        super().__init__()
        self.mesh = mesh
        self._n = mesh.devices.size

    def _build_agg_kernel(self, dag, prepared, cards, segments):
        body = self._agg_kernel_body(dag, prepared, cards, segments)
        sched = prepared["__agg_sched__"]

        def sharded(cols, row_mask):
            return _collective_merge(body(cols, row_mask), sched)

        # every output is replicated post-collective; a single P() acts
        # as a pytree prefix matching every leaf of the output dict
        mapped = shard_map(
            sharded,
            mesh=self.mesh,
            in_specs=(P(AXIS), P(AXIS)),
            out_specs=P(),
        )
        return jax.jit(mapped)

    def _bucket_size(self, n: int) -> int:
        """Round the shape bucket so the rows axis shards evenly AND each
        shard is a multiple of 8 rows — per-shard jnp.packbits pads to
        byte boundaries, and concatenating padded shard masks would shift
        every later shard's rows (seen at 64+ devices where lcm(256, n)
        alone leaves 4-row shards)."""
        b = super()._bucket_size(n)
        lcm = int(np.lcm(256, 8 * self._n))
        return -(-b // lcm) * lcm

    # staging placement: scan columns/masks shard on the rows axis at
    # CREATION time and the sharded arrays are what the caches hold, so
    # epochs stay device-resident across queries (re-placing per dispatch
    # was a mesh-wide transfer per fragment run). Build-table staging
    # (the TLS flag below) places REPLICATED instead — the broadcast-join
    # side every device gathers from. The placed arrays work for tiles
    # too: each TILE_ROWS slice is scanned by all devices.
    def _scan_sharding(self):
        if getattr(self._tls, "place_build", False):
            return NamedSharding(self.mesh, P())
        return NamedSharding(self.mesh, P(AXIS))

    def _note_broadcast(self, *arrays) -> None:
        """Replicating build arrays copies them to every other device —
        the dominant reshard-traffic component; counted HERE because
        placement happens at creation (the later _replicated() re-place
        is an identity and cannot see the broadcast)."""
        if getattr(self._tls, "place_build", False):
            n = sum(int(getattr(a, "nbytes", 0)) for a in arrays)
            obs.MESH_RESHARD_BYTES.inc(n * max(self._n - 1, 1))

    def _place_cols(self, data, valid):
        sharding = self._scan_sharding()
        build = getattr(self._tls, "place_build", False)
        with obs.stage("reshard" if build else "shard"):
            self._note_broadcast(data, valid)
            return (jax.device_put(data, sharding),
                    jax.device_put(valid, sharding))

    def _place_mask(self, mask):
        build = getattr(self._tls, "place_build", False)
        with obs.stage("reshard" if build else "shard"):
            self._note_broadcast(mask)
            return jax.device_put(mask, self._scan_sharding())

    # ---- fragment placement: probe shards, build tables replicate ------
    # (broadcast-join placement — the MPP broadcast exchange mode,
    # reference: planner/core/fragment.go broadcast vs hash partition)

    # hc GROUP BY shards via the group-partition exchange: joined rows
    # route by group-key hash (all_to_all) so each device owns whole
    # groups, then runs the sorted-run candidate path on its partition
    supports_hc = True

    @property
    def hc_exchange_blocks(self) -> int:
        return self._n

    frag_axis = AXIS
    # builds larger than this replicate no more: they shard by key range
    # and probe rows route over ICI (hash-partition vs broadcast exchange,
    # reference: planner/core/fragment.go:45). Tests shrink it to force
    # the partitioned path at toy scale.
    partition_join_threshold = 1 << 21

    def _stage_partitioned_build(self, t, snap, lo, span, j):
        """Key-interleaved build arrays sharded over the mesh: device d
        owns keys with (key-lo) % n_dev == d, laid out at local index
        (key-lo) // n_dev. Round-robin interleaving (not contiguous
        ranges) matters: probe tables are typically key-SORTED (TPC-H
        lineitem is orderkey-ordered), so range ownership would route a
        device's whole shard to one destination and overflow any bounded
        exchange capacity — interleaving spreads sorted probes uniformly.
        The perm indirection of the broadcast path disappears: after
        routing, a probe row gathers its build row by direct local
        key index."""
        from ..copr.client import _mask_digest, _narrow

        n_dev = self._n
        span_pad = -(-span // n_dev) * n_dev
        per_dev = span_pad // n_dev
        epoch = snap.epoch
        key_off = t.col_offsets[j.build_key_local]
        host_mask = snap.base_visible
        ck = (epoch.epoch_id, "partb", key_off, lo, span_pad,
              _mask_digest(host_mask), tuple(t.col_offsets))
        with self._lock:
            hit = self._col_cache.get(ck)
            cacheable = self._live_epochs.get(t.table.id) == epoch.epoch_id
        if hit is not None:
            return hit
        keys = epoch.columns[key_off]
        kvalid = epoch.valids[key_off]
        sel = host_mask.copy()
        if kvalid is not None:
            sel &= kvalid
        idx = np.nonzero(sel)[0]
        k = keys[idx].astype(np.int64) - lo
        pos = (k % n_dev) * per_dev + k // n_dev  # interleave bijection
        present = np.zeros(span_pad, dtype=bool)
        present[pos] = True
        sharding = NamedSharding(self.mesh, P(AXIS))
        bykey = []
        with obs.stage("shard"):
            for off in t.col_offsets:
                data = np.zeros(span_pad, dtype=_narrow(
                    epoch.columns[off][:0]).dtype)
                data[pos] = _narrow(epoch.columns[off][idx])
                v = epoch.valids[off]
                valid = present.copy()
                if v is not None:
                    valid[pos] = v[idx]
                bykey.append((jax.device_put(data, sharding),
                              jax.device_put(valid, sharding)))
            build = {"bykey": bykey,
                     "present": jax.device_put(present, sharding)}
        if cacheable:
            with self._lock:
                self._col_cache[ck] = build
        return build

    def _join_exchange_fn(self, frag, prepared, spans):
        from ..copr.eval import eval_expr
        from . import exchange as EX

        part_ji = prepared["__part_join__"]
        j = frag.joins[part_ji]
        lo, span = spans[part_ji]
        n_dev = self._n

        def route(cols, mask):
            key_v, key_vl = eval_expr(j.probe_key, cols, prepared)
            k = key_v.astype(jnp.int32) - jnp.int32(lo)
            m = mask.shape[0]
            iota = jnp.arange(m, dtype=jnp.int32)
            live = mask & key_vl & (k >= 0) & (k < span)
            # interleaved build ownership: key k lives on device k % n.
            # Dead rows (padding / null / out-of-span keys) spread
            # round-robin so no bucket overflows on them.
            dest = jnp.where(live, k % jnp.int32(n_dev),
                             iota % jnp.int32(n_dev))
            return EX.route_cols(dest, cols, mask, AXIS, n_dev,
                                 EX.capacity_for(m, n_dev))

        return route

    def _hc_exchange_fn(self, frag, prepared):
        from ..copr.eval import eval_expr
        from . import exchange as EX

        n_dev = self._n
        seg_keys = prepared["__hc_segkeys__"]
        nulls = prepared["__hc_nulls__"]
        group_by = frag.agg.group_by

        def route(cols, mask):
            # NULL-encoded segment keys (the same encoding _hc_body uses)
            # determine the destination: every row of a group shares them
            keys = []
            for gi in seg_keys:
                g = group_by[gi]
                v, vl = eval_expr(g, cols, prepared)
                if v.dtype == jnp.bool_:
                    v = v.astype(jnp.int32)
                keys.append(jnp.where(vl, v.astype(jnp.int32),
                                      jnp.int32(nulls[gi])))
            m = mask.shape[0]
            # dead rows (bucket padding / filtered) spread round-robin —
            # they'd otherwise hash to one bucket and overflow it
            iota = jnp.arange(m, dtype=jnp.int32)
            dest = jnp.where(
                mask,
                jnp.abs(EX.mix_hash(keys)) % jnp.int32(n_dev),
                iota % jnp.int32(n_dev))
            return EX.route_cols(dest, cols, mask, AXIS, n_dev,
                                 EX.capacity_for(m, n_dev))

        return route

    def _stage_key_suffix(self):
        # builds cache under a distinct placement namespace: one epoch
        # can be a sharded probe AND a replicated broadcast build
        return ("rep",) if getattr(self._tls, "place_build", False) else ()

    def _stage_build_table(self, facade, snap):
        # build columns place REPLICATED at creation (broadcast-join
        # side) under "rep"-suffixed staging keys; the _replicated()
        # re-placement below is then a no-copy identity, and the repc
        # keys keep the epoch-led eviction story
        self._tls.place_build = True
        try:
            cols, vis, host_cols, host_mask = CopClient._stage_inputs(
                self, facade, snap, overlay=False)
        finally:
            self._tls.place_build = False
        b = vis.shape[0]
        eid = snap.epoch.epoch_id
        with self._lock:
            cacheable = self._live_epochs.get(
                facade.scan.table_id) == eid
        rep_cols = []
        for off, (d, v) in zip(facade.scan.col_offsets, cols):
            rep_cols.append((
                self._replicated((eid, "repc", off, b), d, cacheable),
                self._replicated((eid, "repv", off, b), v, cacheable)))
        from ..copr.client import _mask_digest
        vis = self._replicated(
            (eid, "repvis", b, _mask_digest(host_mask)), vis, cacheable)
        self._frag_cacheable = cacheable
        return rep_cols, vis, host_cols, host_mask

    def _place_build_array(self, arr, key=None):
        # perm arrays are cached device-resident per epoch; replicate once
        # under an epoch-led key so _evict_stale reclaims the broadcast
        if key is None:
            return jax.device_put(arr, NamedSharding(self.mesh, P()))
        return self._replicated(key, arr,
                                getattr(self, "_frag_cacheable", True))

    def _replicated(self, key, arr, cacheable: bool = True):
        """Broadcast once per epoch, then reuse: re-placing cached arrays
        every query would pay a full mesh transfer per fragment run. A
        snapshot on an already-superseded epoch must not seed entries the
        one-shot eviction transition will never reclaim."""
        with self._lock:
            hit = self._col_cache.get(key)
        if hit is not None:
            return hit
        with obs.stage("reshard"):
            placed = jax.device_put(arr, NamedSharding(self.mesh, P()))
        if getattr(arr, "sharding", None) != placed.sharding:
            # a real broadcast (not an identity re-place): every other
            # device receives a full copy over the mesh links
            obs.MESH_RESHARD_BYTES.inc(
                int(getattr(arr, "nbytes", 0)) * max(self._n - 1, 1))
        if cacheable:
            with self._lock:
                self._col_cache[key] = placed
        return placed

    def _frag_jit(self, kernel, mode, prepared):
        """shard_map the fragment body: probe rows sharded, builds
        replicated; agg partials merge with native-int32 collectives, row
        bitmasks concatenate along the rows axis."""
        build_specs = self._build_in_specs(prepared)
        if mode == "agg":
            sched = prepared["__agg_sched__"]

            def merged(pcols, pvis, builds):
                return _collective_merge(kernel(pcols, pvis, builds), sched)

            mapped = shard_map(
                merged, mesh=self.mesh,
                in_specs=(P(AXIS), P(AXIS), build_specs),
                out_specs=P())
            return jax.jit(mapped)
        if mode == "hc":
            mapped = shard_map(
                kernel, mesh=self.mesh,
                in_specs=(P(AXIS), P(AXIS), build_specs),
                out_specs=self._hc_out_specs(prepared))
            return jax.jit(mapped)
        if mode == "topn":
            # fused join+topn: each shard ships its own top-n candidate
            # rows, concatenated along the k axis (n·shards rows total);
            # the host Sort/Limit above merge exactly
            mapped = shard_map(
                kernel, mesh=self.mesh,
                in_specs=(P(AXIS), P(AXIS), build_specs),
                out_specs=P(None, AXIS))
            return jax.jit(mapped)
        # row mode: per-shard packed bitmask; shards are 256-multiples so
        # byte boundaries align and concatenation is the global mask
        mapped = shard_map(
            kernel, mesh=self.mesh,
            in_specs=(P(AXIS), P(AXIS), build_specs),
            out_specs=P(AXIS))
        return jax.jit(mapped)

    @staticmethod
    def _hc_out_specs(prepared) -> dict:
        """shard_map out_specs for the hc partial schema: per-device
        candidate blocks concatenate (disjoint group partitions after
        the exchange); overflow is psum-replicated. Shared with the
        mesh client so the spec dict cannot diverge from the schema."""
        specs: dict = {"picked": P(AXIS), "score": P(AXIS),
                       "overflow": P()}
        for gi in range(len(prepared["__hc_nulls__"])):
            specs[f"gk{gi}"] = P(AXIS)
        for ai, s in enumerate(prepared["__hc_sched__"]):
            specs[f"cnt{ai}"] = P(None, None, AXIS)
            if s["kind"] in ("min", "max"):
                # sorted-operand min/max: one encoded value per candidate
                specs[f"mm{ai}"] = P(AXIS)
            for ti in range(len(s.get("terms", ()))):
                specs[f"s{ai}_{ti}"] = P(None, None, AXIS)
        return specs

    def _build_in_specs(self, prepared):
        """Per-build shard_map in_specs: broadcast builds replicate (P()),
        the partitioned build's key-ordered arrays shard by key range."""
        part_ji = prepared.get("__part_join__")
        n_joins = prepared.get("__n_joins__", 0)
        if part_ji is None:
            return P()
        return [
            {"bykey": P(AXIS), "present": P(AXIS)} if ji == part_ji else P()
            for ji in range(n_joins)
        ] + [P()] * prepared.get("__n_semis__", 0)  # replicated bitmaps

    # ---- TopN: local top-k per shard, host merge ------------------------
    def _build_topn_kernel(self, dag, prepared, expr, desc, n):
        raw = self._topn_body(dag, prepared, expr, desc, n)
        mapped = shard_map(
            raw, mesh=self.mesh,
            in_specs=(P(AXIS), P(AXIS)),
            # per-shard candidate columns concatenate along the k axis;
            # the host PhysSort+PhysLimit above merge exactly
            out_specs=P(None, AXIS))
        return jax.jit(mapped)

    def _build_rowmask_kernel(self, dag, prepared):
        raw = self._rowmask_body(dag, prepared)
        mapped = shard_map(
            raw, mesh=self.mesh,
            in_specs=(P(AXIS), P(AXIS)),
            out_specs=P(AXIS))
        return jax.jit(mapped)


def _collective_merge(out: dict, sched) -> dict:
    """Merge per-shard agg partials over the mesh axis: pmin/pmax for
    min/max keys, psum for everything else (int32 limb partials and float
    block sums are both additive)."""
    minmax_kind = {f"m{ai}": s["kind"] for ai, s in enumerate(sched)
                   if s["kind"] in ("min", "max")}
    hll_keys = {f"h{ai}" for ai, s in enumerate(sched)
                if s["kind"] == "hll"}
    res = {}
    for key, val in out.items():
        kind = minmax_kind.get(key)
        if kind == "min":
            res[key] = jax.lax.pmin(val, AXIS)
        elif kind == "max" or key in hll_keys:
            # hll registers union across shards by elementwise max
            res[key] = jax.lax.pmax(val, AXIS)
        else:
            res[key] = jax.lax.psum(val, AXIS)
    return res
