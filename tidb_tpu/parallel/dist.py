"""Distributed coprocessor execution over a TPU mesh.

The multi-chip tier of the design (SURVEY.md §7 step 10): where the
reference fans coprocessor tasks out to TiKV regions over gRPC and runs MPP
exchanges between TiFlash nodes (reference: store/tikv/coprocessor.go:248
buildCopTasks; store/tikv/mpp.go:372 DispatchMPPTasks; exchange operators
from planner/core/fragment.go), the TPU framework shards the column epoch
across devices and lets XLA collectives do the exchange:

* scan fan-out (P1)  -> rows axis sharding of the padded column arrays
* partial aggregation (P2 partial stage) -> per-shard dense segment_sum
* final merge (P2 final / P9 exchange)   -> psum over the mesh axis (ICI)

The partial layout is identical to the single-chip path, so the host final
stage is unchanged — it just receives partials that were already reduced
across devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..copr.client import CopClient

AXIS = "shard"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D data mesh over the given (or all) devices."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (AXIS,))


class DistCopClient(CopClient):
    """CopClient whose aggregation kernels run sharded over a device mesh.

    Row batches are padded to shape buckets (multiples of 256, so any
    power-of-two mesh divides them); each device reduces its row shard into
    the full dense segment space, then a psum over the mesh axis yields the
    global partials on every device. Inputs are placed with row-sharded
    NamedShardings so jit consumes them without host round-trips.
    """

    def __init__(self, mesh: Mesh) -> None:
        super().__init__()
        self.mesh = mesh
        self._n = mesh.devices.size

    def _build_agg_kernel(self, dag, prepared, cards, segments, narrowed):
        body = self._agg_kernel_body(dag, prepared, cards, segments,
                                     keep_sentinels=True, narrowed=narrowed)
        aggs = dag.agg.aggs
        float_rows = self._float_val_rows(dag)

        def sharded(cols, row_mask):
            out = body(cols, row_mask)
            # per-function merge: sums/counts are additive; min/max need
            # pmin/pmax over the sentinel-preserving partials, then empty
            # segments are zeroed exactly like the single-chip kernel
            merged = {"rows": jax.lax.psum(out["rows"], AXIS)}
            for ai, d in enumerate(aggs):
                cnt = jax.lax.psum(out[f"cnt{ai}"], AXIS)
                val = out[f"val{ai}"]
                if d.arg is not None and d.func == "min":
                    val = jax.lax.pmin(val, AXIS)
                    val = jnp.where(cnt > 0, val, 0)
                elif d.arg is not None and d.func == "max":
                    val = jax.lax.pmax(val, AXIS)
                    val = jnp.where(cnt > 0, val, 0)
                else:
                    val = jax.lax.psum(val, AXIS)
                merged[f"val{ai}"] = val
                merged[f"cnt{ai}"] = cnt
            # pack inside shard_map (post-collective, replicated) so the
            # host sees the same single-buffer layout as the one-chip path
            return self._pack_agg(dag, merged, float_rows)

        out_specs = {"ints": P()}
        if float_rows:
            out_specs["flts"] = P()
        mapped = jax.shard_map(
            sharded,
            mesh=self.mesh,
            in_specs=(P(AXIS), P(AXIS)),
            out_specs=out_specs,
        )
        return jax.jit(mapped)

    def _bucket_size(self, n: int) -> int:
        """Round the shape bucket up to a multiple of the mesh size so the
        rows axis always shards evenly (any device count, not just 2^k)."""
        b = super()._bucket_size(n)
        lcm = int(np.lcm(256, self._n))
        return -(-b // lcm) * lcm

    def _stage_inputs(self, dag, snap, overlay: bool, col_bounds=None):
        cols, row_mask, host_cols, narrowed = super()._stage_inputs(
            dag, snap, overlay, col_bounds=col_bounds)
        n = row_mask.shape[0]
        assert n % self._n == 0, f"bucket {n} vs mesh {self._n}"
        sharding = NamedSharding(self.mesh, P(AXIS))
        cols = [
            (jax.device_put(d, sharding), jax.device_put(v, sharding))
            for d, v in cols
        ]
        row_mask = jax.device_put(row_mask, sharding)
        return cols, row_mask, host_cols, narrowed
