"""Distributed coprocessor execution over a TPU mesh.

The multi-chip tier of the design (SURVEY.md §7 step 10): where the
reference fans coprocessor tasks out to TiKV regions over gRPC and runs MPP
exchanges between TiFlash nodes (reference: store/tikv/coprocessor.go:248
buildCopTasks; store/tikv/mpp.go:372 DispatchMPPTasks; exchange operators
from planner/core/fragment.go), the TPU framework shards the column epoch
across devices and lets XLA collectives do the exchange:

* scan fan-out (P1)  -> rows axis sharding of the padded column arrays
* partial aggregation (P2 partial stage) -> per-shard exact limb partials
* final merge (P2 final / P9 exchange)   -> psum/pmin/pmax over the mesh
  axis (ICI), all in native int32 — the limb partials are exact under
  addition (sumexact.py), so the collective needs no 64-bit emulation.

The partial layout is identical to the single-chip path, so the host final
stage is unchanged — it just receives partials that were already reduced
across devices.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..copr.client import CopClient

AXIS = "shard"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D data mesh over the given (or all) devices."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (AXIS,))


class DistCopClient(CopClient):
    """CopClient whose aggregation kernels run sharded over a device mesh.

    Row batches are padded to shape buckets (multiples of 256, so any
    power-of-two mesh divides them); each device reduces its row shard into
    the full dense segment space, then collectives over the mesh axis yield
    the global partials on every device. Inputs are placed with row-sharded
    NamedShardings so jit consumes them without host round-trips.
    """

    def __init__(self, mesh: Mesh) -> None:
        super().__init__()
        self.mesh = mesh
        self._n = mesh.devices.size

    def _build_agg_kernel(self, dag, prepared, cards, segments):
        body = self._agg_kernel_body(dag, prepared, cards, segments)
        sched = prepared["__agg_sched__"]
        minmax_kind = {f"m{ai}": s["kind"]
                       for ai, s in enumerate(sched)
                       if s["kind"] in ("min", "max")}

        def sharded(cols, row_mask):
            out = body(cols, row_mask)
            merged = {}
            for key, val in out.items():
                kind = minmax_kind.get(key)
                if kind == "min":
                    merged[key] = jax.lax.pmin(val, AXIS)
                elif kind == "max":
                    merged[key] = jax.lax.pmax(val, AXIS)
                else:
                    # limb partials / counts (int32, exact under addition)
                    # and float block sums — both additive
                    merged[key] = jax.lax.psum(val, AXIS)
            return merged

        # every output is replicated post-collective; a single P() acts
        # as a pytree prefix matching every leaf of the output dict
        mapped = jax.shard_map(
            sharded,
            mesh=self.mesh,
            in_specs=(P(AXIS), P(AXIS)),
            out_specs=P(),
        )
        return jax.jit(mapped)

    def _bucket_size(self, n: int) -> int:
        """Round the shape bucket up to a multiple of the mesh size so the
        rows axis always shards evenly (any device count, not just 2^k)."""
        b = super()._bucket_size(n)
        lcm = int(np.lcm(256, self._n))
        return -(-b // lcm) * lcm

    def _stage_inputs(self, dag, snap, overlay: bool):
        cols, row_mask, host_cols, host_mask = super()._stage_inputs(
            dag, snap, overlay)
        n = row_mask.shape[0]
        assert n % self._n == 0, f"bucket {n} vs mesh {self._n}"
        sharding = NamedSharding(self.mesh, P(AXIS))
        cols = [
            (jax.device_put(d, sharding), jax.device_put(v, sharding))
            for d, v in cols
        ]
        row_mask = jax.device_put(row_mask, sharding)
        return cols, row_mask, host_cols, host_mask
