"""Per-statement interrupt plane (KILL QUERY / KILL CONNECTION).

The reference kills running statements by flipping a kill flag the
executors poll between batches (reference: server/server.go:548 Kill ->
sessVars.Killed; executor checkpoints via Next loops). Here the flag is
a threading.Event installed for the duration of a statement; the engine
checks it between plan nodes and the coprocessor client between tiles —
granular enough that long scans and joins die promptly, while a single
in-flight device dispatch (one tile kernel) runs to completion.
"""

from __future__ import annotations

import threading
from typing import Optional


class QueryInterrupted(Exception):
    """errno 1317 ER_QUERY_INTERRUPTED."""

    def __init__(self) -> None:
        super().__init__("Query execution was interrupted")


_local = threading.local()


def install(flag: Optional[threading.Event]) -> None:
    _local.flag = flag


def current() -> Optional[threading.Event]:
    return getattr(_local, "flag", None)


def check() -> None:
    flag = getattr(_local, "flag", None)
    if flag is not None and flag.is_set():
        raise QueryInterrupted()
