"""Host-side utility belt (reference: util/ — memory quota, spill, tracing)."""
