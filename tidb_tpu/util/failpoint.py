"""Fault-injection registry: named points compiled into the runtime.

Counterpart of the reference's failpoint usage (reference:
pingcap/failpoint macros threaded through 66 files — e.g.
store/tikv/2pc.go:704,1027,1264, coprocessor.go:835 — enabled per-test
via failpoint.Enable). Python needs no code rewriting: call sites invoke
`inject(name)` unconditionally; a disabled point is one dict probe.

An enabled point's value drives behavior at the site:
  * an Exception instance or class — raised (simulated failure),
  * a callable — invoked (custom behavior: sleep, crash flag, counter),
  * anything else — returned to the call site for it to interpret.

Tests use the context manager so points never leak:

    with failpoint("twopc/after-primary-commit", CrashError()):
        ...

Cross-process arming (the kill-9 torture harness): the environment
variable `TIDB_TPU_FAILPOINTS=name=value;name2=value2` is parsed at
import, so points arm inside child server processes the harness spawns
(reference: the GO_FAILPOINTS env var of pingcap/failpoint). Values:

    exit(N)      os._exit(N) at the hit — the SIGKILL-grade crash
    sleep(S)     block S seconds at the hit
    raise        raise RuntimeError at the hit
    <number>     returned to the call site (delays, counts)
    true/false   boolean toggle
    anything@K   fire only on the K-th hit (1-based), inert otherwise —
                 lets a crash point skip bootstrap traffic

Armed points and their hit counts are listed on the status port at
/debug/failpoints (snapshot()).
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from typing import Any, Iterator, Optional

_lock = threading.Lock()
_active: dict[str, Any] = {}
_hits: dict[str, int] = {}
# Arming-change listeners (rpc/netfault.py): called OUTSIDE _lock after
# any enable/disable so hot paths can cache "is anything armed" in a
# plain module flag instead of taking _lock per operation.
_listeners: list = []


def on_change(cb) -> None:
    with _lock:
        if cb not in _listeners:
            _listeners.append(cb)


def _notify() -> None:
    for cb in list(_listeners):
        try:
            cb()
        except Exception:  # noqa: BLE001 — listeners never break arming
            pass

# The declared registry: every inject() site in tidb_tpu/ names one of
# these, and every name a test arms (context manager, enable(), or a
# TIDB_TPU_FAILPOINTS env spec) must exist here — an armed point whose
# inject() site was renamed away silently never fires, which is how
# chaos coverage rots. The failpoint-registry analysis rule enforces
# both directions statically (tests/test_analysis.py runs it tier-1).
DECLARED = frozenset({
    "daemon/before-gc",            # store/daemon.py GC tick
    "ddl/before-step",             # ddl/ddl.py job-step boundary
    "diag/peer-down",              # rpc/diag.py fan-out peer failure
    "diag/slow-peer",              # rpc/diag.py fan-out latency
    "governor/mem-pressure",       # util/governor.py synthetic RSS
    "kv/group-fsync",              # kv/mvcc.py pre-fsync crash site
    "kv/wal-torn-append",          # kv/mvcc.py torn WAL record
    "mesh/skew",                   # copr/mesh.py synthetic shard skew
    "net/delay",                   # rpc/netfault.py per-peer frame
                                   # delay schedule
    "net/drop",                    # rpc/netfault.py silent frame loss
    "net/dup",                     # rpc/netfault.py frame duplication
    "net/partition",               # rpc/netfault.py sym/asym partition
    "range/before-commit-ack",     # rpc/ranged.py commit applied,
                                   # ack not sent (leader-kill site)
    "range/before-prewrite-ack",   # rpc/ranged.py prewrite applied,
                                   # ack not sent (leader-kill site)
    "range/lease-drop",            # rpc/ranged.py forced lease release
                                   # (value: range id, or true = all)
    "range/auto-split",            # rpc/ranged.py actuator about to
                                   # execute an advised split
    "range/split-before-meta-commit",   # journal written, table not
    "range/split-after-meta-commit",    # table committed, child empty
    "range/split-mid-wal-partition",    # child WAL half-written
    "range/split-before-parent-retire", # child ready, parent whole
    "replica/apply-stall",         # rpc/apply.py frozen apply loop
    "rpc/conn-drop",               # rpc/client.py transport chaos
    "rpc/delay",
    "rpc/partial-write",
    "rpc/stale-response",
    "storage/before-fold",         # store/storage.py commit fold
    "storage/mid-checkpoint",      # store/storage.py checkpoint crash
    "twopc/after-prewrite",        # kv/twopc.py percolator phases
    "twopc/after-primary-commit",
    "twopc/before-commit-primary",
    "twopc/before-prewrite",
})


def enable(name: str, value: Any = True) -> None:
    with _lock:
        _active[name] = value
    _notify()


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)
    _notify()


def disable_all() -> None:
    with _lock:
        _active.clear()
        _hits.clear()
    _notify()


def is_enabled(name: str) -> bool:
    with _lock:
        return name in _active


def hits(name: str) -> int:
    with _lock:
        return _hits.get(name, 0)


def snapshot() -> dict[str, dict]:
    """Armed points + lifetime hit counts (for /debug/failpoints).
    Points hit after being disarmed keep their counts until
    disable_all(), so a chaos run can still read what fired."""
    with _lock:
        out: dict[str, dict] = {}
        for name in set(_active) | set(_hits):
            out[name] = {
                "armed": name in _active,
                "value": repr(_active.get(name)),
                "hits": _hits.get(name, 0),
            }
        return out


def inject(name: str) -> Optional[Any]:
    """The call-site hook. Returns None when the point is disabled;
    otherwise raises/calls/returns per the enabled value."""
    with _lock:
        if name not in _active:
            return None
        value = _active[name]
        _hits[name] = _hits.get(name, 0) + 1
    if isinstance(value, BaseException):
        raise value
    if isinstance(value, type) and issubclass(value, BaseException):
        raise value(f"failpoint {name}")
    if callable(value):
        return value()
    return value


@contextmanager
def failpoint(name: str, value: Any = True) -> Iterator[None]:
    enable(name, value)
    try:
        yield
    finally:
        disable(name)


# ---- env-var arming (child processes of the torture harness) ---------------
def _parse_action(spec: str) -> Any:
    spec = spec.strip()
    if spec.startswith("exit(") and spec.endswith(")"):
        code = int(spec[5:-1] or 1)
        return lambda: os._exit(code)
    if spec.startswith("sleep(") and spec.endswith(")"):
        secs = float(spec[6:-1] or 0)
        import time as _time
        return lambda: _time.sleep(secs)
    if spec == "raise":
        def _raise():
            raise RuntimeError("failpoint (env-armed)")
        return _raise
    if spec in ("true", "false"):
        return spec == "true"
    try:
        return int(spec)
    except ValueError:
        pass
    try:
        return float(spec)
    except ValueError:
        return spec


def _nth_hit(action: Any, k: int) -> Any:
    """Fire `action` only on the k-th evaluation (1-based): bootstrap
    traffic through the same site must not eat a crash aimed at the
    workload. Inert evaluations return None (call sites treat that as
    disabled)."""
    state = {"n": 0}

    def fire():
        state["n"] += 1
        if state["n"] != k:
            return None
        if isinstance(action, BaseException) or (
                isinstance(action, type)
                and issubclass(action, BaseException)):
            raise action
        return action() if callable(action) else action

    return fire


def arm_from_env(spec: Optional[str] = None) -> list[str]:
    """Parse `name=value;...` (TIDB_TPU_FAILPOINTS by default) and
    enable each point; returns the armed names."""
    if spec is None:
        spec = os.environ.get("TIDB_TPU_FAILPOINTS", "")
    armed = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, raw = part.partition("=")
        raw = raw.strip()
        if "@" in raw:
            raw, _, nth = raw.rpartition("@")
            value: Any = _nth_hit(_parse_action(raw), int(nth))
        else:
            value = _parse_action(raw)
        enable(name.strip(), value)
        armed.append(name.strip())
    return armed


arm_from_env()


__all__ = ["DECLARED", "enable", "disable", "disable_all",
           "is_enabled", "inject", "hits", "snapshot", "failpoint",
           "arm_from_env", "on_change"]
