"""Fault-injection registry: named points compiled into the runtime.

Counterpart of the reference's failpoint usage (reference:
pingcap/failpoint macros threaded through 66 files — e.g.
store/tikv/2pc.go:704,1027,1264, coprocessor.go:835 — enabled per-test
via failpoint.Enable). Python needs no code rewriting: call sites invoke
`inject(name)` unconditionally; a disabled point is one dict probe.

An enabled point's value drives behavior at the site:
  * an Exception instance or class — raised (simulated failure),
  * a callable — invoked (custom behavior: sleep, crash flag, counter),
  * anything else — returned to the call site for it to interpret.

Tests use the context manager so points never leak:

    with failpoint("twopc/after-primary-commit", CrashError()):
        ...
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Iterator, Optional

_lock = threading.Lock()
_active: dict[str, Any] = {}
_hits: dict[str, int] = {}


def enable(name: str, value: Any = True) -> None:
    with _lock:
        _active[name] = value


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)


def disable_all() -> None:
    with _lock:
        _active.clear()
        _hits.clear()


def is_enabled(name: str) -> bool:
    with _lock:
        return name in _active


def hits(name: str) -> int:
    with _lock:
        return _hits.get(name, 0)


def inject(name: str) -> Optional[Any]:
    """The call-site hook. Returns None when the point is disabled;
    otherwise raises/calls/returns per the enabled value."""
    with _lock:
        if name not in _active:
            return None
        value = _active[name]
        _hits[name] = _hits.get(name, 0) + 1
    if isinstance(value, BaseException):
        raise value
    if isinstance(value, type) and issubclass(value, BaseException):
        raise value(f"failpoint {name}")
    if callable(value):
        return value()
    return value


@contextmanager
def failpoint(name: str, value: Any = True) -> Iterator[None]:
    enable(name, value)
    try:
        yield
    finally:
        disable(name)


__all__ = ["enable", "disable", "disable_all", "is_enabled", "inject",
           "hits", "failpoint"]
