"""Memory quota tracking + chunk spill for host operators.

Counterpart of the reference's memory governance (reference:
util/memory/tracker.go:42 hierarchical trackers with per-query quota;
action.go:28 pluggable on-exceed actions; util/chunk/row_container.go:63
disk-backed row container + :493 SortAndSpillDiskAction).

Design for the materialized host engine: operators are chunk-at-a-time,
so the tracker's job is (a) accounting the working set an operator is
about to materialize and (b) letting the operator pick a partitioned
on-disk strategy *before* allocating it. The quota bounds per-operator
transient working sets (hash tables, sort keys, join pair expansion) —
the final result chunk still materializes, exactly as the reference
materializes the outgoing wire chunks.

Actions on exceed (sysvar tidb_mem_oom_action):
  SPILL  — operators that can partition (hash join, hash agg, sort)
           switch to on-disk runs; others raise.
  CANCEL — raise QueryMemExceeded (errno 8175, "Out Of Memory Quota!").
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Iterator, Optional


class QueryMemExceeded(Exception):
    """Raised when a query's working set exceeds tidb_mem_quota_query and
    the operator cannot (or may not) spill."""

    errno = 8175  # ER_QUERY_MEM_EXCEEDED
    sqlstate = "HY000"

    def __init__(self, label: str, need: int, quota: int) -> None:
        super().__init__(
            f"Out Of Memory Quota![conn] operator {label} needs {need} "
            f"bytes, quota {quota} bytes")


class MemTracker:
    """Hierarchical byte tracker with a quota at the root.

    consume/release propagate to the parent; peak is recorded at every
    level. Quota is checked at the root (the per-query tracker); the
    reference attaches the quota the same way (tracker.go:42, one
    per-query root with operator children).
    """

    __slots__ = ("label", "quota", "parent", "consumed", "peak",
                 "action", "spill_count", "governor", "_gov_next",
                 "ledger", "ledger_peak")

    def __init__(self, label: str = "query", quota: int = 0,
                 parent: Optional["MemTracker"] = None,
                 action: str = "SPILL") -> None:
        self.label = label
        self.quota = quota  # 0 = unlimited
        self.parent = parent
        self.consumed = 0
        self.peak = 0
        self.action = action
        self.spill_count = 0
        # server-wide ledger hook: the governor sets itself on the ROOT
        # tracker at statement registration; consume()/account() then
        # re-evaluate server memory pressure every GOV_POLL_BYTES of
        # root growth (util/governor.py), so the kill policy runs
        # exactly where memory is being acquired, with no background
        # thread
        self.governor = None
        self._gov_next = 0
        # materialization ledger (ROOT only): working-set estimates the
        # operators admitted in memory (engine._overflow's fits-branch).
        # Kept SEPARATE from `consumed` so the per-operator quota/spill
        # decisions are untouched — this meter exists for the governor's
        # heaviest-statement choice and the MEM_MAX forensics columns.
        # ledger_peak is the COMBINED (consumed + ledger) high-water,
        # maintained by both consume() and account(): mem_max must never
        # report below the footprint the governor killed at.
        self.ledger = 0
        self.ledger_peak = 0

    def child(self, label: str) -> "MemTracker":
        return MemTracker(label, 0, self, self.action)

    def consume(self, n: int) -> None:
        t: MemTracker = self
        while True:
            t.consumed += n
            if t.consumed > t.peak:
                t.peak = t.consumed
            if t.parent is None:
                break
            t = t.parent
        combined = t.consumed + t.ledger
        if combined > t.ledger_peak:
            t.ledger_peak = combined
        g = t.governor
        if g is not None and combined >= t._gov_next:
            from .governor import GOV_POLL_BYTES
            t._gov_next = combined + GOV_POLL_BYTES
            g.check()

    def account(self, n: int) -> None:
        """Record `n` bytes of in-memory materialization on the ROOT's
        ledger (no quota effect — see the ledger comment above) and
        poll the governor at the same cadence as consume()."""
        root = self._root()
        root.ledger += n
        combined = root.consumed + root.ledger
        if combined > root.ledger_peak:
            root.ledger_peak = combined
        g = root.governor
        if g is not None and combined >= root._gov_next:
            from .governor import GOV_POLL_BYTES
            root._gov_next = combined + GOV_POLL_BYTES
            g.check()

    def footprint(self) -> int:
        """Best live working-set estimate of this statement: tracked
        transient consumption plus the materialization ledger (what the
        governor ranks statements by)."""
        root = self._root()
        return max(root.consumed, 0) + max(root.ledger, 0)

    def peak_footprint(self) -> int:
        """High-water of the combined footprint — what mem_max columns
        report, and by construction >= any footprint() the governor
        ever ranked this statement at."""
        root = self._root()
        return max(root.peak, root.ledger_peak)

    def release(self, n: int) -> None:
        self.consume(-n)

    def _root(self) -> "MemTracker":
        t = self
        while t.parent is not None:
            t = t.parent
        return t

    def available(self) -> int:
        """Bytes left under the root quota (a large number if unlimited)."""
        root = self._root()
        if root.quota <= 0:
            return 1 << 62
        return root.quota - root.consumed

    def over_budget(self, extra: int) -> bool:
        """Would consuming `extra` more bytes exceed the root quota?"""
        return extra > self.available()

    def check(self, extra: int, label: str) -> None:
        """Raise when `extra` cannot fit and the action is CANCEL."""
        if self.over_budget(extra) and self._root().action == "CANCEL":
            root = self._root()
            raise QueryMemExceeded(label, root.consumed + extra, root.quota)

    def note_spill(self) -> None:
        t: Optional[MemTracker] = self
        while t is not None:
            t.spill_count += 1
            t = t.parent


class SpillFile:
    """One spilled chunk partition on disk (pickle of Column buffers).

    Counterpart of the reference's ListInDisk chunk file
    (util/chunk/disk.go). String dictionaries are NOT serialized: they
    are shared table state already resident (the store holds them), so
    the file keeps only the int32 codes and the dictionary objects ride
    along in memory by reference — read() reattaches them, which also
    means Chunk.concat over partitions does no code remapping.
    """

    __slots__ = ("path", "rows", "nbytes", "_dicts")

    def __init__(self, path: str) -> None:
        self.path = path
        self.rows = 0
        self.nbytes = 0
        self._dicts: list = []

    def write(self, chunk) -> None:
        from ..chunk.chunk import Chunk
        from ..chunk.column import Column

        self.rows = chunk.num_rows
        self.nbytes = chunk.nbytes
        self._dicts = [c.dictionary for c in chunk.columns]
        stripped = Chunk([Column(c.ftype, c.data, c.valid, None)
                          for c in chunk.columns])
        with open(self.path, "wb") as f:
            pickle.dump(stripped, f, protocol=pickle.HIGHEST_PROTOCOL)

    def read(self):
        with open(self.path, "rb") as f:
            chunk = pickle.load(f)
        for c, d in zip(chunk.columns, self._dicts):
            c.dictionary = d
        return chunk


class SpillDir:
    """Temp directory owning a query's spill files; removed on close.

    The reference scopes spill files to a per-query temp dir under
    tmp-storage-path (util/disk/tempDir.go); same lifecycle here.
    """

    def __init__(self) -> None:
        self._dir: Optional[tempfile.TemporaryDirectory] = None
        self._seq = 0

    def new_file(self) -> SpillFile:
        if self._dir is None:
            self._dir = tempfile.TemporaryDirectory(prefix="titpu-spill-")
        self._seq += 1
        return SpillFile(os.path.join(self._dir.name, f"part{self._seq}.bin"))

    def spill(self, chunk) -> SpillFile:
        f = self.new_file()
        f.write(chunk)
        return f

    def close(self) -> None:
        if self._dir is not None:
            self._dir.cleanup()
            self._dir = None

    def __del__(self) -> None:  # best-effort; close() is the real path
        try:
            self.close()
        except Exception:
            pass


def iter_partitions(files: list[SpillFile]) -> Iterator:
    for f in files:
        yield f.read()


__all__ = ["MemTracker", "QueryMemExceeded", "SpillDir", "SpillFile",
           "iter_partitions"]
