"""Server-wide resource governor: global memory ledger + admission gate.

Counterpart of the reference's server-level overload protection:
the connection token limiter (reference: server/server.go:141
tokenLimiter capping concurrently executing statements), the
server-memory-limit kill policy of later versions (reference:
util/memory GlobalMemoryController — when the tidb-server instance
crosses `server-memory-limit`, the statement with the highest memory
usage is cancelled, with a cooldown so one pressure spike does not
massacre the whole processlist), and `max-server-connections` /
ER_CON_COUNT_ERROR 1040.

Two cooperating pieces, both owned by the Storage (one per 'cluster',
like Observability) and both thread-only (no background workers — the
ledger is evaluated at statement admission and at tracker-consume
checkpoints, so shutdown joins nothing):

  MemoryGovernor — registers every live per-statement MemTracker root.
      When `server-memory-limit` is crossed (process RSS or the tracked
      sum, whichever is higher — or the synthetic usage injected by the
      `governor/mem-pressure` failpoint, which is what makes the chaos
      suite deterministic), it cancels the heaviest *cancellable*
      running statement through the per-statement interrupt plane
      (util/interrupt.py kill flag) and stamps a kill cooldown.

  AdmissionGate — a priority-aware token bucket bounding concurrently
      EXECUTING statements (`performance.token-limit`). Point gets and
      DML outrank large analytical scans (priority from the planner's
      cost estimate); waiters queue in (priority, FIFO) order and shed
      with a typed "server busy" error after
      `performance.admission-timeout-ms` instead of piling up.

HBM staging in copr/client.py makes over-admission more expensive than
on CPU — a statement admitted past the memory limit does not just page,
it evicts device column cache entries — so the gate sits *before*
run_physical, not inside it.
"""

from __future__ import annotations

import heapq
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional

from . import failpoint

# statement priorities for the admission gate: point lookups and DML
# (latency-sensitive, small working sets) outrank analytical scans
PRI_POINT = 10
PRI_DML = 10
PRI_SMALL = 5
PRI_SCAN = 0

# a small scan by the planner's estimate stays latency-class
SMALL_SCAN_ROWS = 10_000

# governor poll cadence on the tracker-consume hot path: re-evaluate
# the ledger every this-many bytes of root-tracker growth
GOV_POLL_BYTES = 4 << 20


class AdmissionTimeout(Exception):
    """Typed "server busy" shed: the statement waited
    admission-timeout-ms in the execution queue without getting a
    token (reference family: 9003 ER_TIKV_SERVER_BUSY — the backoffer's
    server-busy class, surfaced here at the admission edge)."""

    errno = 9003  # ER_TIKV_SERVER_BUSY
    sqlstate = "HY000"


def _total_ram_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import os
        return os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        return 16 << 30  # last resort: assume 16 GiB


def _rss_bytes() -> int:
    try:
        import os
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        import resource
        import sys
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss if sys.platform == "darwin" else rss * 1024


def parse_mem_limit(spec: Any, total: Optional[int] = None) -> int:
    """`performance.server-memory-limit` forms -> bytes:

        0 / "0"        disabled
        8589934592     absolute bytes
        "80%"          fraction of physical RAM
        "0.8"          same fraction, decimal form

    Raises ValueError on anything else (config.validate maps it to a
    ConfigError so typos fail at startup, matching the strict decode)."""
    if spec is None:
        return 0
    if isinstance(spec, bool):
        raise ValueError(f"invalid server-memory-limit {spec!r}")
    if isinstance(spec, int):
        if spec < 0:
            raise ValueError("server-memory-limit must be >= 0")
        return spec
    s = str(spec).strip()
    if not s:
        return 0
    if s.endswith("%"):
        frac = float(s[:-1]) / 100.0
    else:
        v = float(s)
        if v >= 1 or v == 0:
            if v != int(v):
                raise ValueError(
                    f"server-memory-limit bytes must be integral: {s!r}")
            return int(v)
        frac = v  # negatives fall through to the range check below
    if not 0 < frac <= 1:
        raise ValueError(
            f"server-memory-limit fraction out of (0, 1]: {s!r}")
    return int(frac * (total if total is not None else _total_ram_bytes()))


def plan_priority(plan) -> int:
    """Admission priority of a physical plan: point gets highest, small
    estimated scans middle, everything else (large/unknown analytical
    work) lowest — the planner cost estimate is the tiebreaker the
    ISSUE's "point/DML outrank large scans" policy needs."""
    from ..plan.physical import PhysPointGet, PhysTableRead

    if isinstance(plan, PhysPointGet):
        return PRI_POINT
    total = 0.0
    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, PhysTableRead):
            er = getattr(n, "est_rows", None)
            if er is None:
                return PRI_SCAN  # unknown cardinality: assume large
            total += float(er)
        stack.extend(getattr(n, "children", None) or [])
    return PRI_SMALL if total <= SMALL_SCAN_ROWS else PRI_SCAN


class _NullCounter:
    def inc(self, amount: float = 1.0, **labels) -> None:
        pass

    def set(self, value: float, **labels) -> None:
        pass

    def get(self, **labels) -> float:
        return 0.0


class MemoryGovernor:
    """Global per-server memory ledger + kill policy.

    Sessions register their per-statement MemTracker ROOT at execution
    start and unregister at ExecContext.close; the tracker's consume
    path polls `check()` every GOV_POLL_BYTES of growth (plus once at
    registration), so pressure is evaluated exactly where memory is
    being acquired, with no background thread to leak."""

    def __init__(self, metrics=None, limit_bytes: int = 0,
                 cooldown_ms: int = 1000) -> None:
        self._lock = threading.Lock()
        self._entries: dict[int, dict] = {}
        self._next_token = 0
        self.limit_bytes = int(limit_bytes)
        self.cooldown_ms = int(cooldown_ms)
        self._last_kill = -1e18  # monotonic; epoch-distant past
        self._kill_count = 0     # metrics-independent (stats())
        self._last_usage = 0
        if metrics is not None:
            self.kills = metrics.counter(
                "tidb_governor_kills_total",
                "statements cancelled by the server memory governor")
            self.usage_gauge = metrics.gauge(
                "tidb_governor_memory_usage_bytes",
                "server memory usage at the governor's last evaluation")
            self.stmts_gauge = metrics.gauge(
                "tidb_governor_statements",
                "statements registered with the memory governor")
        else:
            self.kills = _NullCounter()
            self.usage_gauge = _NullCounter()
            self.stmts_gauge = _NullCounter()
        self.usage_gauge.set(0)
        self.stmts_gauge.set(0)
        # structured event sink (obs.EventLog) — the Storage wires its
        # per-server ring here so kills are explainable after the fact
        self.events = None

    def configure(self, limit_bytes: Optional[int] = None,
                  cooldown_ms: Optional[int] = None) -> None:
        if limit_bytes is not None:
            self.limit_bytes = int(limit_bytes)
        if cooldown_ms is not None:
            self.cooldown_ms = int(cooldown_ms)

    # ---- ledger ------------------------------------------------------------
    def register(self, tracker, kill: Callable[[], None],
                 label: str = "", conn_id: int = 0,
                 cancellable: bool = True) -> int:
        """Add a live statement's root tracker; returns the token for
        unregister(). `kill` runs OFF the statement's own thread (the
        thread that tripped the limit) — it must only flip flags, like
        Session._governor_kill does."""
        with self._lock:
            self._next_token += 1
            token = self._next_token
            self._entries[token] = {
                "token": token, "tracker": tracker, "kill": kill,
                "label": label, "conn_id": conn_id,
                "cancellable": bool(cancellable), "killed": False,
            }
            self.stmts_gauge.set(len(self._entries))
        tracker.governor = self
        # pressure is evaluated at admission too: a new statement
        # arriving into an already-over-limit server triggers the kill
        # without waiting for anyone to allocate more
        self.check()
        return token

    def unregister(self, token: int) -> None:
        with self._lock:
            e = self._entries.pop(token, None)
            self.stmts_gauge.set(len(self._entries))
        if e is not None:
            e["tracker"].governor = None

    @staticmethod
    def _weight(tracker) -> int:
        fp = getattr(tracker, "footprint", None)
        return int(fp()) if fp is not None \
            else max(int(tracker.consumed), 0)

    def tracked_bytes(self) -> int:
        with self._lock:
            entries = list(self._entries.values())
        return sum(self._weight(e["tracker"]) for e in entries)

    def current_usage(self) -> int:
        """Server memory usage: the `governor/mem-pressure` failpoint's
        synthetic value when armed (deterministic chaos), else the
        higher of process RSS and the tracked working-set sum."""
        v = failpoint.inject("governor/mem-pressure")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            usage = int(v)
        else:
            usage = max(_rss_bytes(), self.tracked_bytes())
        self._last_usage = usage
        self.usage_gauge.set(usage)
        return usage

    # ---- kill policy -------------------------------------------------------
    def check(self) -> bool:
        """Evaluate the ledger; cancel the heaviest cancellable
        statement when over limit and outside the kill cooldown.
        Returns True when a kill was issued."""
        if self.limit_bytes <= 0:
            return False
        usage = self.current_usage()
        if usage <= self.limit_bytes:
            return False
        now = time.monotonic()
        with self._lock:
            if (now - self._last_kill) * 1000.0 < self.cooldown_ms:
                return False
            cands = [e for e in self._entries.values()
                     if e["cancellable"] and not e["killed"]]
            if not cands:
                return False
            # heaviest first; ties go to the earliest-registered so the
            # choice is deterministic under equal mock trackers
            victim = max(cands,
                         key=lambda e: (self._weight(e["tracker"]),
                                        -e["token"]))
            victim["killed"] = True
            self._last_kill = now
            self._kill_count += 1
        self.kills.inc()
        if self.events is not None:
            self.events.record(
                "governor_kill", severity="warn",
                conn_id=victim["conn_id"],
                detail=f"usage {usage} > server-memory-limit "
                       f"{self.limit_bytes}; killed weight "
                       f"{self._weight(victim['tracker'])}: "
                       f"{victim['label']}")
        try:
            victim["kill"]()
        except Exception:  # noqa: BLE001 — a dead session must not
            pass           # break the allocating statement's consume
        return True

    def stats(self) -> dict:
        with self._lock:
            n = len(self._entries)
            kills = self._kill_count
        return {
            "limit_bytes": self.limit_bytes,
            "usage_bytes": self._last_usage,
            "statements": n,
            "kills": kills,
            "cooldown_ms": self.cooldown_ms,
        }


class AdmissionGate:
    """Priority-aware token bucket over concurrently executing
    statements (reference: server/server.go:141 tokenLimiter, upgraded
    with the priority queue + bounded wait the ISSUE specifies).

    tokens <= 0 means unlimited (the embedded default — tests and
    benches construct thousands of stores; only the serving config
    arms the gate). Waiters park on one Condition and admit strictly
    in (priority desc, arrival) order; a waiter that outlives
    `timeout_ms` removes itself and sheds with AdmissionTimeout."""

    def __init__(self, metrics=None, tokens: int = 0,
                 timeout_ms: int = 10000) -> None:
        self._cv = threading.Condition()
        self.tokens = int(tokens)
        self.timeout_ms = int(timeout_ms)
        self._running = 0
        self._waiters: list[list] = []  # heap of [-pri, seq, alive]
        self._depth = 0
        self._seq = 0
        self._admitted_count = 0  # metrics-independent (stats())
        self._shed_count = 0
        if metrics is not None:
            self.admitted = metrics.counter(
                "tidb_admission_admitted_total",
                "statements admitted through the execution gate")
            self.shed = metrics.counter(
                "tidb_admission_shed_total",
                "statements shed at admission-timeout (server busy)")
            self.depth_gauge = metrics.gauge(
                "tidb_admission_queue_depth",
                "statements waiting for an execution token")
            self.running_gauge = metrics.gauge(
                "tidb_admission_running",
                "statements holding an execution token")
        else:
            self.admitted = _NullCounter()
            self.shed = _NullCounter()
            self.depth_gauge = _NullCounter()
            self.running_gauge = _NullCounter()
        self.depth_gauge.set(0)
        self.running_gauge.set(0)
        # structured event sink (obs.EventLog), wired by the Storage
        self.events = None

    def configure(self, tokens: Optional[int] = None,
                  timeout_ms: Optional[int] = None) -> None:
        with self._cv:
            if tokens is not None:
                self.tokens = int(tokens)
            if timeout_ms is not None:
                self.timeout_ms = int(timeout_ms)
            self._cv.notify_all()

    def _prune(self) -> None:
        while self._waiters and not self._waiters[0][2]:
            heapq.heappop(self._waiters)

    def acquire(self, priority: int = 0,
                timeout_s: Optional[float] = None,
                info: Optional[dict] = None) -> bool:
        """Returns True when a token is now held (release() owed),
        False when the gate is unlimited; raises AdmissionTimeout on
        shed. `info` ({conn_id, sql}) attributes the shed event to the
        statement that was turned away."""
        try:
            return self._acquire(priority, timeout_s)
        except AdmissionTimeout as e:
            # event emission OUTSIDE the gate's condition lock: a shed
            # storm is exactly when the gate is contended, and the
            # ring/counter work must not serialize admitters behind it
            if self.events is not None:
                sql = str((info or {}).get("sql", ""))[:128]
                self.events.record(
                    "admission_shed", severity="warn",
                    conn_id=int((info or {}).get("conn_id", 0) or 0),
                    detail=str(e) + (f"; shed: {sql}" if sql else ""))
            raise

    def _acquire(self, priority: int,
                 timeout_s: Optional[float]) -> bool:
        with self._cv:
            if self.tokens <= 0:
                return False
            if self._running < self.tokens and self._depth == 0:
                self._running += 1
                self._admitted_count += 1
                self.admitted.inc()
                self.running_gauge.set(self._running)
                return True
            self._seq += 1
            ent = [-int(priority), self._seq, True]
            heapq.heappush(self._waiters, ent)
            self._depth += 1
            self.depth_gauge.set(self._depth)
            budget = timeout_s if timeout_s is not None \
                else self.timeout_ms / 1000.0
            deadline = time.monotonic() + budget
            try:
                while True:
                    if self.tokens <= 0:
                        return False  # reconfigured to unlimited
                    self._prune()
                    if self._running < self.tokens and self._waiters \
                            and self._waiters[0] is ent:
                        heapq.heappop(self._waiters)
                        self._running += 1
                        self._admitted_count += 1
                        self.admitted.inc()
                        self.running_gauge.set(self._running)
                        # the next-highest waiter may also fit
                        self._cv.notify_all()
                        return True
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self._shed_count += 1
                        self.shed.inc()
                        raise AdmissionTimeout(
                            f"Server is busy: no execution token within "
                            f"{int(budget * 1000)}ms (token-limit "
                            f"{self.tokens}, {self._running} executing, "
                            f"{self._depth} queued)")
                    self._cv.wait(remaining)
            finally:
                ent[2] = False
                self._prune()
                self._depth -= 1
                self.depth_gauge.set(self._depth)

    def release(self) -> None:
        with self._cv:
            if self._running > 0:
                self._running -= 1
            self.running_gauge.set(self._running)
            self._cv.notify_all()

    @contextmanager
    def admit(self, priority: int = 0,
              timeout_s: Optional[float] = None,
              info: Optional[dict] = None):
        held = self.acquire(priority, timeout_s, info)
        try:
            yield
        finally:
            if held:
                self.release()

    def stats(self) -> dict:
        with self._cv:
            return {
                "token_limit": self.tokens,
                "timeout_ms": self.timeout_ms,
                "running": self._running,
                "queue_depth": self._depth,
                "admitted": self._admitted_count,
                "shed": self._shed_count,
            }


__all__ = ["MemoryGovernor", "AdmissionGate", "AdmissionTimeout",
           "parse_mem_limit", "plan_priority",
           "PRI_POINT", "PRI_DML", "PRI_SMALL", "PRI_SCAN",
           "GOV_POLL_BYTES"]
