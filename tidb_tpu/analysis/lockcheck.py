"""Dynamic lock-order checker: instrumented locks + a global lock graph.

The runtime half of the concurrency analysis plane (the static half
lives in analysis/rules.py). Counterpart of the discipline the
reference gets from `go test -race` and TiKV's deadlock detector: every
concurrent subsystem creates its long-lived locks through `lock()` /
`rlock()` below, and an OPT-IN wrapper records, per thread, the set of
held locks and folds every (held -> acquired) pair into one
process-wide lock-order graph. A cycle in that graph is a potential
deadlock (two code paths acquire the same locks in opposite orders —
the bug class three of the last four PRs fixed post-hoc); a blocking
syscall reported by `note_blocking()` while a HOT lock is held is the
fsync-under-store-mutex class PR 12 fixed in native/kvstore.cpp.

Zero overhead when off — the same contract as Top SQL: with
TIDB_TPU_LOCK_CHECK unset, `lock()`/`rlock()` return PLAIN
threading.Lock/RLock objects (not wrappers), so the production hot
path pays nothing, not even an attribute hop. `note_blocking()` is one
module-global bool probe. Enabled (env var at process start, or
`enable()` in tests, or the [analysis] lock-check knob), every acquire
costs a thread-local list walk + one dict update under the graph lock.

Findings surface three ways: `findings()` (typed dicts, consumed by
tests and the inspection plane), the `lock-order-inversion` inspection
rule (information_schema.inspection_result), and /debug/lockgraph.
The conftest leak guard calls `held_snapshot()` after every test and
fails any test that ends with an instrumented lock still held.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Optional, Union

ENV_VAR = "TIDB_TPU_LOCK_CHECK"

# module-global fast path: note_blocking() and the lock factories probe
# this one bool; flipping it affects locks created AFTERWARDS only
# (already-created plain locks stay plain — tests enable() first, then
# build the storage under test)
_enabled = os.environ.get(ENV_VAR, "") not in ("", "0", "false", "off")

_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


class _Graph:
    """The process-wide lock-order graph. Nodes are lock names; a
    directed edge a->b means some thread acquired b while holding a.
    Bounded: one sample stack per edge, edges capped so a pathological
    run cannot grow without bound."""

    EDGE_CAP = 4096
    BLOCKING_CAP = 256

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (held, acquired) -> {"count": n, "stack": str}
        self.edges: dict[tuple, dict] = {}
        # blocking syscalls observed under a hot lock
        self.blocking: list[dict] = []
        # name -> hot flag (every instrumented lock registers here)
        self.locks: dict[str, bool] = {}

    def register(self, name: str, hot: bool) -> None:
        with self._mu:
            self.locks[name] = bool(hot) or self.locks.get(name, False)

    def add_edge(self, held: str, acquired: str) -> None:
        key = (held, acquired)
        with self._mu:
            e = self.edges.get(key)
            if e is not None:
                e["count"] += 1
                return
            if len(self.edges) >= self.EDGE_CAP:
                return
            stack = "".join(traceback.format_stack(limit=8)[:-2])
            self.edges[key] = {"count": 1, "stack": stack[-2000:]}

    def add_blocking(self, kind: str, lock_name: str,
                     detail: str) -> None:
        with self._mu:
            # dedup by (kind, lock, detail): a hot loop hitting the
            # same bad site must not flood the ring
            for b in self.blocking:
                if b["kind"] == kind and b["lock"] == lock_name \
                        and b["detail"] == detail:
                    b["count"] += 1
                    return
            if len(self.blocking) >= self.BLOCKING_CAP:
                return
            stack = "".join(traceback.format_stack(limit=8)[:-2])
            self.blocking.append({
                "kind": kind, "lock": lock_name, "detail": detail,
                "count": 1, "stack": stack[-2000:]})

    def snapshot(self) -> tuple[dict, list, dict]:
        with self._mu:
            return ({k: dict(v) for k, v in self.edges.items()},
                    [dict(b) for b in self.blocking],
                    dict(self.locks))

    def clear(self) -> None:
        with self._mu:
            self.edges.clear()
            self.blocking.clear()


GRAPH = _Graph()


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Arm the checker (tests; the [analysis] lock-check knob at server
    start). Only locks created AFTER this call are instrumented."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop recorded edges/blocking events AND the held mirror (test
    isolation). The mirror must clear here too: a thread that died
    holding a lock can never self-clear its entry, and a stale entry
    would fail every later test's leak guard. Live holders re-sync
    their entry on their next acquire/release."""
    GRAPH.clear()
    with _holders_mu:
        _holders.clear()


class _CheckedLock:
    """Instrumented Lock/RLock. Records (held -> this) edges on every
    non-reentrant acquire and keeps the thread's held list current.
    Reentrant RLock acquires neither re-record nor re-push."""

    __slots__ = ("name", "hot", "_inner", "_reentrant")

    def __init__(self, name: str, hot: bool, reentrant: bool) -> None:
        self.name = name
        self.hot = hot
        self._reentrant = reentrant
        self._inner = (threading.RLock() if reentrant
                       else threading.Lock())
        GRAPH.register(name, hot)

    def _entry(self) -> Optional[dict]:
        for e in _held():
            if e["lock"] is self:
                return e
        return None

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        held = _held()
        ent = self._entry() if self._reentrant else None
        if ent is None:
            # record intent-order edges BEFORE blocking: the edge
            # exists even if this acquire never succeeds (that is the
            # deadlocked interleaving the graph is for)
            for e in held:
                if e["lock"] is not self:
                    GRAPH.add_edge(e["lock"].name, self.name)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if ent is not None:
                ent["depth"] += 1
            else:
                held.append({"lock": self, "depth": 1})
                _mirror_sync()
        return ok

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i]["lock"] is self:
                held[i]["depth"] -= 1
                if held[i]["depth"] <= 0:
                    del held[i]
                    _mirror_sync()
                break
        self._inner.release()

    def locked(self) -> bool:
        if self._reentrant:
            raise AttributeError("RLock has no locked()")
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "rlock" if self._reentrant else "lock"
        return f"<checked-{kind} {self.name} hot={self.hot}>"


LockLike = Union[threading.Lock, threading.RLock, _CheckedLock]


def lock(name: str, hot: bool = False):
    """A mutex for long-lived subsystem state. Disabled (the default):
    a PLAIN threading.Lock — zero added cost. Enabled: a _CheckedLock
    feeding the lock-order graph. `hot` marks locks on the declared
    hot list (analysis/registry.py HOT_LOCKS): blocking syscalls while
    one is held become findings."""
    if not _enabled:
        return threading.Lock()
    return _CheckedLock(name, hot, reentrant=False)


def rlock(name: str, hot: bool = False):
    if not _enabled:
        return threading.RLock()
    return _CheckedLock(name, hot, reentrant=True)


def note_blocking(kind: str, detail: str = "") -> None:
    """Report a blocking syscall (fsync, sleep, socket send, RPC) from
    the call site about to perform it. One bool probe when disabled.
    A finding is recorded only when the calling thread holds a HOT
    instrumented lock at that moment."""
    if not _enabled:
        return
    for e in _held():
        lk = e["lock"]
        if lk.hot:
            GRAPH.add_blocking(kind, lk.name, detail)


# held-lock mirror (held_snapshot cannot reach other threads' TLS, so
# acquire/release keep this registry current). Keyed by thread IDENT —
# two servers in one process spawn same-NAMED workers (titpu-conn-
# worker-1 each), and a name key would let one thread's release erase
# the other's live record; the name rides along for display only.
_holders_mu = threading.Lock()
_holders: dict[int, tuple[str, list[str]]] = {}


def held_snapshot() -> dict[str, list[str]]:
    """Instrumented locks currently held, keyed 'name#ident' — the
    conftest leak guard fails any test that ends with a non-empty
    snapshot (an instrumented lock still held after teardown is a
    leak, exactly like an orphaned child process)."""
    with _holders_mu:
        return {f"{name}#{tid}": list(names)
                for tid, (name, names) in _holders.items() if names}


def _mirror_sync() -> None:
    names = [e["lock"].name for e in _held()]
    t = threading.current_thread()
    with _holders_mu:
        if names:
            _holders[t.ident] = (t.name, names)
        else:
            _holders.pop(t.ident, None)


def elementary_cycles(edge_pairs) -> list[list[str]]:
    """Elementary cycles over directed (a, b) edge pairs (bounded
    DFS, deduped by canonical rotation). Each cycle is a name list
    [a, b, ..., a]. THE one cycle finder — the dynamic graph below
    and the static lock-order rule (analysis/rules.py) both use it,
    so their dedup/bounds semantics can never drift apart."""
    adj: dict[str, set] = {}
    for (a, b) in edge_pairs:
        adj.setdefault(a, set()).add(b)
    cycles: list[list[str]] = []
    seen_keys: set = set()

    def dfs(start: str, node: str, path: list[str],
            on_path: set) -> None:
        if len(cycles) >= 32 or len(path) > 8:
            return
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                cyc = path + [start]
                # canonical rotation so each cycle reports once
                k = min(range(len(cyc) - 1),
                        key=lambda i: cyc[i])
                key = tuple(cyc[k:-1] + cyc[:k])
                if key not in seen_keys:
                    seen_keys.add(key)
                    rot = list(key) + [key[0]]
                    cycles.append(rot)
            elif nxt not in on_path and nxt > start:
                # only expand nodes > start: each cycle found from its
                # smallest node exactly once
                on_path.add(nxt)
                dfs(start, nxt, path + [nxt], on_path)
                on_path.discard(nxt)

    for n in sorted(adj):
        dfs(n, n, [n], {n})
    return cycles


def find_cycles() -> list[list[str]]:
    """Elementary cycles in the LIVE lock-order graph: some thread
    took b under a while another path takes a under b — a potential
    deadlock."""
    edges, _, _ = GRAPH.snapshot()
    return elementary_cycles(edges)


def findings() -> list[dict]:
    """Typed findings: lock-order cycles (potential deadlock) and
    blocking syscalls observed under a hot lock."""
    out: list[dict] = []
    edges, blocking, _ = GRAPH.snapshot()
    for cyc in find_cycles():
        sample = ""
        for i in range(len(cyc) - 1):
            e = edges.get((cyc[i], cyc[i + 1]))
            if e is not None:
                sample = e["stack"]
                break
        out.append({"kind": "lock-order-inversion",
                    "cycle": cyc,
                    "item": " -> ".join(cyc),
                    "stack": sample})
    for b in blocking:
        out.append({"kind": "blocking-under-hot-lock",
                    "item": f"{b['kind']} under {b['lock']}",
                    "count": b["count"],
                    "detail": b["detail"],
                    "stack": b["stack"]})
    return out


def debug_payload() -> dict:
    """/debug/lockgraph: enabled flag, registered locks, edges with
    counts, cycles, blocking events, currently-held mirror."""
    edges, blocking, locks = GRAPH.snapshot()
    return {
        "enabled": _enabled,
        "locks": [{"name": n, "hot": h}
                  for n, h in sorted(locks.items())],
        "edges": [{"held": a, "acquired": b, "count": e["count"]}
                  for (a, b), e in sorted(edges.items())],
        "cycles": [" -> ".join(c) for c in find_cycles()],
        "blocking": [{k: v for k, v in b.items() if k != "stack"}
                     for b in blocking],
        "held": held_snapshot(),
    }


__all__ = ["ENV_VAR", "enabled", "enable", "disable", "reset", "lock",
           "rlock", "note_blocking", "held_snapshot",
           "find_cycles", "findings", "debug_payload", "GRAPH"]
