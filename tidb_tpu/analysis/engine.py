"""The project static-analysis engine: rule registry + source tree +
baseline ratchet.

Deliberately the same shape as the inspection engine (obs_inspect.py):
rules are registered with a name, a severity and reference text, and
are PURE FUNCTIONS over one bounded snapshot — there an
InspectionContext of live telemetry, here a SourceTree of parsed ASTs.
`lint_rules()` applies the identical registry-hygiene contract.

The baseline file (analysis/baseline.txt) is the ratchet: findings
keyed (rule, path, item) that predate the engine are committed there
with a one-line reason and burn down over time; a NEW finding — one
not in the baseline — fails `--check` (and the tier-1 test that wraps
it). Keys deliberately exclude line numbers so unrelated edits don't
churn the file.

Import-light by design: this module and everything it pulls must never
import jax (or the package's executor/planner chain) — `python -m
tidb_tpu.analysis --check` runs inside tier-1 and in CI shells where
warming a device backend to lint source text would be absurd.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional

SEVERITIES = ("info", "warning", "critical")

# repo root: tidb_tpu/analysis/engine.py -> tidb_tpu -> repo
REPO_ROOT = Path(__file__).resolve().parent.parent.parent
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.txt"


@dataclass(frozen=True)
class AnalysisFinding:
    rule: str
    path: str        # repo-relative posix path
    line: int        # 1-based; 0 = whole-file/projectwide
    item: str        # stable identity within (rule, path) — no lines
    severity: str
    message: str

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.item)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"[{self.severity}] {self.rule} {loc} ({self.item}): " \
               f"{self.message}"


class AnalysisRule:
    __slots__ = ("name", "severity", "reference", "fn")

    def __init__(self, name: str, severity: str, reference: str,
                 fn: Callable) -> None:
        self.name = name
        self.severity = severity
        self.reference = reference
        self.fn = fn


RULES: dict[str, AnalysisRule] = {}


def rule(name: str, severity: str, reference: str):
    """Register one static rule (same metadata contract as
    obs_inspect.rule; lint_rules re-checks it in tier-1)."""
    def deco(fn: Callable) -> Callable:
        if not name or not reference:
            raise ValueError(
                f"analysis rule needs name+reference, got {name!r}")
        if severity not in SEVERITIES:
            raise ValueError(
                f"analysis rule {name}: severity {severity!r} not in "
                f"{SEVERITIES}")
        if name in RULES:
            raise ValueError(f"analysis rule {name} already registered")
        RULES[name] = AnalysisRule(name, severity, reference, fn)
        return fn
    return deco


def lint_rules(rules: Optional[dict] = None) -> list[str]:
    """Registry hygiene: kebab-case names, valid severity, reference
    text present, callable fn — identical to obs_inspect.lint_rules."""
    findings: list[str] = []
    for name, r in (RULES if rules is None else rules).items():
        if not name or name != name.lower() or " " in name \
                or "_" in name:
            findings.append(f"rule {name!r}: name must be kebab-case")
        if getattr(r, "severity", None) not in SEVERITIES:
            findings.append(
                f"rule {name}: severity {getattr(r, 'severity', None)!r}"
                f" not in {SEVERITIES}")
        if not getattr(r, "reference", ""):
            findings.append(f"rule {name}: missing reference text")
        if not callable(getattr(r, "fn", None)):
            findings.append(f"rule {name}: fn is not callable")
    return findings


# ---- the source snapshot rules run over -------------------------------------

class SourceFile:
    __slots__ = ("path", "text", "tree", "parse_error")

    def __init__(self, path: str, text: str) -> None:
        self.path = path
        self.text = text
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.tree = ast.Module(body=[], type_ignores=[])
            self.parse_error = str(e)


class SourceTree:
    """One parsed snapshot of the project's Python sources. Product
    code (tidb_tpu/) and tests are kept distinct — several rules hold
    them to different contracts. Tests build tiny synthetic trees via
    `from_files` to pin each rule's fire/silent behavior."""

    def __init__(self, files: dict[str, str],
                 aux: Optional[dict[str, str]] = None) -> None:
        self.files = {p: SourceFile(p, t)
                      for p, t in sorted(files.items())}
        # non-Python inputs some rules read (config.toml.example);
        # absent in synthetic test trees, whose rules then no-op
        self.aux: dict[str, str] = dict(aux or {})
        self._class_attr_index: Optional[dict] = None

    @classmethod
    def load(cls, root: Optional[Path] = None) -> "SourceTree":
        root = Path(root) if root else REPO_ROOT
        files: dict[str, str] = {}
        for base in ("tidb_tpu", "tests"):
            d = root / base
            if not d.is_dir():
                continue
            for p in sorted(d.rglob("*.py")):
                if "__pycache__" in p.parts:
                    continue
                rel = p.relative_to(root).as_posix()
                files[rel] = p.read_text(encoding="utf-8",
                                         errors="replace")
        for extra in ("bench.py",):
            p = root / extra
            if p.is_file():
                files[extra] = p.read_text(encoding="utf-8",
                                           errors="replace")
        aux = {}
        toml = root / "config.toml.example"
        if toml.is_file():
            aux["config.toml.example"] = toml.read_text(
                encoding="utf-8", errors="replace")
        return cls(files, aux)

    @classmethod
    def from_files(cls, files: dict[str, str],
                   aux: Optional[dict[str, str]] = None) -> "SourceTree":
        return cls(dict(files), aux)

    # ---- helpers rules share -------------------------------------------
    def product_files(self):
        for p, f in self.files.items():
            if p.startswith("tidb_tpu/") and \
                    not p.startswith("tidb_tpu/analysis/"):
                yield f

    def test_files(self):
        for p, f in self.files.items():
            if p.startswith("tests/") or p == "bench.py":
                yield f

    def all_files(self):
        yield from self.files.values()

    def class_attr_index(self) -> dict[str, set]:
        """attr name -> {ClassName} for every `self.X = ...` assignment
        inside a class body, project-wide — the receiver-resolution
        index the lock rules use (`st._commit_lock` resolves to the
        unique class that creates `_commit_lock`)."""
        if self._class_attr_index is not None:
            return self._class_attr_index
        idx: dict[str, set] = {}
        for f in self.product_files():
            for cls_node in ast.walk(f.tree):
                if not isinstance(cls_node, ast.ClassDef):
                    continue
                for node in ast.walk(cls_node):
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                    elif isinstance(node, ast.AnnAssign):
                        targets = [node.target]
                    else:
                        continue
                    for t in targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            idx.setdefault(t.attr, set()).add(
                                cls_node.name)
        self._class_attr_index = idx
        return idx


# ---- shared AST utilities ---------------------------------------------------

def call_name(node: ast.AST) -> str:
    """Dotted tail of a call's func: `os.fsync` -> 'os.fsync',
    `self._syncer.flush` -> 'self._syncer.flush', `foo` -> 'foo'."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def str_prefix(node: ast.AST) -> Optional[str]:
    """The STATIC prefix of a string expression: a literal's full text,
    an f-string's leading literal text (possibly ''), None for
    anything unknowable."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        if node.values and isinstance(node.values[0], ast.Constant) \
                and isinstance(node.values[0].value, str):
            return node.values[0].value
        return ""
    return None


def enclosing_function_name(stack: list) -> str:
    """Qualified-ish name from an ancestor stack: Class.method, or
    function, or '(module)'."""
    names = [n.name for n in stack
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))]
    return ".".join(names) if names else "(module)"


def walk_with_stack(tree: ast.AST):
    """(node, ancestor_stack) depth-first — several rules need the
    enclosing function/class for stable item names."""
    stack: list = []

    def rec(node):
        yield node, stack
        push = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef))
        if push:
            stack.append(node)
        for child in ast.iter_child_nodes(node):
            yield from rec(child)
        if push:
            stack.pop()

    yield from rec(tree)


# ---- baseline ratchet -------------------------------------------------------

def load_baseline(path: Optional[Path] = None) -> dict[tuple, str]:
    """baseline.txt -> {(rule, path, item): reason}. Line format:
    `rule | path | item | reason` with '#' comments; malformed lines
    are ignored loudly by check() (they can never mask a finding)."""
    p = Path(path) if path else BASELINE_PATH
    out: dict[tuple, str] = {}
    if not p.is_file():
        return out
    for line in p.read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = [s.strip() for s in line.split("|")]
        if len(parts) >= 4:
            out[(parts[0], parts[1], parts[2])] = parts[3]
    return out


def format_baseline_line(f: AnalysisFinding, reason: str) -> str:
    return f"{f.rule} | {f.path} | {f.item} | {reason}"


def run(tree: Optional[SourceTree] = None,
        rules: Optional[dict] = None) -> list[AnalysisFinding]:
    """Evaluate every registered rule over one source snapshot. A rule
    that raises degrades to an info finding naming itself (same
    contract as the inspection engine) — analysis must never crash on
    the code it analyzes."""
    from . import rules as _rules  # noqa: F401 — registers on import
    if tree is None:
        tree = SourceTree.load()
    findings: list[AnalysisFinding] = []
    for r in (RULES if rules is None else rules).values():
        try:
            findings.extend(r.fn(tree) or ())
        except Exception as e:  # noqa: BLE001 — report, don't crash
            findings.append(AnalysisFinding(
                r.name, "(rule)", 0, "rule-error", "info",
                f"rule raised {type(e).__name__}: {str(e)[:200]}"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.item))
    return findings


def check(tree: Optional[SourceTree] = None,
          baseline: Optional[dict] = None
          ) -> tuple[list[AnalysisFinding], list[tuple]]:
    """The ratchet: (new_findings, stale_baseline_keys). New findings
    (not baselined) fail --check / the tier-1 test; stale entries —
    baselined findings that no longer fire — are reported for removal
    but do not fail (the burn-down is the point)."""
    if baseline is None:
        baseline = load_baseline()
    findings = run(tree)
    live_keys = {f.key() for f in findings}
    new = [f for f in findings if f.key() not in baseline]
    stale = [k for k in baseline if k not in live_keys]
    return new, stale


__all__ = ["AnalysisFinding", "AnalysisRule", "RULES", "rule",
           "lint_rules", "SourceTree", "SourceFile", "run", "check",
           "load_baseline", "format_baseline_line", "call_name",
           "str_prefix", "walk_with_stack", "enclosing_function_name",
           "REPO_ROOT", "BASELINE_PATH", "SEVERITIES"]
