"""Declared concurrency/hygiene registries the static rules check
against.

One place, in product code, that SAYS what the conventions are — the
rules in analysis/rules.py enforce them. Adding a hot lock, an engine
tag family or a TLS frame helper means adding it HERE first; an
undeclared one is a finding. (Failpoint names live with their runtime
in util/failpoint.py DECLARED — same idea, different owner.)
"""

from __future__ import annotations

# ---- hot locks --------------------------------------------------------------
# Locks on the commit/serving hot path: holding one while performing a
# blocking syscall serializes every writer (or reader) behind disk or
# network. Key = the RESOLVED lock node ("Class.attr", the same naming
# the static rule derives and lockcheck registers); value = why it is
# hot, for the finding text. Qualified on purpose: `_mu` is a hot
# store mutex on MVCCStore but a cold registry mutex on
# CoordRPCServer, and an attr-level match would conflate them.
HOT_LOCKS: dict[str, str] = {
    "Storage._commit_lock":
        "the storage commit lock — every commit, fold and closed-ts "
        "computation serializes under it (store/storage.py)",
    "Storage.infoschema_lock":
        "schema/DDL mutations + every statement's schema validation "
        "pass through it",
    "MVCCStore._mu":
        "the MVCC store mutex — prewrite/commit/read sections "
        "serialize under it (kv/mvcc.py)",
    "NativeOrderedKV._mu":
        "the native store mutex — the PR 12 bug was an fsync under "
        "exactly this lock, which serialized every writer behind the "
        "disk barrier (kv/native.py)",
    "RangeServer._mu":
        "the hosted-leader map lock — every cross-process 2PC request "
        "passes its fencing gate under it, so a lease renewal doing "
        "disk I/O inside would stall every range's writers at once "
        "(rpc/ranged.py)",
    "RangeHeatRecorder._mu":
        "the keyspace heat recorder's cell ring — every point read, "
        "scan, and 2PC commit notes its traffic under it while the "
        "heatmap is enabled, so any blocking call inside would "
        "serialize the whole statement path behind it (obs_heat.py)",
}

# ---- blocking calls ---------------------------------------------------------
# Call shapes the blocking-call-under-hot-lock rule flags inside a
# `with <hot lock>:` body. Matched against the dotted tail of the call
# (`os.fsync` matches `os.fsync(...)`; a bare name matches any
# attribute call ending in it, e.g. `.sendall`).
BLOCKING_CALLS: tuple[str, ...] = (
    "os.fsync", "fsync", "time.sleep", "sleep",
    "sendall", "send", "recv", "recv_into", "connect", "accept",
    "subprocess.run", "subprocess.check_output", "urlopen",
    # disk metadata syscalls: a stat against a contended volume blocks
    # like a read does
    "os.path.getsize", "os.stat", "fcntl.flock",
    # the RPC tier's budgeted call entry points
    "call", "call_with_retry",
)
# receivers whose .send/.recv/.call are NOT sockets/RPC (queue-ish and
# generator-ish false-positive names)
BLOCKING_RECEIVER_ALLOW: tuple[str, ...] = ("gen", "coro", "chan")

# ---- TLS frames -------------------------------------------------------------
# Thread-local push/pop helpers that MUST be finally-paired: the
# restore call has to sit in a `finally:` of a try statement that
# begins immediately after the install (any statement in between can
# raise and leak the frame onto the thread — the bug class the
# tls-frame-hygiene rule exists for). Names are matched on the called
# function's tail identifier.
TLS_FRAME_FNS: tuple[str, ...] = (
    "install_session_time_zone",   # copr/funcs.py — session time zone
    "install_stage_recorder",      # obs.py — per-statement recorder
)
# context-manager-only frames: calling one OUTSIDE a `with` item (or a
# return feeding one) leaves the frame management to the caller and is
# almost always a leak
TLS_FRAME_CTX_ONLY: tuple[str, ...] = (
    "placement_scope",             # copr/client.py, copr/mesh.py
)

# ---- thread discipline ------------------------------------------------------
# Every threading.Thread() started inside tidb_tpu/ must carry a name
# with this prefix (the conftest leak guard and /debug surfaces key on
# it) and either be a daemon or have a join site in its module.
THREAD_NAME_PREFIX = "titpu-"

# ---- engine tags ------------------------------------------------------------
# The EXPLAIN ANALYZE / slow-log / Top SQL `engine` column families —
# the one enum the engine-tag rule checks literal producers against
# (obs.note_engine() / `<result>.engine = ...` sites). A produced tag
# must START with one of these.
ENGINE_TAG_FAMILIES: tuple[str, ...] = (
    "device",      # device, device@mesh8, device[fat]@mesh8
    "ranged",      # host index-range path
    "host(",       # host fallback with the gate reason embedded
    "point",       # the OLTP point fast path (plan/fastpath.py)
    "replica@",    # follower read tier (rpc/replica.py)
    "range#",      # per-range gate verdicts: range#<id>@gated
    "ranges@",     # range-aware covering summary: ranges@covered(...)
)

# bracketed device fragment modes — the exact vocabulary inside
# device[<mode>] / device[<mode>]@meshN tags (copr/fragment.py emode).
# Tooling that switches on the bracket contents (bench.py path lines,
# the golden engines corpus, the README coverage matrix) recognizes
# exactly these; test_golden_plans lints the recorded corpus against
# this enum so a new spelling must be declared here first.
#   agg    dense-segment fused join+aggregation
#   rows   fused joins returning a probe-row bitmask
#   topn   fused join+topn (packed multi-key composite)
#   hc     high-cardinality candidate path (plain, host re-ranks)
#   fat    fused hc final cut (exact device ordering, k+1 rows out)
#   group  all-groups sorted-run aggregation (dense gate rejected)
#   +semi  suffix: semi/anti membership bitmap gates fused in
DEVICE_FRAGMENT_MODES: tuple[str, ...] = (
    "agg", "rows", "topn", "hc", "fat", "group",
    "agg+semi", "rows+semi", "topn+semi", "hc+semi", "fat+semi",
    "group+semi",
)

__all__ = ["HOT_LOCKS", "BLOCKING_CALLS", "BLOCKING_RECEIVER_ALLOW",
           "TLS_FRAME_FNS", "TLS_FRAME_CTX_ONLY", "THREAD_NAME_PREFIX",
           "ENGINE_TAG_FAMILIES", "DEVICE_FRAGMENT_MODES"]
