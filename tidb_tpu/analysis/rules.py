"""The shipped static rules: project-specific concurrency + hygiene
checks over the parsed source tree.

Each rule is a pure function SourceTree -> [AnalysisFinding] with the
same registration contract as the inspection rules (name, severity,
reference). Items are chosen to be stable under unrelated edits (no
line numbers in keys) so the committed baseline only churns when the
finding itself appears or disappears.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from .engine import (AnalysisFinding, SourceTree, call_name, rule,
                     str_prefix, walk_with_stack,
                     enclosing_function_name)
from . import registry as reg

_LOCKISH = re.compile(r"(lock|mutex|_mu|_cv)$")


def _resolve_lock_node(tree: SourceTree, expr: ast.AST,
                       stack: list) -> Optional[str]:
    """A with-item context expression -> a stable lock node name
    ('Class.attr'), or None when it isn't a lock or cannot be resolved
    unambiguously (ambiguity must not fabricate graph edges)."""
    if not isinstance(expr, ast.Attribute):
        return None
    attr = expr.attr
    if not _LOCKISH.search(attr):
        return None
    if isinstance(expr.value, ast.Name) and expr.value.id == "self":
        for n in reversed(stack):
            if isinstance(n, ast.ClassDef):
                return f"{n.name}.{attr}"
        return None
    owners = tree.class_attr_index().get(attr, set())
    if len(owners) == 1:
        return f"{next(iter(owners))}.{attr}"
    return None


def _iter_with_lock_items(tree: SourceTree, f):
    """Yield (With-node, [(lock_node_name, attr)], stack) for every
    with-statement in the file that acquires at least one lock-like
    attribute."""
    for node, stack in walk_with_stack(f.tree):
        if not isinstance(node, ast.With):
            continue
        locks = []
        for item in node.items:
            name = _resolve_lock_node(tree, item.context_expr, stack)
            if name is not None:
                locks.append((name, item.context_expr.attr))
        if locks:
            yield node, locks, list(stack)


def _body_calls(node: ast.With):
    """Call nodes inside a with-body, skipping deferred execution
    (nested function/lambda bodies run later, not under the lock)."""
    def rec(n):
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                yield child
            yield from rec(child)
    for stmt in node.body:
        yield from rec(stmt)
        if isinstance(stmt, ast.Call):
            yield stmt


def _is_blocking_call(call: ast.Call) -> Optional[str]:
    name = call_name(call.func)
    if not name:
        return None
    parts = name.split(".")
    tail = parts[-1]
    for pat in reg.BLOCKING_CALLS:
        if "." in pat:
            if name == pat or name.endswith("." + pat):
                return pat
        elif tail == pat:
            recv = parts[-2] if len(parts) > 1 else ""
            if recv in reg.BLOCKING_RECEIVER_ALLOW:
                continue
            return pat
    return None


def _class_method_map(f) -> dict[tuple, ast.FunctionDef]:
    """(ClassName, method) -> FunctionDef for one file."""
    out = {}
    for node in ast.walk(f.tree):
        if isinstance(node, ast.ClassDef):
            for ch in node.body:
                if isinstance(ch, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    out[(node.name, ch.name)] = ch
    return out


def _scan_blocking(calls, methods, cls_name, depth=1):
    """(call, pattern, via) triples: direct blocking calls plus one
    level of same-class helper expansion — `self._wal_size()` under
    the commit lock is the bug even though getsize lives one frame
    down."""
    for call in calls:
        pat = _is_blocking_call(call)
        if pat is not None:
            yield call, pat, ""
            continue
        if depth <= 0:
            continue
        name = call_name(call.func)
        parts = name.split(".")
        if len(parts) == 2 and parts[0] == "self":
            helper = methods.get((cls_name, parts[1]))
            if helper is None:
                continue
            inner = [n for s in helper.body for n in ast.walk(s)
                     if isinstance(n, ast.Call)]
            for _, ipat, _ in _scan_blocking(inner, methods,
                                             cls_name, depth=0):
                yield call, ipat, parts[1]
                break


@rule("blocking-call-under-hot-lock", "critical",
      "analysis/registry.py HOT_LOCKS — no fsync/sleep/socket/RPC "
      "while holding a declared hot lock (the PR 12 "
      "fsync-under-store-mutex class: every writer serializes behind "
      "the syscall); checks the lock body plus one level of "
      "same-class helpers")
def _r_blocking_under_hot_lock(tree: SourceTree):
    out = []
    for f in tree.product_files():
        methods = None
        for node, locks, stack in _iter_with_lock_items(tree, f):
            hot = [(n, a) for (n, a) in locks if n in reg.HOT_LOCKS]
            if not hot:
                continue
            if methods is None:
                methods = _class_method_map(f)
            cls = next((n.name for n in reversed(stack)
                        if isinstance(n, ast.ClassDef)), "")
            fn = enclosing_function_name(stack)
            for call, pat, via in _scan_blocking(
                    _body_calls(node), methods, cls):
                lock_name = hot[0][0]
                via_txt = f" (via self.{via}())" if via else ""
                out.append(AnalysisFinding(
                    "blocking-call-under-hot-lock", f.path,
                    call.lineno,
                    f"{fn}:{hot[0][1]}:{pat}", "critical",
                    f"{call_name(call.func)}(){via_txt} under hot "
                    f"lock {lock_name} "
                    f"({reg.HOT_LOCKS[lock_name][:80]})"))
    return out


@rule("lock-order", "critical",
      "static lock-acquisition graph over nested `with <lock>:` "
      "blocks — a cycle means two code paths take the same locks in "
      "opposite orders (potential deadlock); fix the order or break "
      "the nesting (TIDB_TPU_LOCK_CHECK catches the dynamic cases)")
def _r_lock_order(tree: SourceTree):
    # edges: (outer, inner) -> sample (path, line)
    edges: dict[tuple, tuple] = {}

    def walk_stmts(f, stmts, held: list, stack: list) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested def is a new execution context: locks held
                # at its DEFINITION are not held when it runs
                stack.append(stmt)
                walk_stmts(f, stmt.body, [], stack)
                stack.pop()
                continue
            if isinstance(stmt, ast.ClassDef):
                stack.append(stmt)
                walk_stmts(f, stmt.body, held, stack)
                stack.pop()
                continue
            acquired: list[str] = []
            if isinstance(stmt, ast.With):
                for item in stmt.items:
                    name = _resolve_lock_node(tree, item.context_expr,
                                              stack)
                    if name is None:
                        continue
                    for h in held + acquired:
                        if h != name:
                            edges.setdefault(
                                (h, name), (f.path, stmt.lineno))
                    acquired.append(name)
            for _, body in ast.iter_fields(stmt):
                if not isinstance(body, list) or not body:
                    continue
                if isinstance(body[0], ast.stmt):
                    walk_stmts(f, body, held + acquired, stack)
                elif isinstance(body[0], ast.excepthandler):
                    # Try.handlers holds ExceptHandler wrappers, not
                    # stmts — error-path acquisitions are exactly
                    # where order inversions hide
                    for h in body:
                        walk_stmts(f, h.body, held + acquired, stack)

    for f in tree.product_files():
        walk_stmts(f, f.tree.body, [], [])

    # THE shared elementary-cycle finder (lockcheck.elementary_cycles)
    # so the static and dynamic halves can never drift in dedup or
    # bound semantics
    from .lockcheck import elementary_cycles
    out = []
    for cyc in elementary_cycles(edges):
        sp, sl = edges[(cyc[-2], cyc[-1])] \
            if (cyc[-2], cyc[-1]) in edges else edges[(cyc[0], cyc[1])]
        out.append(AnalysisFinding(
            "lock-order", sp, sl, " -> ".join(cyc), "critical",
            "lock acquisition order inversion: "
            + "; ".join(
                f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
                for a, b in zip(cyc, cyc[1:]) if (a, b) in edges)))
    return out


def _stmt_calls_fn(stmt: ast.stmt, fn_tail: str) -> bool:
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and \
                call_name(n.func).split(".")[-1] == fn_tail:
            return True
    return False


@rule("tls-frame-hygiene", "warning",
      "analysis/registry.py TLS_FRAME_FNS — a thread-local frame "
      "install must be IMMEDIATELY followed by the try whose finally "
      "restores it (any statement in between can raise and leak the "
      "frame onto the worker thread)")
def _r_tls_frames(tree: SourceTree):
    out = []
    frame_fns = set(reg.TLS_FRAME_FNS)
    ctx_only = set(reg.TLS_FRAME_CTX_ONLY)
    for f in tree.product_files():
        # finally-paired installs
        for node, stack in walk_with_stack(f.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            fname = node.name
            if fname in frame_fns:
                continue  # the frame helper's own definition

            def scan_block(stmts, in_finally, in_protected):
                for i, stmt in enumerate(stmts):
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        continue
                    hit = next((fn for fn in frame_fns
                                if _stmt_calls_fn(stmt, fn)), None)
                    if hit and not isinstance(stmt, ast.Try):
                        ok = in_finally or in_protected
                        if not ok:
                            nxt = stmts[i + 1] if i + 1 < len(stmts) \
                                else None
                            ok = isinstance(nxt, ast.Try) and any(
                                _stmt_calls_fn(s, hit)
                                for s in nxt.finalbody)
                        if not ok:
                            out.append(AnalysisFinding(
                                "tls-frame-hygiene", f.path,
                                stmt.lineno,
                                f"{fname}:{hit}", "warning",
                                f"{hit}() install is not finally-"
                                f"paired: the restoring try/finally "
                                f"must begin on the very next "
                                f"statement"))
                    if isinstance(stmt, ast.Try):
                        protected = any(
                            _stmt_calls_fn(s, fn)
                            for s in stmt.finalbody
                            for fn in frame_fns)
                        scan_block(stmt.body,
                                   in_finally,
                                   in_protected or protected)
                        for h in stmt.handlers:
                            scan_block(h.body, in_finally,
                                       in_protected)
                        scan_block(stmt.orelse, in_finally,
                                   in_protected or protected)
                        scan_block(stmt.finalbody, True,
                                   in_protected)
                    elif isinstance(stmt, (ast.If, ast.For,
                                           ast.While, ast.With)):
                        for field in ("body", "orelse", "finalbody"):
                            sub = getattr(stmt, field, None)
                            if sub:
                                scan_block(sub, in_finally,
                                           in_protected)

            scan_block(node.body, False, False)
        # context-manager-only frames: a call outside a with-item
        with_items = set()
        for node, _ in walk_with_stack(f.tree):
            if isinstance(node, ast.With):
                for item in node.items:
                    with_items.add(id(item.context_expr))
        for node, stack in walk_with_stack(f.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node.func).split(".")[-1] in ctx_only \
                    and id(node) not in with_items:
                fname = enclosing_function_name(stack)
                if fname.split(".")[-1] in ctx_only:
                    continue  # the helper's own definition/recursion
                out.append(AnalysisFinding(
                    "tls-frame-hygiene", f.path, node.lineno,
                    f"{fname}:{call_name(node.func).split('.')[-1]}",
                    "warning",
                    f"{call_name(node.func)}() is declared "
                    f"context-manager-only; use it as a `with` item"))
    return out


# an IDENTIFIER.join( call — `", ".join(...)` (string) fails the
# identifier requirement and `os.path.join(`/`posixpath.join(` is
# excluded by name, so only thread-ish joins satisfy the join-path
# heuristic
_THREAD_JOIN = re.compile(r"[^\"'\w]([A-Za-z_]\w*)\.join\(")


def _has_thread_join(text: str) -> bool:
    return any(m.group(1) not in ("path", "posixpath", "ntpath")
               for m in _THREAD_JOIN.finditer(text))


@rule("thread-discipline", "warning",
      "tests/conftest.py leak guard + /debug surfaces key on thread "
      "names — every threading.Thread started in tidb_tpu/ must be "
      "named 'titpu-*' and be a daemon or have a join path in its "
      "module")
def _r_thread_discipline(tree: SourceTree):
    out = []
    for f in tree.product_files():
        has_join = _has_thread_join(f.text)
        prefix_ok_consts = set(re.findall(
            r'_thread_prefix\s*=\s*["\'](titpu-[^"\']*)["\']', f.text))
        for node, stack in walk_with_stack(f.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = call_name(node.func)
            if cname not in ("threading.Thread", "Thread"):
                continue
            fn = enclosing_function_name(stack)
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            name_node = kw.get("name")
            named_ok = False
            if name_node is not None:
                prefix = str_prefix(name_node)
                if prefix is not None and \
                        prefix.startswith(reg.THREAD_NAME_PREFIX):
                    named_ok = True
                elif isinstance(name_node, ast.JoinedStr) and \
                        name_node.values and \
                        isinstance(name_node.values[0],
                                   ast.FormattedValue):
                    head = name_node.values[0].value
                    if isinstance(head, ast.Attribute) and \
                            head.attr == "_thread_prefix" and \
                            prefix_ok_consts:
                        named_ok = True
            if not named_ok:
                out.append(AnalysisFinding(
                    "thread-discipline", f.path, node.lineno,
                    f"{fn}:name", "warning",
                    "threading.Thread without a static 'titpu-*' name"))
            daemon = kw.get("daemon")
            is_daemon = isinstance(daemon, ast.Constant) and \
                daemon.value is True
            if not is_daemon and not has_join:
                out.append(AnalysisFinding(
                    "thread-discipline", f.path, node.lineno,
                    f"{fn}:join", "warning",
                    "non-daemon thread with no join() path in its "
                    "module"))
    return out


_FP_NAME = re.compile(r"\A[a-z0-9_]+(?:/[a-z0-9_.-]+)+\Z")


def _env_spec_names(value: str) -> list[str]:
    """Failpoint names out of a TIDB_TPU_FAILPOINTS-shaped string,
    parsed exactly like failpoint.arm_from_env (';'-separated
    name=value pairs whose name is a slash path) — prose that happens
    to contain '=' never matches."""
    names = []
    for part in value.split(";"):
        name, eq, _ = part.strip().partition("=")
        if eq and _FP_NAME.match(name.strip()):
            names.append(name.strip())
    return names


def _declared_failpoints(tree: SourceTree) -> Optional[set]:
    """The DECLARED frozenset parsed out of util/failpoint.py's AST —
    read statically so synthetic test trees can carry their own."""
    f = tree.files.get("tidb_tpu/util/failpoint.py")
    if f is None:
        return None
    for node in ast.walk(f.tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "DECLARED"
                for t in node.targets):
            names = set()
            for n in ast.walk(node.value):
                if isinstance(n, ast.Constant) and \
                        isinstance(n.value, str):
                    names.add(n.value)
            return names
    return None


@rule("failpoint-registry", "warning",
      "util/failpoint.py DECLARED — every failpoint.inject() site "
      "uses a declared name and every name a test arms exists in the "
      "runtime (an undeclared armed point silently never fires)")
def _r_failpoints(tree: SourceTree):
    declared = _declared_failpoints(tree)
    if declared is None:
        return []
    out = []
    inject_sites: dict[str, tuple] = {}
    for f in tree.product_files():
        for node, stack in walk_with_stack(f.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node.func).endswith("failpoint.inject") \
                    and node.args:
                lit = str_prefix(node.args[0])
                if lit:
                    inject_sites.setdefault(lit,
                                            (f.path, node.lineno))
    for name, (path, line) in sorted(inject_sites.items()):
        if name not in declared:
            out.append(AnalysisFinding(
                "failpoint-registry", path, line, name, "warning",
                f"failpoint.inject({name!r}) is not in "
                f"util/failpoint.py DECLARED"))
    for name in sorted(declared - set(inject_sites)):
        out.append(AnalysisFinding(
            "failpoint-registry", "tidb_tpu/util/failpoint.py", 0,
            name, "warning",
            f"DECLARED failpoint {name!r} has no inject() site"))
    # names armed by tests (context manager / enable / env var specs);
    # the env-spec scan only runs in files that actually mention the
    # env var — random prose containing '=' must not be parsed as an
    # arming spec
    for f in tree.test_files():
        scan_env = "TIDB_TPU_FAILPOINTS" in f.text
        for node, _ in walk_with_stack(f.tree):
            if isinstance(node, ast.Call):
                tail = call_name(node.func).split(".")[-1]
                if tail in ("failpoint", "enable") and node.args:
                    lit = str_prefix(node.args[0])
                    if lit and "/" in lit and lit not in declared:
                        out.append(AnalysisFinding(
                            "failpoint-registry", f.path,
                            node.lineno, lit, "warning",
                            f"test arms undeclared failpoint "
                            f"{lit!r}"))
            elif scan_env and isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    "=" in node.value and "/" in node.value:
                for name in _env_spec_names(node.value):
                    if name not in declared:
                        out.append(AnalysisFinding(
                            "failpoint-registry", f.path,
                            node.lineno, name, "warning",
                            f"env spec arms undeclared failpoint "
                            f"{name!r}"))
    return out


@rule("bare-except", "warning",
      "a bare `except:`/`except BaseException:` on the statement path "
      "swallows QueryInterrupted/KeyboardInterrupt and breaks the "
      "kill/governor plane; catch Exception (or narrower), or "
      "re-raise")
def _r_bare_except(tree: SourceTree):
    out = []
    for f in tree.product_files():
        counts: dict[str, int] = {}
        for node, stack in walk_with_stack(f.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            bare = node.type is None
            base = isinstance(node.type, ast.Name) and \
                node.type.id == "BaseException"
            if not (bare or base):
                continue
            reraises = any(isinstance(n, ast.Raise) and n.exc is None
                           for s in node.body for n in ast.walk(s))
            if base and reraises:
                continue  # catch-log-reraise is the legitimate shape
            fn = enclosing_function_name(stack)
            idx = counts.get(fn, 0)
            counts[fn] = idx + 1
            out.append(AnalysisFinding(
                "bare-except", f.path, node.lineno,
                f"{fn}:{idx}", "warning",
                ("bare `except:`" if bare else
                 "`except BaseException:` without re-raise")
                + " swallows interrupts"))
    return out


@rule("engine-tag", "warning",
      "analysis/registry.py ENGINE_TAG_FAMILIES — every produced "
      "EXPLAIN/slow-log/Top SQL engine tag starts with a declared "
      "family, so tooling that switches on the tag never meets an "
      "unknown spelling")
def _r_engine_tags(tree: SourceTree):
    out = []

    def check(f, node, value, fn):
        prefix = str_prefix(value)
        if prefix is None or prefix == "":
            return  # dynamic tag — the producer owns it
        if any(prefix.startswith(fam) or fam.startswith(prefix)
               for fam in reg.ENGINE_TAG_FAMILIES):
            return
        out.append(AnalysisFinding(
            "engine-tag", f.path, node.lineno,
            f"{fn}:{prefix[:32]}", "warning",
            f"engine tag {prefix!r} matches no declared family "
            f"{list(reg.ENGINE_TAG_FAMILIES)}"))

    for f in tree.product_files():
        for node, stack in walk_with_stack(f.tree):
            fn = enclosing_function_name(stack)
            if isinstance(node, ast.Call) and \
                    call_name(node.func).split(".")[-1] == \
                    "note_engine" and node.args:
                check(f, node, node.args[0], fn)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "engine":
                        check(f, node, node.value, fn)
    return out


_METRIC_REG_FNS = ("counter", "gauge", "histogram")
_METRIC_REF_FNS = ("metric_family", "metric_delta", "metric")


@rule("metric-families", "warning",
      "obs.py registries — every metric family the inspection/"
      "metrics_schema tier references by name must have a literal "
      "registration site (a renamed family silently zeroes every "
      "rule that read it)")
def _r_metric_families(tree: SourceTree):
    registered: set[str] = set()
    for f in tree.product_files():
        for node, _ in walk_with_stack(f.tree):
            if isinstance(node, ast.Call) and \
                    call_name(node.func).split(".")[-1] in \
                    _METRIC_REG_FNS and node.args:
                lit = str_prefix(node.args[0])
                if lit and lit.startswith("tidb_"):
                    registered.add(lit)
    if not registered:
        return []
    out = []
    for f in tree.product_files():
        for node, stack in walk_with_stack(f.tree):
            if not (isinstance(node, ast.Call) and
                    call_name(node.func).split(".")[-1] in
                    _METRIC_REF_FNS and node.args):
                continue
            lit = str_prefix(node.args[0])
            if not lit or not lit.startswith("tidb_"):
                continue
            family = lit.split("{", 1)[0]
            if family not in registered:
                out.append(AnalysisFinding(
                    "metric-families", f.path, node.lineno, family,
                    "warning",
                    f"references metric family {family!r} with no "
                    f"literal registration site"))
    return out


def _flatten_toml(raw: dict) -> list[tuple[str, str]]:
    """[('', 'port'), ('storage', 'sync-log'), ...]"""
    out = []
    for k, v in raw.items():
        if isinstance(v, dict):
            for kk in v:
                out.append((k, kk))
        else:
            out.append(("", k))
    return out


class _SysvarSink:
    """Captures Config.seed_sysvars writes (duck-typed storage)."""

    def __init__(self) -> None:
        self.values: dict[str, object] = {}
        self.sysvars = self

    def set_config_default(self, name, value):
        self.values[name] = value


@rule("config-knob-drift", "warning",
      "config.toml.example is the contract: every documented knob "
      "must parse into a Config field AND have a read site, and every "
      "config-seeded sysvar's registry default must equal the config "
      "default (SHOW VARIABLES on an embedded store must not lie)")
def _r_config_drift(tree: SourceTree):
    toml_text = tree.aux.get("config.toml.example")
    if toml_text is None:
        return []
    try:
        import tomllib
        raw = tomllib.loads(toml_text)
    except ImportError:
        from ..config import _parse_toml_subset
        raw = _parse_toml_subset(toml_text)
    from ..config import Config
    cfg = Config()
    out = []
    # a read site is an ATTRIBUTE read `.field` anywhere in product
    # code — config.py's own seed_*/validate functions count (they
    # are how knobs reach the runtime) but the dataclass declaration
    # itself does not (no leading dot); CLI flags count (kebab form)
    read_corpus = "\n".join(f.text for f in tree.product_files())
    for section, key in _flatten_toml(raw):
        snake = key.replace("-", "_")
        dotted = f"{section}.{key}" if section else key
        owner = cfg
        if section:
            owner = getattr(cfg, section.replace("-", "_"), None)
            if owner is None:
                out.append(AnalysisFinding(
                    "config-knob-drift", "config.toml.example", 0,
                    dotted, "warning",
                    f"section [{section}] has no Config field"))
                continue
        if not hasattr(owner, snake):
            out.append(AnalysisFinding(
                "config-knob-drift", "config.toml.example", 0,
                dotted, "warning",
                f"knob {dotted} has no parsed Config field"))
            continue
        if not re.search(rf"\.{re.escape(snake)}\b", read_corpus) \
                and f"--{key}" not in read_corpus:
            out.append(AnalysisFinding(
                "config-knob-drift", "config.toml.example", 0,
                dotted, "warning",
                f"knob {dotted} parses into Config.{snake} but "
                f"nothing outside config.py reads it"))
    # sysvar half: simulate seeding from a DEFAULT config and compare
    # against the registry defaults (loaded standalone so this never
    # imports the session/executor chain)
    sink = _SysvarSink()
    cfg.seed_sysvars(sink)
    defaults = _sysvar_defaults()
    if defaults is not None:
        for name, seeded in sorted(sink.values.items()):
            if name not in defaults:
                out.append(AnalysisFinding(
                    "config-knob-drift", "tidb_tpu/config.py", 0,
                    f"sysvar:{name}", "warning",
                    f"seed_sysvars seeds unknown sysvar {name!r}"))
            elif str(defaults[name]) != str(seeded):
                out.append(AnalysisFinding(
                    "config-knob-drift", "tidb_tpu/config.py", 0,
                    f"sysvar:{name}", "warning",
                    f"sysvar {name} registry default "
                    f"{defaults[name]!r} != config-seeded default "
                    f"{seeded!r}"))
    return out


def _sysvar_defaults() -> Optional[dict]:
    """session/sysvars.py's registry defaults via a standalone module
    load (the session package import chain would pull the executor)."""
    import importlib.util
    import sys
    from .engine import REPO_ROOT
    path = REPO_ROOT / "tidb_tpu" / "session" / "sysvars.py"
    if not path.is_file():
        return None
    name = "_titpu_analysis_sysvars"
    cached = sys.modules.get(name)
    if cached is not None:
        return {v.name: v.default for v in cached._VARS}
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves cls.__module__ through sys.modules at class
    # creation, so the module must be registered before exec
    sys.modules[name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(name, None)
        raise
    return {v.name: v.default for v in mod._VARS}
