"""Concurrency analysis plane: static rules + dynamic lock checking.

Two halves over one philosophy — the checker exists BEFORE the next
five roadmap items add more threads and locks, not after:

* static (engine.py + rules.py): an AST rule engine in the
  obs_inspect registry style (named, severity-graded,
  reference-linked rules; committed baseline so pre-existing findings
  burn down rather than block) run as `python -m tidb_tpu.analysis`
  and as a tier-1 test (tests/test_analysis.py).
* dynamic (lockcheck.py): opt-in instrumented locks
  (TIDB_TPU_LOCK_CHECK / [analysis] lock-check) feeding a global
  lock-order graph — cycle findings surface through the inspection
  plane (`lock-order-inversion`) and /debug/lockgraph.

Import-light by contract: nothing under tidb_tpu/analysis/ may import
jax or the executor/planner chain (the engine parses source text, it
never imports the code it checks).
"""

from .engine import (AnalysisFinding, RULES, SourceTree, check,
                     lint_rules, load_baseline, run, rule)
from . import lockcheck

__all__ = ["AnalysisFinding", "RULES", "SourceTree", "check",
           "lint_rules", "load_baseline", "run", "rule", "lockcheck"]
