"""CLI: `python -m tidb_tpu.analysis [--check|--baseline|--list]`.

Default: print every finding (baselined ones marked). `--check` is
the CI/tier-1 entry point — exit 0 iff no finding is missing from the
baseline (stale baseline entries are reported for removal but do not
fail; burning down is the point). `--baseline` rewrites baseline.txt
from the current findings, preserving reasons for keys that survive.
No jax, no device, no server import — this is safe in any shell.
"""

from __future__ import annotations

import argparse
import sys

from .engine import (BASELINE_PATH, RULES, SourceTree, check,
                     format_baseline_line, load_baseline, run)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tidb_tpu.analysis",
        description="TiTPU project static analysis")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero on any finding not in the "
                         "baseline (the CI / tier-1 mode)")
    ap.add_argument("--baseline", action="store_true",
                    help="rewrite analysis/baseline.txt from the "
                         "current findings")
    ap.add_argument("--list", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--rule", default=None,
                    help="run only the named rule")
    args = ap.parse_args(argv)

    from . import rules as _rules  # noqa: F401 — registers rules
    if args.list:
        for name, r in sorted(RULES.items()):
            print(f"{name:32s} {r.severity:8s} {r.reference}")
        return 0

    tree = SourceTree.load()
    rules = None
    if args.rule:
        if args.rule not in RULES:
            print(f"unknown rule {args.rule!r}; --list shows the "
                  f"registry", file=sys.stderr)
            return 2
        rules = {args.rule: RULES[args.rule]}

    if args.check:
        if rules is None:
            new, stale = check(tree)
        else:
            # single-rule gate: the ratchet applies to that rule's
            # findings against that rule's slice of the baseline
            baseline = {k: v for k, v in load_baseline().items()
                        if k[0] == args.rule}
            findings = run(tree, rules=rules)
            live = {f.key() for f in findings}
            new = [f for f in findings if f.key() not in baseline]
            stale = [k for k in baseline if k not in live]
        for f in new:
            print(f.render())
        for key in stale:
            print(f"stale baseline entry (finding no longer fires — "
                  f"remove the line): {' | '.join(key)}")
        if new:
            print(f"\n{len(new)} new finding(s) not in "
                  f"{BASELINE_PATH.name}; fix them or baseline with "
                  f"a reason", file=sys.stderr)
            return 1
        print(f"analysis clean: 0 new findings, "
              f"{len(load_baseline())} baselined, "
              f"{len(stale)} stale")
        return 0

    findings = run(tree, rules=rules)
    baseline = load_baseline()
    for f in findings:
        mark = "  [baselined]" if f.key() in baseline else ""
        print(f.render() + mark)
    if args.baseline:
        old = load_baseline()
        lines = [
            "# analysis baseline — findings that predate the rule (or",
            "# are deliberate); format: rule | path | item | reason.",
            "# New findings are NOT auto-admitted: python -m",
            "# tidb_tpu.analysis --check fails until a finding is",
            "# fixed or a human adds it here with a reason.",
        ]
        for f in findings:
            reason = old.get(f.key(), "TODO: justify or fix")
            lines.append(format_baseline_line(f, reason))
        BASELINE_PATH.write_text("\n".join(lines) + "\n",
                                 encoding="utf-8")
        print(f"wrote {len(findings)} entries to {BASELINE_PATH}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
