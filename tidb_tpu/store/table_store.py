"""Per-table MVCC columnar storage: immutable base epochs + row deltas.

This is the TPU-first answer to the reference's row store + columnar replica
split (TiKV MVCC + TiFlash delta tree; see SURVEY.md §7 hard-part 5).
Version resolution is branchy and belongs on the host:

* The **base epoch** is an immutable set of flat column arrays. It is what
  gets cached on device (the moral equivalent of the reference's coprocessor
  cache, store/tikv/coprocessor_cache.go:30) and what kernels scan.
* **Deltas** are committed row mutations `(commit_ts, handle, row|TOMBSTONE)`
  kept host-side in commit order. A snapshot read at `snap_ts` sees the base
  epoch minus overridden handles, plus the latest visible delta per handle —
  merged into a small "overlay" chunk the device treats as one more tile.
* **Compaction** folds deltas at or below the GC-safe ts into a new epoch
  (reference analog: resolved-lock GC + region compaction).

Handles are int64 row ids, auto-allocated or taken from an integer primary
key (reference: pk-is-handle, table/tables.go).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..catalog.schema import TableInfo
from ..chunk.column import Column, Dictionary, EnumDictionary, _encode_scalar
from ..kv.memdb import TOMBSTONE
from ..types.field_type import TypeKind


def _column_dictionary(ftype) -> Optional[Dictionary]:
    """Dictionary for string-physical columns; ENUM gets the fixed
    definition-ordered validating dictionary."""
    if ftype.kind == TypeKind.ENUM:
        return EnumDictionary(ftype.elems)
    return Dictionary() if ftype.is_string else None

_epoch_ids = itertools.count(1)


class HandleIndex:
    """handle -> row-position map over an epoch's handle array.

    Replaces the eager {int(h): i} dict, whose 180M-entry incarnation
    cost ~15GB of small-int objects at bench scale (the r05 SF100 OOM).
    Nothing is built until the first point lookup: bulk-load + scan
    workloads never pay. Contiguous handles (the bulk-load shape) answer
    with arithmetic; anything else argsorts once and binary-searches."""

    __slots__ = ("_handles", "_mode", "_base", "_sorted", "_order")

    def __init__(self, handles: np.ndarray) -> None:
        self._handles = handles
        self._mode: Optional[str] = None

    def _resolve(self) -> None:
        h = self._handles
        n = len(h)
        if n == 0:
            self._mode = "empty"
            return
        base = int(h[0])
        if int(h[-1]) - base == n - 1 and bool(
                (h == np.arange(base, base + n, dtype=np.int64)).all()):
            self._base = base
            self._mode = "contig"
            return
        self._order = np.argsort(h, kind="stable")
        self._sorted = h[self._order]
        self._mode = "sorted"

    def get(self, handle: int, default=None):
        if self._mode is None:
            self._resolve()
        if self._mode == "empty":
            return default
        if self._mode == "contig":
            i = handle - self._base
            return int(i) if 0 <= i < len(self._handles) else default
        j = int(np.searchsorted(self._sorted, handle))
        if j < len(self._sorted) and int(self._sorted[j]) == handle:
            return int(self._order[j])
        return default

    def __contains__(self, handle: int) -> bool:
        return self.get(handle) is not None

    def __len__(self) -> int:
        return len(self._handles)


@dataclass
class ColumnEpoch:
    """Immutable columnar snapshot of all rows folded up to fold_ts."""

    epoch_id: int
    fold_ts: int
    handles: np.ndarray  # int64[n]
    columns: list[np.ndarray]  # physical data per table column
    valids: list[Optional[np.ndarray]]  # None = all valid
    # handle -> row position; built lazily from handles when not carried
    # over from a predecessor epoch with identical handles
    handle_pos: Optional[HandleIndex] = None

    def __post_init__(self) -> None:
        if not isinstance(self.handle_pos, HandleIndex):
            self.handle_pos = HandleIndex(self.handles)

    @property
    def num_rows(self) -> int:
        return len(self.handles)


@dataclass
class TableSnapshot:
    """A point-in-time readable view: device-friendly base + host overlay."""

    table: TableInfo
    dictionaries: list[Optional[Dictionary]]
    epoch: ColumnEpoch
    # False where a base row is overridden/deleted at this snapshot's ts
    base_visible: np.ndarray  # bool[epoch.num_rows]
    overlay_handles: np.ndarray  # int64[m] rows added/updated after fold_ts
    overlay_columns: list[np.ndarray]
    overlay_valids: list[Optional[np.ndarray]]
    # backref for index lookups (epoch sort-order cache lives on the store)
    store: Any = None
    _overlay_pos: Optional[dict] = field(default=None, repr=False)

    @property
    def num_visible_rows(self) -> int:
        return int(self.base_visible.sum()) + len(self.overlay_handles)

    def overlay_pos(self) -> dict:
        if self._overlay_pos is None:
            self._overlay_pos = {
                int(h): i for i, h in enumerate(self.overlay_handles)
            }
        return self._overlay_pos

    def has_handle(self, handle: int) -> bool:
        """True if a live row with this handle is visible at the snapshot."""
        if handle in self.overlay_pos():
            return True
        pos = self.epoch.handle_pos.get(handle)
        return pos is not None and bool(self.base_visible[pos])

    def gather(self, handles: np.ndarray, offsets: list[int]):
        """Rows for the given (visible) handles as per-offset (data, valid)
        arrays, in handle-argument order. The point-get / index-lookup read
        path (reference: executor/point_get.go, executor/distsql.go
        IndexLookUp table task) — O(k), never materializes the table."""
        k = len(handles)
        ov_pos = self.overlay_pos()
        base_rows = np.empty(k, dtype=np.int64)
        ov_rows = np.empty(k, dtype=np.int64)
        from_overlay = np.zeros(k, dtype=bool)
        for i, h in enumerate(handles):
            oi = ov_pos.get(int(h))
            if oi is not None:
                from_overlay[i] = True
                ov_rows[i] = oi
                base_rows[i] = 0
            else:
                pos = self.epoch.handle_pos.get(int(h))
                assert pos is not None and self.base_visible[pos], (
                    f"gather of non-visible handle {h}")
                base_rows[i] = pos
                ov_rows[i] = 0
        out = []
        for off in offsets:
            dt = self.table.columns[off].ftype.np_dtype
            if self.epoch.num_rows:
                data = self.epoch.columns[off][base_rows].astype(dt, copy=True)
            else:
                data = np.zeros(k, dtype=dt)
            valid = np.ones(k, dtype=bool)
            bv = self.epoch.valids[off]
            if bv is not None and self.epoch.num_rows:
                valid &= bv[base_rows] | from_overlay
            if from_overlay.any():
                data[from_overlay] = self.overlay_columns[off][
                    ov_rows[from_overlay]]
                ovv = self.overlay_valids[off]
                if ovv is not None:
                    valid[from_overlay] = ovv[ov_rows[from_overlay]]
            out.append((data, valid))
        return out

    def column(self, offset: int) -> Column:
        """Materialize one full visible column (host path / small tables)."""
        ft = self.table.columns[offset].ftype
        base_data = self.epoch.columns[offset][self.base_visible]
        base_valid = self.epoch.valids[offset]
        if base_valid is not None:
            base_valid = base_valid[self.base_visible]
        data = np.concatenate([base_data, self.overlay_columns[offset]])
        ov_valid = self.overlay_valids[offset]
        if base_valid is None and ov_valid is None:
            valid = None
        else:
            bv = base_valid if base_valid is not None else np.ones(len(base_data), bool)
            ov = ov_valid if ov_valid is not None else np.ones(
                len(self.overlay_columns[offset]), bool)
            valid = np.concatenate([bv, ov])
        return Column(ft, data, valid, self.dictionaries[offset])

    def handles(self) -> np.ndarray:
        return np.concatenate(
            [self.epoch.handles[self.base_visible], self.overlay_handles]
        )


def _empty_epoch(table: TableInfo) -> ColumnEpoch:
    return ColumnEpoch(
        epoch_id=next(_epoch_ids),
        fold_ts=0,
        handles=np.empty(0, dtype=np.int64),
        columns=[np.empty(0, dtype=c.ftype.np_dtype) for c in table.columns],
        valids=[None] * len(table.columns),
    )


class TableStore:
    """MVCC store for one table."""

    # fold deltas into a fresh epoch once this many are visible to everyone
    COMPACT_THRESHOLD = 8192

    def __init__(self, table: TableInfo) -> None:
        self.table = table
        self.dictionaries: list[Optional[Dictionary]] = [
            _column_dictionary(c.ftype) for c in table.columns
        ]
        self.epoch = _empty_epoch(table)
        # committed mutations after epoch.fold_ts, in commit-ts order
        self.deltas: list[tuple[int, int, Any]] = []  # (commit_ts, handle, row)
        self._next_handle = 1
        self._lock = threading.RLock()
        # (epoch_id, index_id) -> sorted permutation; see store/index.py
        self._index_orders: dict[tuple[int, int], np.ndarray] = {}
        # rows touched since creation — the auto-analyze delta feed
        # (reference: stats delta in handle/update.go)
        self.modify_count = 0
        # bumped by every DDL that changes this table's schema; txns that
        # buffered writes under an older token must abort at commit
        # (reference: schema validator fencing, domain/schema_validator.go)
        self.schema_token = 0
        # durable-storage hook: fired after every base-epoch replacement
        # (bulk_load / compact / apply_schema / cast_column) so the owner
        # can persist the columnar snapshot (Storage._on_epoch_changed).
        # `required=False` (compaction) only marks the epoch dirty: the
        # folded deltas are still recoverable from the KV truth, so the
        # snapshot write can defer to checkpoint()/GC instead of stalling
        # the committing session on an O(table) file write
        self.on_epoch = None
        self.epoch_dirty = False
        # eager-eviction hooks: fired on every base-epoch replacement so
        # device-resident caches (the mesh plane's sharded epochs pin
        # HBM on EVERY device) free the superseded epoch's buffers now,
        # not on the next dispatch (Storage.add_epoch_listener attaches)
        self.evict_hooks: list = []

    def _epoch_changed(self, required: bool = True) -> None:
        if self.on_epoch is not None:
            self.on_epoch(self, required)
        for fn in list(self.evict_hooks):
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — cache eviction must
                pass           # never fail the committing session

    def restore_epoch(self, epoch: ColumnEpoch,
                      dictionaries: list[Optional[Dictionary]],
                      next_handle: int) -> None:
        """Install a recovered columnar snapshot (restart recovery path)."""
        with self._lock:
            self.epoch = epoch
            self.dictionaries = dictionaries
            self._next_handle = max(self._next_handle, next_handle)

    # ---- write path --------------------------------------------------------
    def alloc_handle(self) -> int:
        with self._lock:
            h = self._next_handle
            self._next_handle += 1
            return h

    def note_handle(self, handle: int) -> None:
        """Keep auto-alloc above explicitly-written pk-is-handle values."""
        with self._lock:
            if handle >= self._next_handle:
                self._next_handle = handle + 1

    def encode_row(self, values: list[Any]) -> tuple:
        """Host scalars -> physical tuple (dictionary side effects included)."""
        assert len(values) == self.table.num_columns
        out = []
        for v, col, d in zip(values, self.table.columns, self.dictionaries):
            if v is None:
                out.append(None)
            else:
                out.append(_encode_scalar(col.ftype, v, d))
        return tuple(out)

    def apply_commit(self, commit_ts: int, handle: int, row: Any) -> None:
        """Record one committed mutation (row tuple or TOMBSTONE)."""
        with self._lock:
            self.deltas.append((commit_ts, handle, row))
            self.modify_count += 1

    def latest_commit_ts(self, handle: int) -> int:
        """Newest commit touching handle (0 if only in base/absent) —
        the write-conflict check input."""
        with self._lock:
            for commit_ts, h, _ in reversed(self.deltas):
                if h == handle:
                    return commit_ts
        return 0

    # ---- read path ---------------------------------------------------------
    def snapshot(
        self,
        snap_ts: int,
        txn_overlay: Optional[dict[int, Any]] = None,
    ) -> TableSnapshot:
        """Build the visible view at snap_ts, optionally unioned with an
        uncommitted txn buffer (read-your-writes; reference analog:
        executor/union_scan.go over kv/union_iter.go)."""
        with self._lock:
            epoch = self.epoch
            # latest visible version per handle among deltas
            visible: dict[int, Any] = {}
            for commit_ts, handle, row in self.deltas:
                if commit_ts <= snap_ts:
                    visible[handle] = row
            if txn_overlay:
                visible.update(txn_overlay)

        base_visible = np.ones(epoch.num_rows, dtype=bool)
        ov_handles: list[int] = []
        ov_rows: list[tuple] = []
        for handle, row in visible.items():
            pos = epoch.handle_pos.get(handle)
            if pos is not None:
                base_visible[pos] = False
            if row is not TOMBSTONE:
                ov_handles.append(handle)
                ov_rows.append(row)

        ncols = self.table.num_columns
        ov_columns: list[np.ndarray] = []
        ov_valids: list[Optional[np.ndarray]] = []
        for ci in range(ncols):
            dt = self.table.columns[ci].ftype.np_dtype
            data = np.zeros(len(ov_rows), dtype=dt)
            valid = np.ones(len(ov_rows), dtype=bool)
            for ri, row in enumerate(ov_rows):
                v = row[ci]
                if v is None:
                    valid[ri] = False
                else:
                    data[ri] = v
            ov_columns.append(data)
            ov_valids.append(None if valid.all() else valid)

        return TableSnapshot(
            table=self.table,
            dictionaries=self.dictionaries,
            epoch=epoch,
            base_visible=base_visible,
            overlay_handles=np.array(ov_handles, dtype=np.int64),
            overlay_columns=ov_columns,
            overlay_valids=ov_valids,
            store=self,
        )

    # ---- bulk load ----------------------------------------------------------
    def bulk_load(
        self,
        columns: list[np.ndarray],
        valids: Optional[list[Optional[np.ndarray]]] = None,
        commit_ts: int = 0,
    ) -> None:
        """Append pre-encoded column arrays directly into a new base epoch.

        The loader path of cmd/importer (reference: cmd/importer) — bypasses
        the transaction layer; intended for benchmarks and dataset loads.
        Physical encodings must match the table's column types (dictionary
        codes for strings, scaled ints for decimals, day numbers for dates).
        """
        if len(columns) != self.table.num_columns:
            raise ValueError(
                f"bulk_load: {len(columns)} columns for "
                f"{self.table.num_columns}-column table")
        n = len(columns[0]) if columns else 0
        for ci, c in enumerate(columns):
            if len(c) != n:
                raise ValueError(
                    f"bulk_load: column {ci} has {len(c)} rows, expected {n}")
        if valids is not None:
            for ci, v in enumerate(valids):
                if v is not None and len(v) != n:
                    raise ValueError(
                        f"bulk_load: valids[{ci}] has {len(v)} rows, "
                        f"expected {n}")
        with self._lock:
            epoch = self.epoch
            self.modify_count += n
            handles = np.arange(self._next_handle, self._next_handle + n,
                                dtype=np.int64)
            self._next_handle += n
            new_cols = []
            new_valids: list[Optional[np.ndarray]] = []
            for ci in range(self.table.num_columns):
                dt = self.table.columns[ci].ftype.np_dtype
                if epoch.num_rows == 0:
                    # adopt the caller's arrays without copying: a SF100
                    # load is ~60GB of columns and a concatenate would
                    # double the peak footprint. Epoch columns are
                    # treated as immutable everywhere.
                    new_cols.append(columns[ci].astype(dt, copy=False))
                else:
                    new_cols.append(np.concatenate(
                        [epoch.columns[ci], columns[ci].astype(dt)]))
                add_v = valids[ci] if valids is not None else None
                old_v = epoch.valids[ci]
                if old_v is None and add_v is None:
                    new_valids.append(None)
                else:
                    ov = old_v if old_v is not None else np.ones(
                        epoch.num_rows, bool)
                    av = add_v if add_v is not None else np.ones(n, bool)
                    new_valids.append(np.concatenate([ov, av]))
            all_handles = np.concatenate([epoch.handles, handles])
            self.epoch = ColumnEpoch(
                epoch_id=next(_epoch_ids),
                fold_ts=max(epoch.fold_ts, commit_ts),
                handles=all_handles,
                columns=new_cols,
                valids=new_valids,
            )
        self._epoch_changed()

    # ---- schema change (DDL reorg primitives) ------------------------------
    def apply_schema(self, new_info: TableInfo,
                     column_map: list, fills: dict) -> None:
        """Swap to a new TableInfo, rewriting stored data to its layout.

        column_map[i] = old offset backing new column i, or None for a new
        column (filled from fills[i] = (phys_default, valid)). Old snapshots
        stay consistent: they hold the previous TableInfo object and epoch
        (immutable); only new snapshots see the new layout. This is the
        storage half of the DDL state machine (reference: delete-only/
        write-only states guard TiKV row format changes, ddl/column.go —
        here the epoch swap is atomic under the store lock)."""
        with self._lock:
            epoch = self.epoch
            n = epoch.num_rows
            cols: list[np.ndarray] = []
            valids: list[Optional[np.ndarray]] = []
            dicts: list[Optional[Dictionary]] = []
            for i, c in enumerate(new_info.columns):
                src = column_map[i]
                if src is None:
                    dv, dvalid = fills[i]
                    dt = c.ftype.np_dtype
                    d = _column_dictionary(c.ftype)
                    if dvalid and isinstance(dv, str):
                        dv = d.encode(dv)  # string default -> fresh code
                    cols.append(np.full(n, dv if dvalid else 0, dtype=dt))
                    valids.append(None if dvalid else np.zeros(n, bool))
                    dicts.append(d)
                    fills[i] = (dv, dvalid)  # deltas reuse the encoded value
                else:
                    data = epoch.columns[src]
                    if data.dtype != c.ftype.np_dtype:
                        data = data.astype(c.ftype.np_dtype)
                    cols.append(data)
                    valids.append(epoch.valids[src])
                    dicts.append(self.dictionaries[src])
            new_deltas = []
            for commit_ts, handle, row in self.deltas:
                if row is not TOMBSTONE:
                    row = tuple(
                        (row[column_map[i]] if column_map[i] is not None
                         else (fills[i][0] if fills[i][1] else None))
                        for i in range(len(new_info.columns)))
                new_deltas.append((commit_ts, handle, row))
            self.table = new_info
            self.dictionaries = dicts
            self.deltas = new_deltas
            self.epoch = ColumnEpoch(
                epoch_id=next(_epoch_ids),
                fold_ts=epoch.fold_ts,
                handles=epoch.handles,
                columns=cols,
                valids=valids,
                handle_pos=epoch.handle_pos,
            )
            self._index_orders.clear()
            self.schema_token += 1
        self._epoch_changed()

    def cast_column(self, offset: int, cast_fn,
                    new_info: Optional[TableInfo] = None) -> Optional[str]:
        """Rewrite one column's physical values (MODIFY COLUMN reorg).
        cast_fn(data, valid) -> (new_data, new_valid) or raises ValueError;
        returns an error string on failure (job rolls back).

        new_info, when given, is swapped in atomically with the rewritten
        epoch: a snapshot must never pair new physical values with the old
        FieldType (e.g. a DECIMAL(10,2)->INT rescale read back at scale 2)
        — mirror of apply_schema's atomic table+epoch swap."""
        with self._lock:
            epoch = self.epoch
            try:
                data, valid = cast_fn(
                    epoch.columns[offset],
                    epoch.valids[offset] if epoch.valids[offset] is not None
                    else np.ones(epoch.num_rows, bool))
                new_deltas = []
                for commit_ts, handle, row in self.deltas:
                    if row is not TOMBSTONE and row[offset] is not None:
                        v, ok = cast_fn(np.array([row[offset]]),
                                        np.ones(1, bool))
                        if not ok[0]:
                            raise ValueError(f"cannot convert {row[offset]}")
                        row = row[:offset] + (v[0].item(),) + row[offset + 1:]
                    new_deltas.append((commit_ts, handle, row))
            except (ValueError, OverflowError) as e:
                return str(e)
            cols = list(epoch.columns)
            valids = list(epoch.valids)
            cols[offset] = data
            valids[offset] = None if valid.all() else valid
            self.deltas = new_deltas
            self.epoch = ColumnEpoch(
                epoch_id=next(_epoch_ids),
                fold_ts=epoch.fold_ts,
                handles=epoch.handles,
                columns=cols,
                valids=valids,
                handle_pos=epoch.handle_pos,
            )
            if new_info is not None:
                self.table = new_info
            self._index_orders.clear()
            self.schema_token += 1
        self._epoch_changed()
        return None

    # ---- compaction --------------------------------------------------------
    def maybe_compact(self, safe_ts: int) -> None:
        if len(self.deltas) >= self.COMPACT_THRESHOLD:
            self.compact(safe_ts)

    def compact(self, safe_ts: int) -> None:
        """Fold deltas with commit_ts <= safe_ts into a new immutable epoch.

        safe_ts must not exceed the oldest active snapshot ts (the Storage
        layer enforces this — GC-safepoint analog, store/tikv/gcworker).
        """
        with self._lock:
            epoch = self.epoch
            folding: dict[int, Any] = {}
            remaining: list[tuple[int, int, Any]] = []
            for commit_ts, handle, row in self.deltas:
                if commit_ts <= safe_ts:
                    folding[handle] = row
                else:
                    remaining.append((commit_ts, handle, row))
            if not folding:
                return

            keep = np.ones(epoch.num_rows, dtype=bool)
            for handle in folding:
                pos = epoch.handle_pos.get(handle)
                if pos is not None:
                    keep[pos] = False
            new_rows = [(h, r) for h, r in folding.items() if r is not TOMBSTONE]
            new_rows.sort(key=lambda x: x[0])  # handle order keeps scans stable

            ncols = self.table.num_columns
            handles = np.concatenate(
                [epoch.handles[keep], np.array([h for h, _ in new_rows], np.int64)]
            )
            columns: list[np.ndarray] = []
            valids: list[Optional[np.ndarray]] = []
            for ci in range(ncols):
                dt = self.table.columns[ci].ftype.np_dtype
                add = np.zeros(len(new_rows), dtype=dt)
                addv = np.ones(len(new_rows), dtype=bool)
                for ri, (_, row) in enumerate(new_rows):
                    v = row[ci]
                    if v is None:
                        addv[ri] = False
                    else:
                        add[ri] = v
                columns.append(np.concatenate([epoch.columns[ci][keep], add]))
                oldv = epoch.valids[ci]
                if oldv is None and addv.all():
                    valids.append(None)
                else:
                    ov = oldv[keep] if oldv is not None else np.ones(int(keep.sum()), bool)
                    valids.append(np.concatenate([ov, addv]))

            new_epoch = ColumnEpoch(
                epoch_id=next(_epoch_ids),
                fold_ts=safe_ts,
                handles=handles,
                columns=columns,
                valids=valids,
            )
            self.epoch = new_epoch
            self.deltas = remaining
        self._epoch_changed(required=False)
