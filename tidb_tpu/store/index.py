"""Secondary-index runtime over columnar epochs.

TPU-first index design. Where the reference materializes per-row index KV
entries (`t{tid}_i{iid}{vals}` keys written by table/tables/index.go and
scanned by IndexReader executors), an index here is a *sorted permutation*
of the immutable column epoch: computed lazily per (epoch, index) with
np.lexsort, cached on the TableStore, and binary-searched with
np.searchsorted for point lookups. Snapshot overlay rows (recent commits +
the txn buffer) are searched linearly — they are small by construction
(compaction folds them into the epoch).

This matches the storage design: the epoch is immutable, so its sort order
is immutable too; there is no per-write index maintenance at all (the
reference pays one index KV write per row per index, table/tables/index.go
Create). The cost moves to the first lookup after an epoch fold.

String key columns are dictionary-encoded and codes are NOT
collation-ordered, so string index columns support equality points only;
range predicates on strings stay as plain filters.

NULL semantics follow MySQL: NULLs sort first inside the permutation (so
the valid region is a suffix), equality points never match NULL, and
unique indexes admit any number of NULL keys (enforced by the DML layer
skipping NULL-keyed uniqueness checks).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..catalog.schema import IndexInfo
from .table_store import TableSnapshot, TableStore


def epoch_index_order(store: TableStore, epoch, index: IndexInfo) -> np.ndarray:
    """Sorted permutation of `epoch` (the one a snapshot pinned — NOT
    necessarily the store's live epoch; a concurrent commit may have
    compacted past it) for `index`.

    Sort key: (valid0, data0, valid1, data1, ...) with NULLs (valid=False)
    first within each column level. Cached per (epoch_id, index_id).
    """
    cache = store._index_orders
    key = (epoch.epoch_id, index.id)
    order = cache.get(key)
    if order is not None:
        return order
    # np.lexsort: LAST key is the primary sort key
    keys: list[np.ndarray] = []
    for off in reversed(index.col_offsets):
        data = epoch.columns[off]
        valid = epoch.valids[off]
        keys.append(data)
        if valid is not None:
            keys.append(valid)
    order = np.lexsort(keys) if keys else np.arange(epoch.num_rows)
    if len(cache) >= 32:
        # bounded: drop orders for epochs other than the live one (old
        # entries belong to snapshots that will release soon)
        live = store.epoch.epoch_id
        for k in list(cache):
            if k[0] != live and k != key:
                del cache[k]
    cache[key] = order
    return order


def epoch_column_order(store: TableStore, epoch, off: int
                       ) -> tuple[np.ndarray, int]:
    """(sorted permutation, start) for a single column: NULL rows sort
    first, `start` is the index of the first non-NULL position, so
    data[order[start:]] is monotone. Cached per (epoch, column) beside
    the index orders (same eviction policy)."""
    cache = store._index_orders
    key = (epoch.epoch_id, ("col", off))
    hit = cache.get(key)
    if hit is not None:
        return hit
    data = epoch.columns[off]
    valid = epoch.valids[off]
    if valid is None:
        order = np.argsort(data, kind="stable")
        start = 0
    else:
        order = np.lexsort((data, valid))
        start = int(np.searchsorted(valid[order], True, "left"))
    if len(cache) >= 32:
        live = store.epoch.epoch_id
        for k in list(cache):
            if k[0] != live and k != key:
                del cache[k]
    cache[key] = (order, start)
    return order, start


def probe_and_gather(snap: TableSnapshot, ranges,
                     col_offsets: list[int]):
    """Resolve a ScanRanges' point set to visible handles and gather those
    rows' columns — the shared core of the point-get executor and the
    coprocessor's ranged path. Returns (handles, [(data, valid), ...])."""
    searcher = IndexSearcher(snap.store, snap, ranges.index)
    if ranges.interval is not None:
        lo, hi, li, hi_i = ranges.interval
        handles = np.unique(searcher.range(lo, hi, li, hi_i))
    else:
        found = [searcher.eq(p) for p in ranges.points]
        handles = (np.unique(np.concatenate(found)) if found
                   else np.empty(0, dtype=np.int64))
    return handles, snap.gather(handles, col_offsets)


class IndexSearcher:
    """Point/prefix lookups for one index over one snapshot."""

    def __init__(self, store: TableStore, snap: TableSnapshot,
                 index: IndexInfo) -> None:
        self.store = store
        self.snap = snap
        self.index = index
        self._order: Optional[np.ndarray] = None

    def _encode_key(self, values: tuple) -> Optional[list]:
        """Cast host key values into the physical column domain; None if the
        key can never match (absent dictionary string)."""
        out = []
        for v, off in zip(values, self.index.col_offsets):
            ft = self.snap.table.columns[off].ftype
            if ft.is_string:
                d = self.snap.dictionaries[off]
                assert d is not None
                code = d.lookup(v) if isinstance(v, str) else int(v)
                if code < 0:
                    return None
                out.append(code)
            else:
                out.append(v)
        return out

    def eq(self, values: tuple) -> np.ndarray:
        """Handles of visible rows whose index prefix equals `values`.

        Any None in values returns empty (SQL equality with NULL is never
        true). len(values) may be a prefix of the index columns.
        """
        if any(v is None for v in values):
            return np.empty(0, dtype=np.int64)
        key = self._encode_key(values)
        epoch = self.snap.epoch
        base = np.empty(0, dtype=np.int64)
        if key is not None and epoch.num_rows:
            if self._order is None:
                self._order = epoch_index_order(self.store, epoch, self.index)
            order = self._order
            lo, hi = 0, len(order)
            for v, off in zip(key, self.index.col_offsets):
                valid = epoch.valids[off]
                if valid is not None:
                    # valid region is the True-suffix at this level
                    sub_v = valid[order[lo:hi]]
                    lo += int(np.searchsorted(sub_v, True, "left"))
                data = epoch.columns[off]
                sub = data[order[lo:hi]]
                l = lo + int(np.searchsorted(sub, v, "left"))
                r = lo + int(np.searchsorted(sub, v, "right"))
                lo, hi = l, r
                if lo >= hi:
                    break
            if lo < hi:
                pos = order[lo:hi]
                pos = pos[self.snap.base_visible[pos]]
                base = epoch.handles[pos]
        return np.concatenate([base, self._overlay_eq(values)])

    def range(self, lo, hi, lo_incl: bool, hi_incl: bool) -> np.ndarray:
        """Handles of visible rows whose FIRST index column lies in the
        interval (numeric/temporal only — dictionary codes are unordered).
        None bounds are unbounded; NULLs never match (MySQL comparison)."""
        epoch = self.snap.epoch
        off = self.index.col_offsets[0]
        base = np.empty(0, dtype=np.int64)
        if epoch.num_rows:
            if self._order is None:
                self._order = epoch_index_order(self.store, epoch, self.index)
            order = self._order
            lo_pos, hi_pos = 0, len(order)
            valid = epoch.valids[off]
            if valid is not None:
                lo_pos += int(np.searchsorted(valid[order], True, "left"))
            data = epoch.columns[off]
            sub = data[order[lo_pos:hi_pos]]
            l, r = 0, len(sub)
            if lo is not None:
                l = int(np.searchsorted(sub, lo,
                                        "left" if lo_incl else "right"))
            if hi is not None:
                r = int(np.searchsorted(sub, hi,
                                        "right" if hi_incl else "left"))
            if l < r:
                pos = order[lo_pos + l:lo_pos + r]
                pos = pos[self.snap.base_visible[pos]]
                base = epoch.handles[pos]
        snap = self.snap
        m = len(snap.overlay_handles)
        if m == 0:
            return base
        data = snap.overlay_columns[off]
        mask = np.ones(m, dtype=bool)
        ovv = snap.overlay_valids[off]
        if ovv is not None:
            mask &= ovv
        if lo is not None:
            mask &= (data >= lo) if lo_incl else (data > lo)
        if hi is not None:
            mask &= (data <= hi) if hi_incl else (data < hi)
        return np.concatenate([base, snap.overlay_handles[mask]])

    def _overlay_eq(self, values: tuple) -> np.ndarray:
        snap = self.snap
        m = len(snap.overlay_handles)
        if m == 0:
            return np.empty(0, dtype=np.int64)
        key = self._encode_key(values)
        if key is None:
            return np.empty(0, dtype=np.int64)
        mask = np.ones(m, dtype=bool)
        for v, off in zip(key, self.index.col_offsets):
            data = snap.overlay_columns[off]
            valid = snap.overlay_valids[off]
            mask &= data == data.dtype.type(v)
            if valid is not None:
                mask &= valid
        return snap.overlay_handles[mask]
