from .table_store import TableStore, TableSnapshot, ColumnEpoch
from .storage import Storage, Transaction, WriteConflictError

__all__ = [
    "TableStore",
    "TableSnapshot",
    "ColumnEpoch",
    "Storage",
    "Transaction",
    "WriteConflictError",
]
