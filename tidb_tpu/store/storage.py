"""Storage: the single-node transactional store over per-table MVCC stores.

Plays the role of the reference's `kv.Storage` + embedded unistore (reference:
kv/kv.go:462, store/mockstore/unistore.go) for the dev/test topology, and of
the txn coordinator (store/tikv/2pc.go) reduced to its single-node core:
optimistic snapshot-isolation transactions with first-committer-wins
write-conflict detection at commit. The distributed 2PC/percolator protocol
slots in behind the same Transaction surface once multi-node exists.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ..catalog.schema import Catalog, TableInfo
from ..kv.memdb import MemDB, TOMBSTONE
from ..kv.tso import TimestampOracle
from .table_store import TableSnapshot, TableStore


class WriteConflictError(Exception):
    """Another txn committed to a key after our start_ts (optimistic SI)."""


class Storage:
    def __init__(self) -> None:
        from ..stats import StatsHandle

        self.catalog = Catalog()
        self.tso = TimestampOracle()
        self.stats = StatsHandle()
        self.tables: dict[int, TableStore] = {}
        # DDL job queue + history (the meta-KV DDLJobList analog,
        # reference meta/meta.go:571) — lives on storage so a replacement
        # worker resumes pending jobs with their reorg checkpoints
        self.ddl_jobs: list = []
        self.ddl_history: list = []
        self._commit_lock = threading.Lock()
        # active snapshot ts registry -> GC/compaction safepoint
        self._active_snapshots: dict[int, int] = {}
        self._snap_lock = threading.Lock()

    # ---- schema ------------------------------------------------------------
    def register_table(self, info: TableInfo) -> TableStore:
        store = TableStore(info)
        self.tables[info.id] = store
        return store

    def unregister_table(self, table_id: int) -> None:
        self.tables.pop(table_id, None)

    def table_store(self, table_id: int) -> TableStore:
        return self.tables[table_id]

    # ---- snapshot registry (compaction safepoint) ---------------------------
    def acquire_snapshot_ts(self) -> int:
        ts = self.tso.next_ts()
        with self._snap_lock:
            self._active_snapshots[ts] = self._active_snapshots.get(ts, 0) + 1
        return ts

    def release_snapshot_ts(self, ts: int) -> None:
        with self._snap_lock:
            n = self._active_snapshots.get(ts, 0) - 1
            if n <= 0:
                self._active_snapshots.pop(ts, None)
            else:
                self._active_snapshots[ts] = n

    def safe_ts(self) -> int:
        """Newest ts that every active snapshot is at or above."""
        with self._snap_lock:
            if self._active_snapshots:
                return min(self._active_snapshots) - 1
        return self.tso.current()

    # ---- transactions ------------------------------------------------------
    def begin(self) -> "Transaction":
        return Transaction(self, self.acquire_snapshot_ts())

    def commit(self, txn: "Transaction") -> int:
        """Conflict-check + apply. Single commit lock = the degenerate,
        correct form of region-grouped parallel 2PC (2pc.go:616)."""
        mutations = txn.memdb.mutations()
        if not mutations:
            return txn.start_ts
        with self._commit_lock:
            for table_id, token in txn.schema_tokens.items():
                store = self.tables.get(table_id)
                if store is not None and store.schema_token != token:
                    # rows were buffered against an older layout (reference:
                    # schema validator fails the txn, domain/schema_validator.go)
                    raise WriteConflictError(
                        "Information schema is changed during the execution "
                        "of the statement; try again")
            for (table_id, handle), _ in mutations.items():
                store = self.tables.get(table_id)
                if store is None:
                    continue  # table dropped mid-txn; DDL wins
                if store.latest_commit_ts(handle) > txn.start_ts:
                    raise WriteConflictError(
                        f"write conflict on table {table_id} handle {handle}"
                    )
            commit_ts = self.tso.next_ts()
            for (table_id, handle), row in mutations.items():
                store = self.tables.get(table_id)
                if store is not None:
                    store.apply_commit(commit_ts, handle, row)
        # opportunistic compaction at the GC-safe ts
        safe = self.safe_ts()
        for (table_id, _), _ in mutations.items():
            store = self.tables.get(table_id)
            if store is not None:
                store.maybe_compact(min(safe, commit_ts - 1) if safe else 0)
        return commit_ts

    def flush(self) -> None:
        """Fold all committed deltas into base epochs (test/bench helper)."""
        safe = self.safe_ts()
        for store in self.tables.values():
            store.compact(safe)


class Transaction:
    """An optimistic snapshot-isolation transaction."""

    def __init__(self, storage: Storage, start_ts: int) -> None:
        self.storage = storage
        self.start_ts = start_ts
        self.memdb = MemDB()
        self._finished = False
        # table_id -> schema_token observed at first buffered write
        self.schema_tokens: dict[int, int] = {}

    # ---- writes ------------------------------------------------------------
    def set_row(self, table_id: int, handle: int, row: tuple) -> None:
        self._note_schema(table_id)
        self.memdb.set((table_id, handle), row)

    def delete_row(self, table_id: int, handle: int) -> None:
        self._note_schema(table_id)
        self.memdb.set((table_id, handle), TOMBSTONE)

    def _note_schema(self, table_id: int) -> None:
        if table_id not in self.schema_tokens:
            store = self.storage.tables.get(table_id)
            if store is not None:
                self.schema_tokens[table_id] = store.schema_token

    # ---- reads -------------------------------------------------------------
    def snapshot(self, table_id: int) -> TableSnapshot:
        """Snapshot at start_ts unioned with our own uncommitted writes."""
        store = self.storage.table_store(table_id)
        overlay = {h: v for h, v in self.memdb.iter_table(table_id)}
        return store.snapshot(self.start_ts, overlay or None)

    # ---- lifecycle ---------------------------------------------------------
    def commit(self) -> int:
        assert not self._finished, "transaction already finished"
        try:
            return self.storage.commit(self)
        finally:
            self._finish()

    def rollback(self) -> None:
        if not self._finished:
            self._finish()

    def _finish(self) -> None:
        self._finished = True
        self.storage.release_snapshot_ts(self.start_ts)
